"""Pipeline dispatcher: the host loop driving the fused TPU step.

This is the TPU reshape of the reference's inbound-processing service
(``InboundPayloadProcessingLogic.java:135-159`` — Kafka poll → per-record
thread-pool tasks → per-event gRPC) plus the enrichment forwarding
(``OutboundPayloadEnrichmentLogic.java:54-88``) and the fan-out consumers:
instead of processes connected by Kafka topics, ONE host thread cycles

    batcher → jitted pipeline step (device) → routed host egress

where egress covers everything the reference spreads over five services:

- accepted rows  → event store append (event-management persistence)
- enriched cols  → outbound connector workers (outbound-connectors) —
  which also host rule-processor callbacks (rule-processing)
- command rows   → command processor (command-delivery)
- unregistered   → registration manager → replay (device-registration,
  reprocess topic)
- derived alerts + presence state-changes → re-injected into the batcher
- new state      → DeviceStateManager.commit (device-state), sweep-safe

Overlapped host pipeline: the host half of the event path is split into
stages that overlap the device step instead of serializing behind it —
the only work left on the critical dispatch thread is batch assembly +
jitted-step launch:

- DECODE runs on the ingest decode pool (``ingest/sources.py``
  DecodePool → :meth:`PipelineDispatcher.decode_wire_lines`): window
  N+1's ``decode_json_lines`` runs while window N is on device, with
  per-source sequence keys keeping delivery (journal + batch) in
  submission order.
- H2D is double-buffered: plans stage their packed buffers via
  ``device_put`` at emission (``pipeline/packed.py stage_packed_batch``,
  capability-probed with a synchronous CPU/older-JAX fallback), so the
  next plan's transfer overlaps the current step.
- EGRESS (persistence, outbound fan-out, command delivery, replay) runs
  on a supervised offload worker pulling from the bounded in-flight
  window; the dispatch thread stalls only when egress falls a full
  window behind (backpressure).  The at-least-once rule is unchanged:
  the journal offset only advances past plans whose egress COMPLETED —
  a crashed egress leaves its plan outstanding and the commit gate
  fails closed.
- The STEP itself is device-resident at depth (the promoted phase-C
  packed chain): full-width fill plans collect in a K-slot ring of
  pre-staged H2D inputs, and ONE jitted ``lax.fori_loop`` chain steps
  all K with the ``PackedState`` carry threading on device — the host
  dispatches once and, via the ring's shared output fetch, syncs once
  per K steps instead of per step (``pipeline.host_syncs`` counts it).
  Commits stay per batch: each slot windows as its own plan, so a
  mid-ring egress crash leaves exactly the uncommitted steps
  outstanding.  Deadline/flush partials, re-injected plans, mesh and
  CPU-default deployments all take the single-step path (draining
  ring-held predecessors in order first), so the ring only engages
  where it pays: sustained full-width traffic on a host-attached chip.

Output fetches stay selective: batch columns never round-trip (the
batcher keeps its numpy originals in ``BatchPlan``), device→host copies
start asynchronously at dispatch, and the unregistered mask /
derived-alert rows are fetched only when the step's metric counters say
they exist.  Per-stage host time lands in the
``pipeline.stage_{decode,batch,dispatch,egress}_s`` timers — when their
totals exceed wall elapsed, the stages are provably overlapping.
"""

from __future__ import annotations

import collections
import collections.abc
import logging
import os
import threading
import time
from typing import Callable, Dict, List, Optional

import jax
import numpy as np

from sitewhere_tpu.analysis.markers import hot_path
from sitewhere_tpu.ids import NULL_ID
from sitewhere_tpu.ingest.batcher import Batcher, BatchPlan
from sitewhere_tpu.ingest.decoders import DecodedRequest
from sitewhere_tpu.ingest.journal import Journal, JournalReader
from sitewhere_tpu.pipeline.step import pipeline_step
from sitewhere_tpu.runtime import faults
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent
from sitewhere_tpu.runtime.resilience import dead_letter
from sitewhere_tpu.schema import EventBatch, EventType, as_numpy
from sitewhere_tpu.store import segment as _segment_schema

logger = logging.getLogger("sitewhere_tpu.dispatcher")

# egress-view split of the canonical storage schema: the 5 step-output
# enrichment columns, and everything else (minus the store-stamped
# receive time) resolving straight out of plan.host_cols.  Derived, not
# hand-maintained — a copy would silently desync from store COLUMNS.
_EGRESS_ENRICHMENT = ("device_type_id", "assignment_id", "area_id",
                      "customer_id", "asset_id")
_EGRESS_HOST = tuple(
    n for n in _segment_schema.COLUMN_NAMES
    if n not in _EGRESS_ENRICHMENT and n != "received_s"
)


class EgressColumns(collections.abc.Mapping):
    """Zero-copy egress column view over one plan's host columns plus
    the step's enrichment outputs.

    Replaces the per-batch dict build in ``_columns`` (the tagged
    ROADMAP-2 worklist entry: ~4.0 ms of dispatch bookkeeping in
    ``HOSTPATH_r06``, dominated by the 5 EAGER ``np.asarray`` enrichment
    fetches).  Host columns resolve straight out of ``plan.host_cols``;
    enrichment columns (``device_type_id`` … ``asset_id``) fetch from
    the step output LAZILY on first access and memoize, so an egress
    where no consumer touches them — store disabled, outbound-only
    fan-out — never pays the device sync at all, and the common path
    pays it exactly once per column (the segment store's
    ``append_columns`` touches all five, caching them for the async
    outbound/analytics consumers that run afterwards)."""

    ENRICHMENT_COLUMNS = _EGRESS_ENRICHMENT
    _ENRICH_SET = frozenset(_EGRESS_ENRICHMENT)
    HOST_COLUMNS = _EGRESS_HOST
    # O(1) membership: connectors look fields up per row per batch
    _HOST_SET = frozenset(_EGRESS_HOST)

    __slots__ = ("_host", "_out", "_fetched", "_fetch_lock")

    def __init__(self, host_cols: Dict[str, np.ndarray], out):
        self._host = host_cols
        self._out = out
        self._fetched: Optional[Dict[str, np.ndarray]] = None
        # one view is shared across the egress thread AND every async
        # outbound/analytics consumer; the enrichment fetch must be
        # thread-safe (the lock is per batch, taken at most once per
        # consumer — the fast path below is a lock-free memo read)
        self._fetch_lock = threading.Lock()

    def _enrichment(self) -> Dict[str, np.ndarray]:
        fetched = self._fetched
        if fetched is None:
            with self._fetch_lock:
                fetched = self._fetched
                if fetched is None:
                    out = self._out
                    # all five at once (matching the old eager cost the
                    # first time ANY consumer asks), then release the
                    # step output so a view parked in a lagging
                    # outbound queue doesn't pin the step's device
                    # buffers
                    fetched = {
                        n: np.asarray(getattr(out, n))
                        for n in self.ENRICHMENT_COLUMNS
                    }
                    self._fetched = fetched
                    self._out = None
        return fetched

    def release_output(self) -> None:
        """Memoize the enrichment columns and drop the step-output
        reference.  The egress calls this before handing the view to
        async consumers whenever the store path didn't already fetch —
        a view parked in a lagging outbound queue must never pin the
        step's device buffers."""
        self._enrichment()

    def __getitem__(self, name: str) -> np.ndarray:
        if name in self._ENRICH_SET:
            return self._enrichment()[name]
        if name in self._HOST_SET and name in self._host:
            return self._host[name]
        raise KeyError(name)

    def __contains__(self, name) -> bool:
        return (name in self._ENRICH_SET
                or (name in self._HOST_SET and name in self._host))

    def __iter__(self):
        for name in self.HOST_COLUMNS:
            if name in self._host:
                yield name
        yield from self.ENRICHMENT_COLUMNS

    def __len__(self) -> int:
        return (sum(1 for n in self.HOST_COLUMNS if n in self._host)
                + len(self.ENRICHMENT_COLUMNS))


class PipelineDispatcher(LifecycleComponent):
    """Owns the ingest→step→egress loop for one instance.

    Collaborators are duck-typed providers so tenants/tests can compose
    subsets:

    - ``registry_provider()`` / ``zones_provider()`` / ``rules_provider()``
      → current device-resident epochs (RegistryMirror / RuleManager)
    - ``state_manager`` → DeviceStateManager (commit + sweeps)
    - ``event_store`` → accepted-row persistence (append_columns)
    - ``outbound`` → OutboundConnectorsManager (submit cols+mask)
    - ``on_command_rows(cols, idx, trace=None)`` → command-delivery hook
      (``trace`` is the plan's trace so the delivery span joins it)
    - ``registration`` → RegistrationManager (process_unregistered)
    """

    def __init__(
        self,
        batcher: Batcher,
        registry_provider: Callable[[], object],
        state_manager,
        rules_provider: Callable[[], object],
        zones_provider: Callable[[], object],
        event_store=None,
        outbound=None,
        registration=None,
        on_command_rows: Optional[Callable[..., None]] = None,
        analytics=None,
        rules_engine=None,
        journal: Optional[Journal] = None,
        dead_letters: Optional[Journal] = None,
        resolve_tenant: Optional[Callable[[str], int]] = None,
        on_host_request: Optional[Callable[[DecodedRequest, bytes], None]] = None,
        max_replay_depth: int = 4,
        inflight_depth: Optional[int] = None,
        mesh=None,
        journal_reader: Optional[JournalReader] = None,
        recovery_decoder: Optional[Callable[[bytes], List[DecodedRequest]]] = None,
        tracer=None,
        metrics=None,
        egress_offload: Optional[bool] = None,
        overload=None,
        ring_depth: Optional[int] = None,
        flightrec=None,
        slo=None,
        breaker=None,
        watchdog=None,
        quarantine_after: int = 3,
        cost_analysis: Optional[bool] = None,
        usage_ledger=None,
        name: str = "pipeline-dispatcher",
    ):
        super().__init__(name)
        self.batcher = batcher
        self.registry_provider = registry_provider
        self.rules_provider = rules_provider
        self.zones_provider = zones_provider
        self.state_manager = state_manager
        self.event_store = event_store
        self.outbound = outbound
        self.registration = registration
        self.on_command_rows = on_command_rows
        # Streaming analytics (analytics/runner.QueryRunner): egress
        # offers every accepted enriched batch via a NON-blocking
        # bounded queue — live CEP/window queries evaluate on the
        # runner's own worker, never on the egress path's budget.
        self.analytics = analytics
        # Bring-your-own-rules engine (rules/engine.RuleEngineRunner):
        # same egress offer discipline as analytics — non-blocking
        # bounded queue, compiled tenant programs evaluate on the
        # engine's own worker, fired programs re-enter through
        # inject_rule_alerts below.
        self.rules_engine = rules_engine
        self.journal = journal
        self.dead_letters = dead_letters
        self.resolve_tenant = resolve_tenant or (lambda token: 0)
        # host-plane requests (device streams) decoded off the wire path
        self.on_host_request = on_host_request
        # Overload admission gate (runtime/overload.py): the LIVE intake
        # edges (ingest / ingest_many / ingest_wire_decoded) consult it
        # BEFORE journaling; shed rows dead-letter (kind "intake-shed")
        # and a fully-shed payload raises OverloadShed so the receiving
        # transport signals protocol-native backpressure.  Recovery
        # paths (journal replay, derived re-injection, ingest_arrays)
        # deliberately bypass it — already-journaled work is never shed.
        self.overload = overload
        self.max_replay_depth = max_replay_depth
        # No donation of `state`: DeviceStateManager.commit's sweep-merge
        # and concurrent readers still reference the previous epoch.
        self.mesh = mesh
        if mesh is not None:
            # Multi-chip: shard_map step over the mesh (Kafka-partitioning
            # analog, SURVEY.md §2.4) — the batcher already routes each row
            # to the sub-batch of the shard owning its registry block.
            # When the batcher emits packed plans, the packed mesh form
            # runs instead (per-call placement cost on a mesh scales with
            # buffer count × hosts; see build_sharded_packed_step).
            from sitewhere_tpu.pipeline.sharded import (
                build_sharded_packed_step,
                build_sharded_step,
            )

            self._step = build_sharded_step(mesh, donate=False)
            self._packed_step = build_sharded_packed_step(mesh)
        else:
            self._step = jax.jit(pipeline_step)
            # Single-chip fast path: the packed step moves ~11 buffers per
            # call instead of ~110 — per-call dispatch scales with buffer
            # count, which measured ~30 ms/step at width 131k through a
            # network-attached chip (pipeline/packed.py).  Used whenever
            # the batcher emits packed plans.  NO donation: the carry
            # passed in is the state manager's LIVE epoch — donating it
            # would leave concurrent readers (checkpointer, presence
            # sweep, REST queries) holding deleted buffers until
            # commit_packed lands.  Donation is for private carries
            # (bench loops); here XLA just allocates fresh output
            # buffers (~3 MB/step, HBM-trivial).
            from sitewhere_tpu.pipeline.packed import packed_pipeline_step

            self._packed_step = jax.jit(packed_pipeline_step)
        from sitewhere_tpu.pipeline.packed import pack_tables

        self._pack_tables = jax.jit(pack_tables)
        self._tables_cache: Optional[tuple] = None
        # Identity-keyed cache of mesh-placed epochs: providers return the
        # same object while clean, so steady-state steps reuse the resident
        # sharded arrays instead of re-placing every step.
        self._placed_epochs: Dict[str, tuple] = {}
        # Commit-after-egress stream position (Kafka manual-commit analog,
        # MicroserviceKafkaConsumer.java:94): the highest journal offset
        # whose row has completed egress.  Committed only at quiescent
        # points (no pending rows, no in-flight step) so an earlier offset
        # still queued in another shard segment can never be skipped.
        self.journal_reader = journal_reader
        # Decoder for journaled wire payloads on crash recovery — MUST
        # match what the instance's sources journal (JSON by default; a
        # deployment with binary/composite sources passes its own).
        self.recovery_decoder = recovery_decoder
        self._max_egressed_ref = -1
        # Crash-recovery store dedup (runtime/checkpoint.py offset
        # contract): rows whose journal offset is below this floor are
        # durably in the event store already (the commit gate seals
        # BEFORE the offset commits), so a replay that starts below the
        # committed offset — rebuilding volatile component state from an
        # older snapshot — re-runs their state/analytics effects WITHOUT
        # duplicating persistence.  0 = inactive; set by replay_journal.
        self.store_dedup_floor = 0
        # Plans emitted by the batcher whose egress has not completed.
        # Guarded by _lock; the commit gate requires it to be zero so a
        # plan sitting between emission and _run_plan (outside both
        # batcher.pending and _inflight) can never be committed past.
        self._plans_outstanding = 0
        self._lock = threading.Lock()
        # Serializes read-state → step → commit → egress across the loop
        # thread, source threads, and the presence thread: two concurrent
        # steps from the same snapshot would lose the first commit's state
        # merges.  RLock: replay/derived re-injection recurses.
        self._step_lock = threading.RLock()
        # FIFO of (plan, outputs, replay_depth, trace) steps dispatched but
        # not yet egressed; guarded by _step_lock.  Depth >1 keeps several
        # steps in flight so egress (a device→host fetch) overlaps later
        # steps' compute+transfers — on a network-attached chip each fetch
        # costs a full RTT (~70 ms measured through the bench tunnel), and
        # a 1-deep window serializes the whole wire path on it.  The
        # outputs' host copies are started asynchronously at dispatch time
        # (copy_to_host_async), so by the time a plan reaches the egress
        # end of the window its bytes are already host-side.  Latency
        # stays bounded: the loop thread drains the window whenever no new
        # plan is due, so depth only manifests under sustained load —
        # exactly when per-plan latency is throughput-bound anyway.
        if inflight_depth is None or inflight_depth <= 0:
            inflight_depth = 8 if jax.default_backend() == "tpu" else 1
        self.inflight_depth = int(inflight_depth)
        # Device-resident dispatch ring (the promoted phase-C packed
        # chain): full-width packed plans collect in `_ring` until
        # `ring_depth` are staged, then ONE jitted K-step chain
        # (pipeline/packed.py build_packed_chain) steps them all with a
        # single host dispatch and — via the shared RingFetch — a single
        # D2H sync for the whole ring's egress.  None = backend-adaptive
        # (8 on TPU where the ~70 ms host RTT dwarfs the device step, off
        # elsewhere); any value < 2 disables.  On a mesh the SAME ring
        # runs the sharded chain (pipeline/sharded.py
        # build_sharded_packed_chain): one SPMD program steps all K
        # slots across every shard, so the 1/K host-sync economy and the
        # mesh's aggregate throughput compose instead of excluding each
        # other.  Latency stays bounded: deadline/flush/replay plans —
        # and the loop thread, once the ring's oldest plan ages past the
        # batcher deadline — drain the ring through the single-step path
        # IN ORDER, so per-device event order is never reordered around
        # ring-held predecessors and an idle trickle degrades to exactly
        # the pre-ring behavior.
        if ring_depth is None or ring_depth < 0:
            from sitewhere_tpu.pipeline.packed import ring_depth_default

            ring_depth = ring_depth_default()
        self.ring_depth = int(ring_depth) if int(ring_depth) >= 2 else 0
        self._ring: List[BatchPlan] = []
        self._ring_chains: Dict[int, Callable] = {}
        # Ring-shaped dispatch scratch: the K slot references a chain
        # dispatch hands to the jitted call are written into these
        # preallocated lists (and cleared after the dispatch so staged
        # H2D buffers don't outlive their ring) — the steady-state chain
        # path allocates no per-dispatch K-length lists.
        self._ring_slots_i: List = [None] * self.ring_depth
        self._ring_slots_f: List = [None] * self.ring_depth
        # Donate the chain carry only where donation is real (the CPU
        # backend ignores it with a warning per call): the state manager
        # hands the epoch over exclusively via lease_packed, so donation
        # can never delete buffers a concurrent reader still holds.
        self._ring_donate = jax.default_backend() != "cpu"
        if self.ring_depth:
            # the in-flight window must hold at least two rings so chain
            # N+1 dispatches while ring N's egress drains (double
            # buffering at ring granularity)
            self.inflight_depth = max(self.inflight_depth,
                                      2 * self.ring_depth)
        self._inflight: collections.deque = collections.deque()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Egress offload (overlapped host pipeline): between start() and
        # stop() a dedicated worker pulls dispatched steps off _inflight
        # and runs the host fan-out, so the ONLY work left on the
        # dispatch thread is batch assembly + jitted-step launch.  The
        # window doubles as the bounded offload queue: _run_plan stalls
        # (before taking the step lock — never while holding it, so the
        # worker cannot deadlock against a lock-holder) once egress falls
        # `egress_queue_depth` plans behind.  The worker runs under a
        # Supervisor: an egress crash is a worker death mid-window — the
        # failed plan stays outstanding (commit gate fails closed,
        # at-least-once replay recovers it) while the restarted worker
        # keeps draining its siblings.  Without start() (or with
        # egress_offload=False) every path degrades to the inline
        # synchronous egress, the pre-offload behavior.
        #
        # Default is backend-adaptive (same spirit as inflight_depth and
        # packed_step_default): ON off-CPU, where egress blocks on
        # device→host fetches with the GIL released and the overlap is
        # real; OFF on the CPU backend, where the GIL serializes the
        # stages anyway and the offload's backpressure stalls read as
        # idle to the adaptive batcher (measured: 151k→102k ev/s on the
        # CPU wire bench with both on, 189k with inline egress).
        if egress_offload is None:
            egress_offload = jax.default_backend() != "cpu"
        self.egress_offload = bool(egress_offload)
        self.egress_queue_depth = max(2, self.inflight_depth)
        self._egress_super = None
        self._egress_busy = False
        self._egress_stop = threading.Event()
        self._egress_evt = threading.Event()   # work queued
        self._room_evt = threading.Event()     # slot freed
        self.egress_failures = 0
        # Per-plan end-to-end latency samples (oldest-row wait in the
        # batcher + emit→egress-complete), the <10ms p99 target's metric.
        self.latencies_s: collections.deque = collections.deque(maxlen=4096)
        # Span tracing (reference: Jaeger 1% sampling) — no-op when unset.
        if tracer is None:
            from sitewhere_tpu.runtime.tracing import Tracer

            tracer = Tracer(sample_rate=0.0)  # disabled unless configured
        self.tracer = tracer
        # Registry surface (the .prom exposition): instruments are bound
        # ONCE here so the per-plan path pays attribute loads, not dict
        # lookups.  Histogram observations carry the plan's trace id as
        # an exemplar when that trace was retained — the exposition links
        # a latency bucket to a concrete trace an operator can open.
        if metrics is None:
            from sitewhere_tpu.runtime.metrics import MetricsRegistry

            metrics = MetricsRegistry()
        self.metrics = metrics
        self._m_e2e = metrics.histogram("pipeline.e2e_latency_s")
        self._m_assemble = metrics.histogram("pipeline.batch_assemble_s")
        self._m_steps = metrics.counter("pipeline.steps")
        # Per-stage host-time timers (the overlapped-pipeline instrument
        # surface): decode / batch-assembly / step-dispatch / egress each
        # accumulate the HOST time they consume, so `sum(stage totals) >
        # wall elapsed` is the measurable proof the stages overlap.
        self._m_stage = {
            s: metrics.timer(f"pipeline.stage_{s}_s")
            for s in ("decode", "batch", "dispatch", "egress",
                      # ring stages: per-slot wait before its chain
                      # launches, and the chain's host dispatch cost
                      "ring_wait", "ring_dispatch",
                      # unpacked plans' lazy EventBatch H2D (moved off
                      # the intake lock out of _emit — its own stage so
                      # the batch timer's per-plan sample count stays 1)
                      "h2d")
        }
        # "How often does the host touch the device" as a first-class
        # metric: one inc per BLOCKING device→host sync on the dispatch/
        # egress path (the packed views' lazy fetch, the ring's shared
        # fetch, the unpacked fallback's egress fetch).  The ring's whole
        # point is host_syncs/steps → 1/K.
        self._m_host_syncs = metrics.counter("pipeline.host_syncs")
        # Zero-copy ingest evidence: bytes memcpy'd per host stage.  The
        # fill-direct wire path contributes ZERO to decode (the C scan
        # writes once, into the batcher's packed rows) and an adopted
        # full-width reservation contributes zero to batch — measured
        # here, not asserted.  h2d counts the staged transfer bytes.
        self._m_bytes = {
            key: metrics.counter(f"pipeline.bytes_copied.{key}")
            for key in ("decode", "batch", "h2d")
        }
        # Decodes that raced the seconds-long first-use native build and
        # silently took the Python path (native/__init__.py counter,
        # sampled by the loop thread).
        self._m_native_fb = metrics.gauge("native.build_fallbacks")
        # Fill-direct wire decode (zero-copy native ingest).  SW_NATIVE=0
        # still disables the whole native tier; SW_NATIVE_FILL=0 keeps
        # the classic native scanners but turns the fill-direct path off
        # (the bench's A/B knob).
        self._fill_enabled = os.environ.get("SW_NATIVE_FILL", "1") != "0"
        self._m_ring_chains = metrics.counter("pipeline.ring_chains")
        self._m_ring_flushes = metrics.counter("pipeline.ring_flushes")
        self._m_host_copy_err = metrics.counter("pipeline.host_copy_errors")
        self._m_egress_fail = metrics.counter("pipeline.egress_failures")
        self._m_stall_overflow = metrics.counter(
            "pipeline.egress_stall_overflows")
        self._m_queue = metrics.gauge("ingest.queue_depth")
        self._m_inflight = metrics.gauge("pipeline.inflight_steps")
        self._m_seal = metrics.gauge("pipeline.ingest_to_seal_latency_s")
        self._m_totals = {
            key: metrics.counter(f"pipeline.events_{key}")
            for key in ("processed", "accepted", "unregistered",
                        "unassigned", "threshold_alerts", "zone_alerts")
        }
        # Flight recorder (runtime/flightrec.py): one structured record
        # per egressed batch, dumped to JSONL on anomaly — egress-worker
        # crash here, overload transitions and SLO burn alerts via the
        # instance wiring.  None = recording off (tests composing bare
        # dispatchers).
        self.flightrec = flightrec
        # SLO burn-rate engine (runtime/metrics.py BurnRateEngine): the
        # loop thread ticks it alongside the overload controller.
        self.slo = slo
        # On-device occupancy telemetry (pipeline/packed.py
        # TELEMETRY_SCALARS rides the packed metrics vector — zero extra
        # host syncs), surfaced as last-batch gauges.
        self._m_occ = {
            key: metrics.gauge(f"device.occupancy.{key}")
            for key in ("rows_admitted", "rows_invalid", "rules_fired",
                        "state_writes", "presence_merges")
        }
        # Device-tier fault containment (runtime/devguard.py + the
        # _recover_ring/_contain_step_failure paths below).  The metric
        # families are declared closed in analysis/metric_names.py —
        # device.* is a governed prefix.
        self._m_fault = {
            key: metrics.counter(f"device.fault.{key}")
            for key in ("chain_faults", "step_faults", "bisect_rounds",
                        "poison_rows", "releases", "breaker_trips",
                        "watchdog_soft_trips", "watchdog_hard_trips",
                        "host_copy_faults", "cpu_fallback_steps")
        }
        self._m_breaker_state = metrics.gauge("device.fault.breaker_state")
        self._m_quar_devices = metrics.gauge("pipeline.quarantine.devices")
        self._m_quar_rows = metrics.counter(
            "pipeline.quarantine.rows_nonfinite")
        self._m_quar_changes = metrics.counter(
            "pipeline.quarantine.state_changes")
        from sitewhere_tpu.runtime.devguard import (
            DeviceBreaker,
            DeviceWatchdog,
            ShardBreakers,
        )

        # Breaker: repeated device faults across distinct batches demote
        # dispatch chained → single-step → CPU fallback; a cooldown
        # probe restores.  Watchdog: wall-clock budgets over in-flight
        # dispatches; past the hard budget the tier is unhealthy and the
        # flag rides the heartbeat (instance wiring).  Callers may pass
        # pre-configured guards (thresholds/clock); the dispatcher
        # attaches its own handlers to any that were left unset.
        #
        # Mesh dispatch gets a PER-SHARD breaker bank: a fault
        # attributed to one shard's batch segment demotes that shard
        # alone — its rows are masked out of the chain and side-routed
        # (_sidecar_shard_rows) while the healthy shards keep chaining.
        self._mesh_shards = (batcher.n_shards
                             if mesh is not None and batcher.n_shards > 1
                             else 0)
        # batch rows of shard s live at [s*seg, (s+1)*seg) — the
        # batcher's routed segment layout, the attribution key for
        # nonfinite-row → shard fault mapping
        self._shard_seg = (batcher.width // batcher.n_shards
                           if self._mesh_shards else 0)
        if breaker is not None:
            self.breaker = breaker
        elif self._mesh_shards:
            self.breaker = ShardBreakers(self._mesh_shards)
        else:
            self.breaker = DeviceBreaker()
        self._shard_breakers = hasattr(self.breaker, "demoted_shards")
        if self.breaker.on_trip is None:
            self.breaker.on_trip = (self._on_shard_breaker_trip
                                    if self._shard_breakers
                                    else self._on_breaker_trip)
        if self.breaker.on_restore is None:
            self.breaker.on_restore = (self._on_shard_breaker_restore
                                       if self._shard_breakers
                                       else self._on_breaker_restore)
        self.watchdog = (watchdog if watchdog is not None
                         else DeviceWatchdog())
        if self.watchdog.on_soft is None:
            self.watchdog.on_soft = self._on_watchdog_soft
        if self.watchdog.on_unhealthy is None:
            self.watchdog.on_unhealthy = self._on_watchdog_hard
        if self.watchdog.on_recovered is None:
            self.watchdog.on_recovered = self._on_watchdog_recovered
        # Shard-scoped wedge attribution: when the hard budget trips on
        # a mesh, the breaker bank's suspect shards are recorded here
        # and ride the heartbeat (device_unhealthy_shards) so peers can
        # park forwards for the sick shard's device range only.  Cleared
        # when the watchdog recovers.
        self._unhealthy_shards: tuple = ()
        # NaN/Inf quarantine: host policy over the device-counted
        # rows_nonfinite telemetry scalar.  The per-device attribution
        # scan runs ONLY when a plan's scalar is nonzero (the rare
        # path); a device crossing `quarantine_after` cumulative poison
        # rows emits one STATE_CHANGE through normal egress.
        self.quarantine_after = max(1, int(quarantine_after))
        self._nonfinite_seen: Dict[int, int] = {}
        self._quarantined: set = set()
        # D2H copy-fault escalation: _on_host_copy_error flags the
        # suspect; the egress failure that follows re-dispatches the
        # plan single-step instead of surfacing the secondary fetch
        # error as an unexplained egress crash.
        self._copy_suspect = False
        # Watchdog tokens per dispatched plan, keyed by id(plan) —
        # BatchPlan has __slots__, and the token is dispatch-scoped
        # bookkeeping, not plan state.
        self._wd_tokens: Dict[int, int] = {}
        self._cpu_step = None   # lazily-built FALLBACK-level step
        # XLA cost analysis of the compiled chain at warm-up (flops /
        # bytes as device.cost.* gauges — the static roofline half).
        # Backend-adaptive default: the AOT lower+compile costs a second
        # compile, which boot absorbs on TPU but tier-1 CPU runs (where
        # the ring is forced on for smoke coverage) should not pay.
        if cost_analysis is None:
            cost_analysis = jax.default_backend() != "cpu"
        self.cost_analysis = bool(cost_analysis)
        # Tenant metering plane (runtime/metering.py UsageLedger): egress
        # folds each plan's device-side per-tenant scatter block into it
        # (_meter_plan) — the block rides the same fetched metrics
        # vector as TELEMETRY_SCALARS, so attribution costs zero extra
        # host syncs.  None = metering off (bare test dispatchers).
        self.usage_ledger = usage_ledger
        # decode-stage attribution mark: egress is serialized per plan,
        # so the delta of the decode timer's running total between
        # meter calls is the decode time this plan's rows paid for
        self._meter_decode_mark = 0.0
        # host-aggregated counters (metrics endpoint surface)
        self.steps = 0
        self.totals: Dict[str, int] = {
            "processed": 0, "accepted": 0, "unregistered": 0,
            "unassigned": 0, "threshold_alerts": 0, "zone_alerts": 0,
            "replayed": 0, "derived_alerts": 0, "commands": 0,
        }

    def step_barrier(self):
        """The lock serializing read-state → step → commit.  Out-of-band
        state writers (ownership migration imports) hold it so an
        in-flight step computed from the pre-write epoch cannot clobber
        their rows at commit time."""
        return self._step_lock

    # -- ingest entry points (wired as InboundEventSource.on_event) ---------

    def _take(self, intake: Callable[[], object]) -> List[BatchPlan]:
        """Run a batcher intake under the lock, counting every emitted plan
        as outstanding until its egress completes — the commit gate's
        accounting (see ``_maybe_commit_offset``)."""
        t0 = time.perf_counter()
        with self._lock:
            out = intake()
            if out is None:
                plans: List[BatchPlan] = []
            elif isinstance(out, list):
                plans = [p for p in out if p is not None]
            else:
                plans = [out]
            self._plans_outstanding += len(plans)
        if plans:
            self._m_stage["batch"].observe(time.perf_counter() - t0)
        return plans

    def _run_plans(self, plans: List[BatchPlan],
                   replay_depth: int = 0) -> None:
        """Stage every plan's H2D transfer up front, then step them —
        with 2+ plans from one intake the later transfers overlap the
        earlier steps (the double-buffer across a burst)."""
        for plan in plans:
            self._stage_plan(plan)
        for plan in plans:
            self._run_plan(plan, replay_depth)

    def _stage_plan(self, plan: BatchPlan) -> None:
        """Start the async H2D copy of a packed plan (double-buffer front
        half; capability-probed no-op on the CPU backend / older JAX —
        the jitted call then transfers synchronously as before).  Mesh
        plans stage through place_packed_batch: the per-shard device_put
        is asynchronous, so a burst's later placements overlap earlier
        steps exactly like the single-chip staging path."""
        if plan.staged is None and plan.packed_i is not None:
            if self.mesh is not None:
                from sitewhere_tpu.pipeline.sharded import place_packed_batch

                plan.staged = place_packed_batch(
                    self.mesh, plan.packed_i, plan.packed_f)
                self._m_bytes["h2d"].inc(
                    plan.packed_i.nbytes + plan.packed_f.nbytes)
                return
            from sitewhere_tpu.pipeline.packed import stage_packed_batch

            plan.staged = stage_packed_batch(plan.packed_i, plan.packed_f)
            if plan.staged is not None:
                self._m_bytes["h2d"].inc(
                    plan.packed_i.nbytes + plan.packed_f.nbytes)
        elif plan.packed_i is None and plan._batch is None \
                and plan.host_cols:
            # Unpacked plans: materialize the EventBatch HERE, off the
            # intake and step locks — _emit no longer pays the 16 H2D
            # transfers under the intake lock (swlint LK004 fix).
            # Timed as its OWN stage (pipeline.stage_h2d_s): folding it
            # into the batch timer would double that timer's per-plan
            # sample count and halve the per-batch attribution the
            # bench derives from totals/counts.
            t0 = time.perf_counter()
            plan.materialize_batch()
            self._m_stage["h2d"].observe(time.perf_counter() - t0)

    def _shed_intake(self, payload: bytes, shed: Dict[object, int],
                     source_id: str, tenant: str,
                     budget_bound: bool = False) -> None:
        """Audit one intake shed: dead-letter the payload with reason +
        per-class counts so shedding is inspectable AND replayable
        (``requeue_dead_letter`` re-drives it like a failed decode once
        the overload clears).  Sheds the tenant's CONFIGURED budget
        overlay caused carry their own kind ``tenant-budget`` (with the
        budget that clipped them) — distinct from the generic
        ``intake-shed``, so an operator can tell "the fleet was
        overloaded" from "this tenant outran the budget it bought";
        replay re-applies the tenant's CURRENT budget either way."""
        doc = {
            "kind": "tenant-budget" if budget_bound else "intake-shed",
            "state": self.overload.state.name,
            "reason": ("tenant budget exceeded" if budget_bound
                       else self.overload.last_driver or "admission"),
            "classes": {cls.name.lower(): int(n)
                        for cls, n in shed.items()},
            "source": source_id,
            "tenant": tenant,
            "payload": payload.hex(),
        }
        if budget_bound:
            overlay = self.overload.tenant_budgets.overlay(tenant)
            if overlay:
                doc["budget"] = overlay
        dead_letter(self.dead_letters, doc)
        if self.usage_ledger is not None:
            try:
                self.usage_ledger.charge(
                    self.resolve_tenant(tenant), "dead_letter_rows",
                    sum(shed.values()))
            except Exception:
                logger.exception("dead-letter usage charge failed")

    def _admit_requests(self, reqs: List[DecodedRequest], payload: bytes,
                        source_id: str) -> List[DecodedRequest]:
        """Admission-filter a decoded request list.  Returns the admitted
        subset; sheds are dead-lettered once per payload.  Raises
        :class:`OverloadShed` when NOTHING was admitted — the caller's
        transport turns that into native backpressure."""
        from sitewhere_tpu.runtime.overload import classify_event_type

        admitted: List[DecodedRequest] = []
        shed: Dict[object, int] = {}
        worst = None
        budget_bound = False
        for req in reqs:
            cls = classify_event_type(int(req.event_type))
            tenant = (req.metadata.get("tenant", "default")
                      if req.metadata else "default")
            ok, reason = self.overload.admit_detail(
                cls, tenant=tenant, source=source_id)
            if ok:
                admitted.append(req)
            else:
                shed[cls] = shed.get(cls, 0) + 1
                worst = cls
                budget_bound = budget_bound or reason == "budget"
        if shed:
            tenant = (reqs[0].metadata.get("tenant", "default")
                      if reqs[0].metadata else "default")
            self._shed_intake(payload, shed, source_id, tenant,
                              budget_bound=budget_bound)
        if not admitted and shed:
            raise self.overload.shed_exception(worst)
        return admitted

    def ingest(self, req: DecodedRequest, payload: bytes = b"",
               source_id: str = "ingest") -> None:
        """Queue one decoded request (journal it first: at-least-once)."""
        if self.overload is not None and req.event_type is not None:
            req = self._admit_requests([req], payload, source_id)[0]
        ref = NULL_ID
        if self.journal is not None and payload:
            ref = self.journal.append(payload)
        tenant_id = self.resolve_tenant(req.metadata.get("tenant", "default")
                                        if req.metadata else "default")
        self._run_plans(self._take(
            lambda: self.batcher.add(req, tenant_id=tenant_id,
                                     payload_ref=ref)))

    def ingest_many(self, reqs: List[DecodedRequest],
                    payload: bytes = b"",
                    source_id: str = "ingest") -> None:
        """Columnar intake of one wire payload's decoded events (the
        batch-decoder fast path): one resolution pass, no per-row
        dataclass churn, and the payload journals ONCE — every row shares
        the offset, so replay decodes it a single time (at-least-once,
        like the reference's record-level Kafka redelivery)."""
        if not reqs:
            return
        # Validate BEFORE journaling so a host-plane request in the batch
        # can't leave an orphaned journal record behind a raised error.
        for r in reqs:
            if r.event_type is None:
                raise ValueError(
                    f"{r.kind.name} is a host-plane request, not a pipeline event"
                )
        if self.overload is not None:
            # admission before the journal append: shed rows are dead-
            # lettered (replayable), never journaled — a fully shed
            # payload raises so the transport signals backpressure
            reqs = self._admit_requests(reqs, payload, source_id)
            if not reqs:
                return
        ref = NULL_ID
        if self.journal is not None and payload:
            ref = self.journal.append(payload)
        tenants = [
            self.resolve_tenant(r.metadata.get("tenant", "default")
                                if r.metadata else "default")
            for r in reqs
        ]
        self._run_plans(self._take(
            lambda: self.batcher.add_requests(reqs, tenants,
                                              [ref] * len(reqs))))

    def ingest_arrays(self, **columns) -> None:
        """Pre-resolved columnar intake (dense handles, no string work):
        the highest-rate edge, fed by vectorized decoders or re-injection.
        Accepts the :mod:`sitewhere_tpu.ingest.batcher` column set; rows
        without an explicit ``tenant_id`` land in the default tenant (the
        scalar ``ingest`` path's behavior)."""
        if "tenant_id" not in columns:
            n = len(columns["device_id"])
            columns["tenant_id"] = np.full(
                n, self.resolve_tenant("default"), np.int32)
        self._run_plans(self._take(
            lambda: self.batcher.add_arrays(**columns)))

    def ingest_wire_lines(self, payload: bytes, source_id: str = "wire",
                          raise_on_decode_error: bool = False) -> int:
        """Columnar NDJSON wire intake: bytes → column arrays → batcher.

        The true 1M events/sec edge (round-2 verdict weak #2): ONE
        C-level JSON parse for the whole payload, one sweep per field, no
        per-event ``DecodedRequest`` objects, one journal record shared by
        every row.  Host-plane lines (registrations) take the scalar
        path; an undecodable payload dead-letters whole.  Returns the
        number of event rows accepted into the batcher.
        """
        from sitewhere_tpu.ingest.decoders import DecodeError

        try:
            columns, host_reqs = self.decode_wire_lines(payload)
        except DecodeError as e:
            # raise_on_decode_error: a raw_wire source wants the error
            # back so ITS failure counter ticks and ITS on_failed_decode
            # dead-letters (once) — same observable path as the scalar
            # decoder's failures
            if raise_on_decode_error:
                raise
            self.ingest_failed_decode(payload, source_id, e)
            return 0
        return self.ingest_wire_decoded(payload, columns, host_reqs,
                                        source_id=source_id)

    def decode_wire_lines(self, payload: bytes):
        """The pure DECODE stage of :meth:`ingest_wire_lines` — no
        journal append, no state mutation, so a decode-pool worker can
        run it for window N+1 while window N is on device.  Raises
        :class:`DecodeError`; returns ``(columns, host_requests)``.

        Fill-direct fast path: resolved measurement payloads scan
        STRAIGHT into a private batcher reservation (zero intermediate
        copies; the reservation rides the ``columns`` slot through the
        decode pool and commits in delivery order at
        :meth:`ingest_wire_decoded`).  Any shape deviation falls back to
        :func:`decode_json_lines` bit-for-bit, errors included.
        """
        from sitewhere_tpu.ingest.columnar import (
            CopyTally,
            decode_fill_direct,
            decode_json_lines,
            fill_direct_ready,
            space_of,
        )

        with self._m_stage["decode"].time():
            space = space_of(self.batcher.resolve_device)
            if space is not None and self._fill_enabled \
                    and fill_direct_ready(payload, space):
                res = self.batcher.reserve(payload.count(b"\n") + 1)
                if res is not None and decode_fill_direct(
                        payload, space, res,
                        self.batcher.resolve_mtype) is not None:
                    return res, []
            tally = CopyTally()
            out = decode_json_lines(payload, device_space=space,
                                    copied=tally)
            if tally.n:
                self._m_bytes["decode"].inc(tally.n)
            return out

    def _admit_columns(self, columns, payload: bytes, source_id: str):
        """Admission-filter one decoded wire-column dict (vectorized:
        one fancy-index classifies every row, one bucket take per class
        per payload).  Returns ``(admitted_columns, shed_classes)`` —
        columns may be the input unchanged, or None for zero admitted
        rows; dead-letters sheds; raising is the CALLER's decision
        (host-plane lines may still make the payload partially
        useful)."""
        from sitewhere_tpu.ingest.columnar import n_rows
        from sitewhere_tpu.runtime.overload import (
            CLASS_OF_EVENT_TYPE,
            PriorityClass,
        )

        n = n_rows(columns)
        if n == 0:
            return columns, {}
        et = np.asarray(columns["event_type"])
        class_of = np.fromiter(
            (int(c) for c in CLASS_OF_EVENT_TYPE), np.int32,
            len(CLASS_OF_EVENT_TYPE))
        # out-of-range types (STATE_CHANGE, future kinds) classify as
        # COMMAND — same default as classify_event_type; a bare clip
        # would alias them onto the last slot (COMMAND_RESPONSE →
        # CRITICAL) and exempt them from shedding entirely
        in_range = (et >= 0) & (et < len(class_of))
        classes = np.where(
            in_range, class_of[np.clip(et, 0, len(class_of) - 1)],
            np.int32(int(PriorityClass.COMMAND)))
        keep = np.ones(n, bool)
        shed: Dict[object, int] = {}
        budget_bound = False
        for cls in (PriorityClass.TELEMETRY, PriorityClass.COMMAND):
            m = classes == int(cls)
            count = int(m.sum())
            if count:
                ok, reason = self.overload.admit_detail(
                    cls, source=source_id, n=count)
                if not ok:
                    keep &= ~m
                    shed[cls] = count
                    budget_bound = budget_bound or reason == "budget"
        if not shed:
            return columns, shed
        self._shed_intake(payload, shed, source_id, "default",
                          budget_bound=budget_bound)
        if not keep.any():
            return None, shed
        # decoded columns mix ndarrays (event_type, ts, values) and
        # python lists (device_token, mtype, alert_type) — filter every
        # length-n sequence, pass scalars/None through untouched
        rows = np.nonzero(keep)[0]

        def _filter(value):
            if isinstance(value, np.ndarray) and value.ndim >= 1 \
                    and len(value) == n:
                return value[keep]
            if isinstance(value, (list, tuple)) and len(value) == n:
                return [value[i] for i in rows]
            return value

        return ({key: _filter(value) for key, value in columns.items()},
                shed)

    def ingest_wire_decoded(self, payload: bytes, columns,
                            host_reqs, source_id: str = "wire") -> int:
        """The ordered INGEST tail of :meth:`ingest_wire_lines`: journal
        once, route host-plane lines, resolve + batch the event rows.
        Must run in per-source submission order (the decode pool's
        delivery contract) so per-device event order and the journal's
        offset↔row correspondence are preserved."""
        from sitewhere_tpu.ingest.batcher import Reservation

        if isinstance(columns, Reservation):
            return self._ingest_reserved(payload, columns, source_id)
        if self.overload is not None:
            columns, shed = self._admit_columns(columns, payload, source_id)
            if columns is None:
                if host_reqs:
                    columns = {}   # host-plane lines still route below
                else:
                    # the WHOLE payload was shed: native backpressure,
                    # attributed to the most-privileged class refused
                    raise self.overload.shed_exception(
                        min(shed, key=int))
        # Decode validated the payload — journal once (at-least-once).
        ref = NULL_ID
        if self.journal is not None and payload:
            ref = self.journal.append(payload)
            # chaos kill point: journaled, never batched — the record is
            # the durable truth and MUST reappear via replay
            faults.crosspoint("crash.post_journal")
        from sitewhere_tpu.ingest.decoders import RequestKind

        for req in host_reqs:
            if req.kind == RequestKind.REGISTRATION:
                self.ingest_registration(req, b"")
            elif self.on_host_request is not None:
                # device-stream requests (and other host-plane lines)
                # route to the instance handler — this is also how a
                # FORWARDED stream request is handled at its owning host
                self.on_host_request(req, payload)
            elif self.dead_letters is not None:
                # they must never silently mint devices via registration
                dead_letter(self.dead_letters, {
                    "kind": "unsupported-wire-line",
                    "request_kind": req.kind.name,
                    "device_token": req.device_token,
                    "payload_ref": int(ref),
                })
        if not columns:
            return 0   # every event row was shed; host-plane lines routed
        return self._ingest_resolved_columns(columns, ref)

    def _ingest_reserved(self, payload: bytes, res, source_id: str) -> int:
        """Ordered ingest tail of the fill-direct path: admission, ONE
        journal append, the per-payload constants, then commit under the
        intake lock.  Every scanned row is a MEASUREMENT (the resolved
        scanner accepts nothing else), so admission is exactly the
        whole-payload TELEMETRY decision the vector path would make —
        same audit record, same backpressure exception."""
        n = res.n
        if self.overload is not None:
            from sitewhere_tpu.runtime.overload import PriorityClass

            ok, reason = self.overload.admit_detail(
                PriorityClass.TELEMETRY, source=source_id, n=n)
            if not ok:
                res.abort()
                self._shed_intake(payload, {PriorityClass.TELEMETRY: n},
                                  source_id, "default",
                                  budget_bound=reason == "budget")
                raise self.overload.shed_exception(PriorityClass.TELEMETRY)
        ref = NULL_ID
        if self.journal is not None and payload:
            ref = self.journal.append(payload)
            # chaos kill point: same contract as ingest_wire_decoded's
            faults.crosspoint("crash.post_journal")
        res.set_const(tenant_id=self.resolve_tenant("default"),
                      payload_ref=ref)
        self._run_plans(self._take(res.commit))
        return n

    def _ingest_resolved_columns(self, columns, ref: int) -> int:
        """Resolve one decoded column dict and queue its rows (shared by
        live wire intake and columnar journal replay — replay's
        equivalence argument depends on both using THIS code: rows get
        ``ref`` as payload_ref and land in the default tenant)."""
        from sitewhere_tpu.ingest.columnar import n_rows, resolve_columns

        n = n_rows(columns)
        if n == 0:
            return 0
        cols = resolve_columns(
            columns,
            self.batcher.resolve_device,
            self.batcher.resolve_mtype,
            self.batcher.resolve_alert,
            invocations=self.batcher.invocations,
        )
        cols["payload_ref"] = np.full(n, ref, np.int32)
        cols["tenant_id"] = np.full(
            n, self.resolve_tenant("default"), np.int32)
        self._run_plans(self._take(
            lambda: self.batcher.add_arrays(_copy=False, **cols)))
        return n

    def ingest_registration(self, req: DecodedRequest, payload: bytes = b"") -> None:
        if self.registration is not None:
            self.registration.handle_registration(req)

    def ingest_failed_decode(self, payload: bytes, source_id: str, error) -> None:
        if self.dead_letters is not None:
            dead_letter(self.dead_letters,
                        {"kind": "failed-decode", "source": source_id,
                         "error": str(error), "payload": payload.hex()})

    # -- the loop -----------------------------------------------------------

    def start(self) -> None:
        super().start()
        self._stop.clear()
        if self.egress_offload and self._egress_super is None:
            from sitewhere_tpu.runtime.resilience import (
                RetryPolicy,
                Supervisor,
            )

            self._egress_stop.clear()
            self._egress_super = Supervisor(
                f"{self.name}-egress", self._egress_worker,
                policy=RetryPolicy(initial_s=0.01, max_s=1.0),
                max_restarts=8, min_uptime_s=5.0,
                on_restart=self._on_egress_restart,
                metrics=self.metrics)
            self._egress_super.start()
        self._warm_ring()
        self._thread = threading.Thread(
            target=self._loop, name=f"{self.name}-loop", daemon=True
        )
        self._thread.start()

    def _warm_ring(self) -> None:
        """Compile the K-step chain at boot with an all-invalid ring (a
        semantic no-op: zero valid rows touch no state), so the first
        REAL chain doesn't charge a multi-second jit compile to live
        traffic's p99.  Best-effort: a failure only defers the compile
        to the first chain."""
        if not self.ring_depth:
            return
        try:
            from sitewhere_tpu.pipeline.packed import BATCH_F, BATCH_I

            width = self.batcher.width
            bi = np.zeros((len(BATCH_I), width), np.int32)
            bf = np.zeros((len(BATCH_F), width), np.float32)
            chain = self._ring_chain(self.ring_depth)
            tables = self._tables_packed()
            with self._step_lock:
                # block=True: completion is forced BEFORE the commit, so
                # an asynchronously-surfacing execution failure raises
                # here (state manager still holds the pre-chain epoch)
                # instead of poisoning the adopted epoch for every
                # subsequent live dispatch
                self._dispatch_chain(
                    chain, tables, [bi] * self.ring_depth,
                    [bf] * self.ring_depth, block=True)
            if self.cost_analysis:
                # static roofline of the compiled chain: flops/bytes as
                # device.cost.* gauges (AOT lower+compile of the same
                # shapes; best-effort, inside this try on purpose)
                from sitewhere_tpu.pipeline.telemetry import (
                    record_cost_metrics,
                    xla_cost_analysis,
                )

                k = self.ring_depth
                cost = xla_cost_analysis(
                    chain, tables, self.state_manager.current_packed,
                    *([bi] * k), *([bf] * k))
                record_cost_metrics(self.metrics, cost)
        except Exception:
            logger.warning("ring warm-up failed (compile deferred to the "
                           "first chain)", exc_info=True)

    def _dispatch_chain(self, chain, tables, slots_i, slots_f,
                        block: bool = False):
        """ONE chained dispatch with the donation-aware state hand-off
        (shared by the live ring and the boot warm-up so the
        donation-sensitive commit semantics cannot diverge): leased +
        donated carry where donation is real, plain epoch + read_epoch
        commit otherwise.  ``block=True`` forces completion before the
        commit — warm-up only; the live path keeps dispatch async and
        relies on the fail-closed window for execution failures."""
        if self._ring_donate:
            ps, token = self.state_manager.lease_packed()
            if self.mesh is not None:
                # a freshly-materialized lease pack has no mesh layout
                # yet; device_put is a no-op once the planes already
                # carry it (every lease after the first chain)
                from sitewhere_tpu.pipeline.sharded import (
                    place_packed_state,
                )

                ps = place_packed_state(self.mesh, ps)
            out = chain(tables, ps, *slots_i, *slots_f)
            if block:
                jax.block_until_ready(out)
            self.state_manager.commit_packed(
                out[0], present_now=out[3], lease_token=token)
        else:
            epoch = self.state_manager.current_packed
            ps = epoch
            if self.mesh is not None:
                from sitewhere_tpu.pipeline.sharded import (
                    place_packed_state,
                )

                ps = place_packed_state(self.mesh, ps)
            out = chain(tables, ps, *slots_i, *slots_f)
            if block:
                jax.block_until_ready(out)
            self.state_manager.commit_packed(
                out[0], present_now=out[3], read_epoch=epoch)
        return out

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        self.flush()
        if self._egress_super is not None:
            # after flush: the offload queue is drained (or the gate is
            # wedged closed by a dead plan — either way nothing further
            # to hand the worker)
            self._egress_stop.set()
            self._egress_evt.set()
            self._egress_super.stop()
            self._egress_super = None
        super().stop()

    def _loop(self) -> None:
        # poll at half the (possibly adaptive) deadline, floored at 2 ms:
        # an idle instance whose window shrank to the floor must not spin
        # the loop thread at sub-millisecond cadence
        while not self._stop.wait(max(self.batcher.deadline_s / 2, 0.002)):
            try:
                from sitewhere_tpu import native as _native

                self._m_native_fb.set(_native.build_fallbacks)
                if self.overload is not None:
                    # sample the pressure signals + run the overload
                    # state machine (rate-limited inside tick)
                    self.overload.tick()
                if self.slo is not None:
                    # SLO burn-rate sample (rate-limited inside tick)
                    self.slo.tick()
                # Hung-step watchdog: dispatch is async, so this thread
                # stays live even with a wedged chain in flight — the
                # blocking fetch happens at egress, not here.
                self.watchdog.check()
                # Backpressure: with the in-flight window full, a deadline
                # tick would emit a PARTIAL plan behind `depth` queued
                # steps — it gains no latency and fragments the width.
                # Drain one slot instead; pending rows keep coalescing
                # toward full-width plans (the counts>=seg ingest path is
                # unaffected and self-paces the source thread).
                # NEVER block this thread on the step lock: a wedged
                # dispatch holds it for the whole hang, and the watchdog
                # check above is the only thing that can still observe
                # it — a blocking acquire here would cap the loop at ONE
                # check per wedge (exactly when budget trips matter).
                if not self._step_lock.acquire(blocking=False):
                    continue
                try:
                    full = len(self._inflight) >= self.inflight_depth
                finally:
                    self._step_lock.release()
                if full:
                    self._drain_inflight(max_n=1)
                    continue
                plans = self._take(self.batcher.poll)  # deadline emit
                if plans:
                    self._run_plans(plans)
                else:
                    # No new batch: age out a partial ring, then drain
                    # the deferred steps so egress latency stays bounded
                    # when traffic pauses.
                    self._flush_ring_if_due()
                    self._drain_inflight()
                    self._maybe_commit_offset()
            except Exception:
                logger.exception("dispatch cycle failed")

    def flush(self, timeout_s: float = 10.0) -> None:
        """Force pending rows through; on return every row ingested
        BEFORE the call has completed egress (tests/shutdown contract).

        A plan the loop thread has taken but not yet run is in neither
        ``batcher.pending`` nor ``_inflight`` — only the plans-outstanding
        gate sees it — so flush waits for gate quiescence (bounded:
        concurrent sources can keep refilling under sustained traffic).
        """
        self._run_plans(self._take(self.batcher.flush))
        self._flush_ring()
        self._drain_inflight()
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                quiesced = (self._plans_outstanding == 0
                            and self.batcher.pending == 0)
            # _egress_busy outlives the outstanding decrement (the
            # worker's metrics/trace tail runs before its finally clears
            # the flag) — breaking on outstanding alone would let the
            # commit below bail on the busy guard with no retry, skipping
            # the FINAL offset commit on stop()
            if quiesced and not self._egress_busy:
                break
            # re-take: rows ingested since the first take must not rely on
            # the loop thread (stop() joins it BEFORE this flush)
            self._run_plans(self._take(self.batcher.flush))
            self._flush_ring()
            self._drain_inflight()
            time.sleep(0.001)
        self._maybe_commit_offset()

    def _maybe_commit_offset(self) -> None:
        """Durably commit journal progress at a quiescent point.

        Commit order matches the reference (Mongo buffer flush, THEN Kafka
        offset): the event store's in-memory buffer is sealed to disk
        first, so a crash after commit can never have dropped a row the
        offset claims is done.
        """
        reader = self.journal_reader
        if reader is None or self._max_egressed_ref < 0:
            return
        with self._step_lock:
            if self._inflight or self._egress_busy:
                return
            with self._lock:
                if self.batcher.pending > 0 or self._plans_outstanding > 0:
                    return
                upto = self._max_egressed_ref + 1
                if upto > reader.committed:
                    if self.event_store is not None:
                        self.event_store.flush()
                    reader.commit(upto)

    def replay_journal(self, decoder=None, max_records: int = 4096,
                       upto: Optional[int] = None,
                       from_offset: Optional[int] = None) -> int:
        """Re-ingest journal records past the committed offset (crash
        recovery, at-least-once — ``MicroserviceKafkaConsumer.java:116-139``).

        Records were journaled as raw wire payloads; they replay through
        ``decoder`` (default JSON) without re-journaling, keeping their
        original offsets as ``payload_ref``.  Undecodable records
        dead-letter.  ``upto`` (exclusive) bounds the replay — pass the
        journal end captured before live sources start so a racing fresh
        append is never double-ingested.  ``from_offset`` starts the
        replay BELOW the committed offset (the checkpoint restore's
        per-component replay floor): those records re-run state and
        analytics effects but skip event-store persistence (they are
        durably stored already — ``store_dedup_floor``).  Returns
        replayed event rows.
        """
        reader = self.journal_reader
        if reader is None:
            return 0
        from sitewhere_tpu.ingest.decoders import (
            DecodeError,
            JsonLinesDecoder,
        )

        # With the DEFAULT decoder, C-scanner-accepted payloads replay
        # columnar-ly (the strict scanners bail on metadata/extras, so
        # anything they accept is bit-identical under both paths — the
        # scalar decoder keeps handling everything else, including
        # per-request metadata tenants).  A custom recovery decoder
        # disables the fast path outright.
        use_columnar = decoder is None and self.recovery_decoder is None
        decoder = decoder or self.recovery_decoder or JsonLinesDecoder()
        start = reader.committed
        if from_offset is not None:
            start = min(start, max(0, int(from_offset)))
        # rows below the committed offset sealed before that offset
        # committed — replaying them must not duplicate persistence
        self.store_dedup_floor = max(self.store_dedup_floor,
                                     reader.committed)
        reader.seek(start)
        n = 0
        done = False
        while not done:
            records = reader.poll(max_records)
            if not records:
                break
            for offset, payload in records:
                if upto is not None and offset >= upto:
                    done = True
                    break
                if use_columnar:
                    fast = self._replay_columnar(payload, offset)
                    if fast is not None:
                        n += fast
                        continue
                try:
                    reqs = decoder(payload)
                except DecodeError as e:
                    self.ingest_failed_decode(payload, "journal-replay", e)
                    continue
                events = [r for r in reqs if r.event_type is not None]
                if not events:
                    continue
                tenants = [
                    self.resolve_tenant(r.metadata.get("tenant", "default")
                                        if r.metadata else "default")
                    for r in events
                ]
                self._run_plans(self._take(
                    lambda: self.batcher.add_requests(
                        events, tenants, [offset] * len(events))))
                n += len(events)
        if n:
            logger.info("replayed %d journaled events past offset %d",
                        n, reader.committed)
        self.flush()
        with self._lock:
            quiesced = (self._plans_outstanding == 0
                        and self.batcher.pending == 0
                        and not self._egress_busy)
        if quiesced:
            # every replayed sub-committed row has egressed; retire the
            # dedup mask so live egress stops paying for it (a timed-out
            # flush keeps the floor — correctness over the nanoseconds)
            self.store_dedup_floor = 0
        return n

    def _replay_columnar(self, payload: bytes, offset: int) -> Optional[int]:
        """Replay one journal record through the C columnar lane, or
        None when the STRICT measurement scanner doesn't accept it —
        the caller falls back to the scalar decoder.  Only the
        measurement scanner qualifies: it bails on ANY unknown request
        key, so a payload it accepts carries no ``metadata`` and the
        scalar decoder would produce bit-identical rows (default
        tenant, no alternate ids).  The family scanner is deliberately
        NOT used here — it skips unknown request keys, so it would
        accept a metadata-carrying payload and silently drop the
        per-request tenant the scalar replay honors.  Rows keep the
        original ``offset`` as payload_ref and the payload is NOT
        re-journaled."""
        from sitewhere_tpu.ingest.columnar import (
            _native_decode_resolved,
            space_of,
        )
        from sitewhere_tpu.ingest.decoders import DecodeError

        space = space_of(self.batcher.resolve_device)
        if space is None:
            return None
        # The scanner BAILS (None) on anything malformed or non-
        # measurement rather than raising — but its timestamp hardening
        # (_split_epoch) RAISES DecodeError for finite out-of-int32
        # eventDates, and a journal written by pre-hardening code may
        # hold exactly such a record.  Replay must never abort instance
        # boot over one bad record: fall through to the scalar decoder,
        # whose DecodeError handler owns dead-lettering.
        try:
            out = _native_decode_resolved(payload, space)
        except DecodeError:
            return None
        if out is None:
            return None
        columns, _host = out
        return self._ingest_resolved_columns(columns, offset)

    # -- one step -----------------------------------------------------------

    def _mesh_put(self, x, spec):
        """One leaf's mesh placement — a bound method, not a per-call
        closure, so the unpacked re-take path allocates no lambda per
        step (swlint HP004)."""
        from jax.sharding import NamedSharding

        return jax.device_put(x, NamedSharding(self.mesh, spec))

    def _placed(self, kind: str, obj, replicated: bool = False):
        """Place a provider epoch on the mesh, cached by object identity."""
        cached = self._placed_epochs.get(kind)
        if cached is not None and cached[0] is obj:
            return cached[1]
        from sitewhere_tpu.pipeline.sharded import (
            _specs_replicated,
            _specs_sharded,
        )
        from jax.sharding import NamedSharding

        specs = _specs_replicated(obj) if replicated else _specs_sharded(obj)
        placed = jax.tree_util.tree_map(
            lambda x, s: jax.device_put(x, NamedSharding(self.mesh, s)),
            obj, specs,
        )
        self._placed_epochs[kind] = (obj, placed)
        return placed

    def _tables_packed(self):
        """PackedTables for the current provider epochs, identity-cached
        (re-packs only when a registry/rule/zone epoch actually changed).
        On a mesh the pack is placed with its canonical shardings
        (registry plane sharded by capacity, broadcast tables
        replicated) so steady-state steps reuse the resident buffers."""
        reg = self.registry_provider()
        rules = self.rules_provider()
        zones = self.zones_provider()
        c = self._tables_cache
        if c is not None and c[0] is reg and c[1] is rules and c[2] is zones:
            return c[3]
        t = self._pack_tables(reg, rules, zones)
        if self.mesh is not None:
            from sitewhere_tpu.pipeline.sharded import place_packed_tables

            t = place_packed_tables(self.mesh, t)
        self._tables_cache = (reg, rules, zones, t)
        return t

    def _run_plan(self, plan: BatchPlan, replay_depth: int = 0) -> None:
        """Route one emitted plan: full-width fill plans join the
        device-resident dispatch ring (chained K at a time); everything
        else — deadline/flush partials, re-injected plans, unpacked or
        mesh plans — takes the single-step path, draining any ring-held
        predecessors first so per-device event order is preserved."""
        if self._ring_eligible(plan, replay_depth):
            self._stage_plan(plan)
            with self._step_lock:
                self._ring.append(plan)
                due = len(self._ring) >= self.ring_depth
            if due:
                self._stall_for_egress_room()
                with self._step_lock:
                    if len(self._ring) >= self.ring_depth:
                        self._run_ring()
            return
        if self.ring_depth and self._ring:
            # ordering barrier: rows already queued in the ring precede
            # this plan — step them first (stall only outside the egress
            # worker's own context, same rule as the single-step path).
            # Bounded by this plan's emission seq: concurrently appended
            # NEWER fill plans are successors, and draining them here
            # would both reorder them ahead of this plan and starve it
            # indefinitely under a sustained full-width stream.
            self._flush_ring(stall=replay_depth == 0,
                             upto_seq=plan.seq if plan.seq >= 0 else None)
        self._dispatch_plan(plan, replay_depth)

    def _ring_eligible(self, plan: BatchPlan, replay_depth: int) -> bool:
        """May this plan wait in the ring for a chained dispatch?  Only
        depth-0 full-width fill emissions on the packed path:
        deadline/flush partials are latency-sensitive and re-injected
        plans (derived alerts, replay) must not recurse through the
        ring.  Mesh plans chain through the sharded packed chain — the
        fused mode — under the same eligibility rules.  The explicit
        width check matters with n_shards > 1, where a single skewed
        shard segment triggers a "fill" emission far below full width —
        those are latency-carrying partials too."""
        return (self.ring_depth > 0
                and replay_depth == 0
                and plan.packed_i is not None
                and plan.reason == "fill"
                and plan.n_events == plan.width
                # breaker demoted past CHAINED: bisectable single-step
                # dispatch only, until a cooldown probe succeeds
                and self.breaker.allow_chain())

    def _stall_for_egress_room(self) -> None:
        """Bounded offload queue: stall — never while holding the step
        lock — once egress has fallen a full window behind."""
        if not self._offloaded():
            return
        deadline = time.monotonic() + 10.0
        while (len(self._inflight) >= self.egress_queue_depth
               and self._offloaded()
               and time.monotonic() < deadline):
            self._room_evt.clear()
            # re-check AFTER the clear: a slot freed between the
            # check above and the clear must not be lost to a full
            # poll interval
            if len(self._inflight) < self.egress_queue_depth:
                break
            self._room_evt.wait(0.05)
        else:
            if (self._offloaded()
                    and len(self._inflight) >= self.egress_queue_depth):
                # gave up on the stall bound: the window overfills
                # rather than deadlocking the producer, but an
                # operator must be able to see it happening
                self._m_stall_overflow.inc()
                logger.warning(
                    "egress stalled > 10s with %d plans in flight "
                    "(bound %d) — proceeding past the window bound",
                    len(self._inflight), self.egress_queue_depth)

    def _flush_ring(self, stall: bool = True,
                    upto_seq: Optional[int] = None) -> None:
        """Drain ring-held plans through the single-step path in emission
        order: the partial-ring deadline/flush path, and the ordering
        barrier ahead of a non-ring plan.  ``stall=False`` when called
        from the egress worker's own context (it must never block on its
        own backlog); ``upto_seq`` bounds the drain to plans emitted
        BEFORE that sequence number (the barrier's predecessors — newer
        arrivals stay ringed for their own chain).

        Each pop+dispatch happens under ONE step-lock hold: the ring is
        the ordered dispatch queue, so a concurrently refilled ring can
        never chain newer plans ahead of an older plan this drain has
        taken but not yet stepped (the stall, which must never run under
        the lock, sits between holds)."""
        while True:
            if stall:
                self._stall_for_egress_room()
            with self._step_lock:
                if not self._ring:
                    return
                if upto_seq is not None and self._ring[0].seq >= upto_seq:
                    return
                plan = self._ring.pop(0)
                self._m_ring_flushes.inc()
                self._dispatch_plan(plan, 0, stall=False)

    def _flush_ring_if_due(self) -> None:
        """Loop-thread linger bound: a partial ring whose oldest plan has
        aged past the batcher deadline drains single-step, so the ring
        adds at most ~one deadline of latency under trickle traffic."""
        if not self.ring_depth:
            return
        with self._step_lock:
            due = bool(self._ring) and (
                time.monotonic() - self._ring[0].created_at
                >= self.batcher.deadline_s)
        if due:
            self._flush_ring()

    def _ring_chain(self, k: int):
        """The jitted K-step chain, built once per K (K is always
        ``ring_depth`` in steady state; the cache tolerates a mid-chaos
        variation without recompiling every dispatch)."""
        chain = self._ring_chains.get(k)
        if chain is None:
            if self.mesh is not None:
                from sitewhere_tpu.pipeline.sharded import (
                    build_sharded_packed_chain,
                )

                chain = build_sharded_packed_chain(
                    self.mesh, k, donate=self._ring_donate)
            else:
                from sitewhere_tpu.pipeline.packed import build_packed_chain

                chain = build_packed_chain(k, donate=self._ring_donate)
            self._ring_chains[k] = chain
        return chain

    @hot_path
    def _run_ring(self) -> None:
        """Dispatch one chained K-step program over the ring's staged
        slots (called under ``_step_lock`` with a full ring): one host
        dispatch covers K steps, the carry threads on device (donated —
        the state manager leased it exclusively), per-step output blocks
        come back stacked, and their D2H copies start immediately so the
        egress worker's ONE shared fetch per ring finds the bytes
        host-side.  Each slot then windows as its own plan: commits stay
        fail-closed per batch, attributed to the step that produced them."""
        # chaos hook: a chain-dispatch failure leaves every plan in the
        # ring — all stay outstanding, the commit gate fails closed, and
        # journal replay recovers their rows (at-least-once)
        faults.fire("dispatcher.step")
        from sitewhere_tpu.pipeline.packed import (
            RingFetch,
            RingStepView,
            start_host_copy,
        )

        plans = self._ring[:self.ring_depth]
        del self._ring[:self.ring_depth]
        k = len(plans)
        chain = self._ring_chain(k)
        now = time.monotonic()
        # per-shard containment (mesh): shards the breaker bank has
        # demoted get their rows side-routed + masked BEFORE the chain,
        # so one sick chip degrades its own shard without costing the
        # healthy shards the 1/K host-sync economy
        demoted = (self.breaker.demoted_shards()
                   if self._shard_breakers else ())
        if demoted:
            self._sidecar_shard_rows(plans, demoted)
        # ring-shaped scratch: slot references land in the preallocated
        # K-length lists (cleared after the dispatch), so the chain path
        # builds no per-dispatch lists (swlint HP001)
        slots_i, slots_f = self._ring_slots_i, self._ring_slots_f
        while len(slots_i) < k:   # mid-chaos partial chain (cold path)
            slots_i.append(None)
            slots_f.append(None)
        while len(slots_i) > k:
            slots_i.pop()
            slots_f.pop()
        for i, plan in enumerate(plans):
            self._m_stage["ring_wait"].observe(
                max(0.0, now - plan.created_at))
            staged = plan.staged or (plan.packed_i, plan.packed_f)
            slots_i[i] = staged[0]
            slots_f[i] = staged[1]
        t0 = time.perf_counter()
        tables = self._tables_packed()
        # one watchdog entry for the whole chain; each slot's egress
        # decrements a part, so the entry drains when the LAST slot does
        # (`plans` rides as the opaque payload — the trip callback
        # renders records lazily, off the per-batch hot path)
        wd = self.watchdog.begin(plans, parts=k)
        for plan in plans:
            self._wd_tokens[id(plan)] = wd
        ctrace = self.tracer.trace("pipeline.chain")
        try:
            if faults.device_active():
                # device-fault injection point: fires against the HOST
                # copies of the packed batch (plan.packed_i/f, always
                # retained), so when_nonfinite matches exactly what the
                # device would compute over
                for plan in plans:
                    faults.device_fire("device.dispatch",
                                       values=plan.packed_f,
                                       valid=plan.packed_i[0] != 0)
            with ctrace.span("ring.dispatch").tag("steps", k):
                _, ois, mets, _present = self._dispatch_chain(
                    chain, tables, slots_i, slots_f)
            start_host_copy(ois, mets, on_error=self._on_host_copy_error)
        except Exception as e:
            ctrace.end()
            self._recover_ring(plans, e)
            return
        finally:
            # drop the slot references: staged H2D buffers must not
            # outlive their ring pinned in the dispatch scratch
            for i in range(k):
                slots_i[i] = None
                slots_f[i] = None
        ctrace.end()
        # chaos kill point: the K-step chain dispatched and committed on
        # device, but NO slot has egressed — every ring plan must replay
        faults.crosspoint("crash.mid_ring")
        chain_dt = time.perf_counter() - t0
        self._m_stage["ring_dispatch"].observe(chain_dt)
        self._m_ring_chains.inc()
        for plan in plans:
            plan.dispatch_s = chain_dt / k   # per-slot share of the chain
        fetch = RingFetch(ois, mets, on_fetch=self._m_host_syncs.inc)
        for slot, plan in enumerate(plans):
            trace = self.tracer.trace("pipeline.plan")
            trace.record("batch.assemble", plan.max_wait_s,
                         rows=plan.n_events, fill=round(plan.fill, 3))
            trace.record("ring.slot", max(0.0, now - plan.created_at),
                         slot=slot, seq=plan.seq, chain_k=k)
            self._m_assemble.observe(plan.max_wait_s)
            self._window_step(plan, RingStepView(fetch, slot), 0, trace)
        # a clean CHAINED dispatch closes a half-open breaker probe —
        # per-shard, it vouches only for the shards that actually rode
        # the chain (a masked shard proved nothing)
        if demoted:
            self.breaker.record_success(chained=True, masked=demoted)
        else:
            self.breaker.record_success(chained=True)

    def _recover_ring(self, plans, exc) -> None:
        """Chain-failure containment (runs under ``_step_lock``).

        The K plans were popped off the ring BEFORE the dispatch, so a
        raw failure would leave them invisible to every accounting
        surface that reads ``self._ring`` — ``oldest_unsealed_wait_s``
        (the overload ladder's queue-delay signal) and the partial-ring
        deadline drain both go blind.  Re-parking them at the FRONT
        restores that accounting (and emission order) first.

        The donated carry is not stranded either: the chain faulted, so
        ``commit_packed`` never ran and the state manager still holds
        the last committed epoch — each single-step re-dispatch below
        re-leases a fresh pack of it (``lease_generation`` advances on
        the same live manager: recovery without restart).  Recovery must
        NEVER touch the donated ``ps`` argument itself — swlint's DN001
        donation pass guards that statically.

        A re-dispatch that fails again is contained by
        :meth:`_contain_step_failure` (bisect → poison-row quarantine),
        and repeated faults across distinct batches trip the breaker.
        """
        self._ring[:0] = plans
        self._m_fault["chain_faults"].inc()
        if self._ring_donate:
            # the failed chain held the packed lease; the re-dispatches
            # below re-lease the carry from the last committed epoch
            self._m_fault["releases"].inc()
        for plan in plans:
            self._wd_end(plan)
        logger.warning(
            "chained dispatch failed (%d plans re-parked): %s",
            len(plans), exc)
        if self.flightrec is not None:
            for plan in plans:
                self._flight_record(
                    plan, None, 0, commit="device-fault",
                    error=f"{type(exc).__name__}: {exc}")
            self.flightrec.anomaly(
                "device-fault",
                detail=f"chain of {len(plans)} failed: "
                       f"{type(exc).__name__}: {exc}")
        # per-shard attribution on a mesh: nonfinite rows in a shard's
        # batch segment strike THAT shard's breaker; an unattributable
        # chain fault strikes every shard (fail conservative)
        self._record_device_fault(plans[0].seq, plans)
        # single-step re-dispatch in emission order; a plan that fails
        # AGAIN stays re-parked (front of the ring), keeps the commit
        # gate closed, and journal replay recovers it after restart
        for _ in range(len(plans)):
            plan = self._ring.pop(0)
            try:
                self._dispatch_plan(plan, 0, stall=False)
            except Exception:
                self._ring.insert(0, plan)
                logger.exception(
                    "single-step re-dispatch of seq=%d failed; "
                    "plan stays parked", plan.seq)
                break

    def _on_host_copy_error(self, exc) -> None:
        """A D2H output copy failed.  The dispatch itself committed, so
        the rows are NOT lost — but the egress fetch that follows will
        hit the same dead buffer.  Escalate beyond the counter: flag the
        plan's egress failure for a single-step re-dispatch (the state
        re-step is at-least-once, identical to journal replay) and dump
        the anomaly so the copy fault is attributable, not a mystery
        egress crash minutes later."""
        self._m_host_copy_err.inc()
        self._m_fault["host_copy_faults"].inc()
        self._copy_suspect = True
        logger.warning("device→host output copy failed: %s", exc)
        if self.flightrec is not None:
            self.flightrec.anomaly(
                "host-copy-fault",
                detail=f"{type(exc).__name__}: {exc}")

    # --- device-tier fault-containment callbacks (devguard wiring) ---

    def _on_breaker_trip(self, level: int) -> None:
        from sitewhere_tpu.runtime.devguard import BREAKER_LEVELS
        from sitewhere_tpu.runtime.overload import OverloadState

        self._m_fault["breaker_trips"].inc()
        self._m_breaker_state.set(level)
        logger.warning("device breaker tripped to %s",
                       BREAKER_LEVELS[level])
        if self.flightrec is not None:
            self.flightrec.anomaly(
                "device-breaker",
                detail=f"dispatch demoted to {BREAKER_LEVELS[level]}")
        if (self.overload is not None
                and self.overload.state == OverloadState.NORMAL):
            # ride the overload ladder: a demoted device tier sheds the
            # same way genuine pressure does, and the ladder's own
            # hysteresis owns any further escalation
            self.overload.force(OverloadState.DEGRADED,
                                reason="device-breaker")

    def _on_breaker_restore(self) -> None:
        from sitewhere_tpu.runtime.overload import OverloadState

        self._m_breaker_state.set(0)
        logger.info("device breaker restored chained dispatch")
        if (self.overload is not None
                and self.overload.state == OverloadState.DEGRADED
                and getattr(self.overload, "last_driver", None)
                == "device-breaker"):
            # release only our own demotion — a ladder driven by real
            # pressure meanwhile keeps its state
            self.overload.force(OverloadState.NORMAL,
                                reason="device-breaker-recovered")

    def _on_shard_breaker_trip(self, shard: int, level: int) -> None:
        """One mesh shard demoted (ShardBreakers callback): the gauge
        tracks the WORST shard, the flight recorder names the sick one,
        and the overload ladder only engages once NO shard can chain —
        a single demoted shard still rides masked on a healthy mesh."""
        from sitewhere_tpu.runtime.devguard import BREAKER_LEVELS
        from sitewhere_tpu.runtime.overload import OverloadState

        self._m_fault["breaker_trips"].inc()
        self._m_breaker_state.set(self.breaker.level)
        logger.warning("device breaker tripped to %s for mesh shard %d "
                       "(other shards keep chaining)",
                       BREAKER_LEVELS[level], shard)
        if self.flightrec is not None:
            self.flightrec.anomaly(
                "device-breaker",
                detail=f"shard {shard} demoted to {BREAKER_LEVELS[level]}")
        if (self.overload is not None
                and not self.breaker.allow_chain()
                and self.overload.state == OverloadState.NORMAL):
            self.overload.force(OverloadState.DEGRADED,
                                reason="device-breaker")

    def _on_shard_breaker_restore(self, shard: int) -> None:
        from sitewhere_tpu.runtime.overload import OverloadState

        self._m_breaker_state.set(self.breaker.level)
        logger.info("device breaker restored chained dispatch for "
                    "mesh shard %d", shard)
        if (self.breaker.level == 0
                and self.overload is not None
                and self.overload.state == OverloadState.DEGRADED
                and getattr(self.overload, "last_driver", None)
                == "device-breaker"):
            self.overload.force(OverloadState.NORMAL,
                                reason="device-breaker-recovered")

    def _fault_shards(self, plans) -> Optional[set]:
        """Attribute a mesh dispatch fault to shard(s): scan the retained
        HOST batch buffers for nonfinite float rows (the dominant device
        fault the injection harness and real poison produce) and map
        each poisoned row's batch position to its shard segment.  None =
        unattributable — the caller strikes every shard, because an
        un-guarded tier is worse than a conservatively demoted one."""
        if not self._mesh_shards:
            return None
        shards: set = set()
        for plan in plans:
            if plan.packed_i is None:
                continue
            bf = np.asarray(plan.packed_f)
            valid = np.asarray(plan.packed_i[0]) != 0
            bad = valid & ~np.isfinite(bf).all(axis=0)
            for row in np.nonzero(bad)[0]:
                shards.add(int(row) // self._shard_seg)
        return shards or None

    def _record_device_fault(self, seq: int, plans) -> None:
        """Route one device fault into the breaker — per-shard when the
        bank is shard-aware AND the fault attributes to specific
        segments, tier-wide otherwise."""
        if not self._shard_breakers:
            self.breaker.record_fault(seq)
            return
        shards = self._fault_shards(plans)
        if shards is None:
            self.breaker.record_fault(seq)
        else:
            for s in sorted(shards):
                self.breaker.record_fault(seq, shard=s)

    def _sidecar_shard_rows(self, plans, demoted: tuple) -> None:
        """Demoted-shard side route (mesh ring, under ``_step_lock``):
        dispatch each ring plan's rows belonging to ``demoted`` shards
        through the containment subset path — the sharded single step
        while the shard sits at SINGLE_STEP, the CPU fallback once it
        reaches FALLBACK — then mask those rows out of the staged chain
        batch.  The healthy shards keep the fused chain; the sick
        shard's rows still flow (degraded), commit via the same
        read-epoch merge, and window/egress normally.  A side dispatch
        that FAILS leaves its rows in the chain on purpose: the chain
        fault that follows re-enters `_recover_ring`'s containment
        instead of silently dropping rows."""
        from sitewhere_tpu.runtime.devguard import FALLBACK

        fallback = any(self.breaker.level_of(s) >= FALLBACK
                       for s in demoted)
        step_fn = self._cpu_packed_step() if fallback else None
        if step_fn is None:
            # no addressable CPU device: demoted single-step through the
            # mesh beats a dead fallback (same policy as _dispatch_plan)
            fallback = False
        seg = self._shard_seg
        for plan in plans:
            if plan.packed_i is None:
                continue
            valid = np.asarray(plan.packed_i[0]) != 0
            take = np.zeros(valid.shape[0], dtype=bool)
            for s in demoted:
                take[s * seg:(s + 1) * seg] = True
            rows = np.nonzero(take & valid)[0]
            if rows.size == 0:
                continue
            trace = self.tracer.trace("pipeline.shard-sidecar")
            trace.record("shard.sidecar", 0.0, seq=plan.seq,
                         rows=int(rows.size), shards=list(demoted))
            if not self._try_subset(plan, rows, 0, trace,
                                    step_fn=step_fn):
                logger.warning(
                    "sidecar dispatch for demoted shard(s) %s failed "
                    "(seq=%d); rows stay in the chain for containment",
                    demoted, plan.seq)
                continue
            if fallback:
                self._m_fault["cpu_fallback_steps"].inc()
            # mask the side-routed rows out of the chained dispatch:
            # fresh host buffer (the retained original must keep its
            # rows for bisect/dead-letter), restaged on the mesh
            bi = np.array(plan.packed_i, copy=True)
            bi[0][rows] = 0
            plan.packed_i = bi
            from sitewhere_tpu.pipeline.sharded import place_packed_batch

            plan.staged = place_packed_batch(self.mesh, bi, plan.packed_f)

    def _on_watchdog_soft(self, payload, elapsed_s: float) -> None:
        """Soft budget tripped: dump the in-flight dispatch's plan
        records to the flight recorder.  ``payload`` is the opaque
        value handed to ``watchdog.begin`` — a BatchPlan (single-step)
        or the ring's plan list (chained); records render HERE, on the
        cold trip path, never per batch."""
        plans = payload if isinstance(payload, list) else [payload]
        self._m_fault["watchdog_soft_trips"].inc()
        logger.warning("device dispatch slow: %.3fs in flight (budget "
                       "%.3fs), %d plan(s)", elapsed_s,
                       self.watchdog.soft_s, len(plans))
        if self.flightrec is not None:
            for i, plan in enumerate(plans):
                self.flightrec.record(
                    kind="hung-step",
                    **self._wd_record(plan,
                                      slot=i if len(plans) > 1 else None))
            self.flightrec.anomaly(
                "device-hung-step",
                detail=f"{elapsed_s:.3f}s in flight "
                       f"(soft budget {self.watchdog.soft_s:.3f}s)")

    def _on_watchdog_hard(self, payload, elapsed_s: float) -> None:
        self._m_fault["watchdog_hard_trips"].inc()
        # shard-scoped wedge attribution (mesh): the breaker bank's
        # suspects — shards with live strikes or an elevated level — are
        # the best available culprit for the wedge; () means the whole
        # tier is suspect and peers park everything, same as single-chip
        if self._shard_breakers:
            self._unhealthy_shards = self.breaker.suspect_shards()
        logger.error("device tier unhealthy: dispatch wedged %.3fs "
                     "(hard budget %.3fs)%s", elapsed_s,
                     self.watchdog.hard_s,
                     (f", suspect shards {self._unhealthy_shards}"
                      if self._unhealthy_shards else ""))
        if self.flightrec is not None:
            self.flightrec.anomaly(
                "device-wedged",
                detail=f"{elapsed_s:.3f}s in flight "
                       f"(hard budget {self.watchdog.hard_s:.3f}s)")

    def _on_watchdog_recovered(self) -> None:
        self._unhealthy_shards = ()
        logger.info("device tier recovered: in-flight dispatches drained")

    @property
    def device_unhealthy(self) -> bool:
        """Heartbeat export: True while the hung-step watchdog holds the
        tier unhealthy (rpc/forward.py carries it to peers)."""
        return self.watchdog.unhealthy

    @property
    def device_unhealthy_shards(self) -> tuple:
        """Heartbeat export, mesh refinement of :attr:`device_unhealthy`:
        the shard ids suspected in the CURRENT wedge.  Empty while
        healthy — and also when a wedge cannot be attributed, in which
        case peers treat the whole tier as sick (the conservative
        single-chip semantics)."""
        if not self.watchdog.unhealthy:
            return ()
        return self._unhealthy_shards

    def _wd_record(self, plan: BatchPlan, slot: Optional[int] = None) -> dict:
        rec = {"seq": int(plan.seq), "rows": int(plan.n_events),
               "reason": plan.reason}
        if slot is not None:
            rec["slot"] = slot
        return rec

    def _wd_end(self, plan: BatchPlan) -> None:
        self.watchdog.end(self._wd_tokens.pop(id(plan), None))

    def _on_egress_restart(self, exc) -> None:
        """Supervisor restart of the egress worker — a flight-recorder
        anomaly in its own right.  SAME reason as the worker's own
        crash dump on purpose: the rate limit is per reason, so the
        restart milliseconds after the crash coalesces into one
        snapshot instead of burning the retention budget twice."""
        if self.flightrec is not None:
            self.flightrec.anomaly(
                "egress-crash", detail=f"supervisor restart: {exc}")

    @hot_path
    def _flight_record(self, plan: BatchPlan, out, replay_depth: int,
                       commit: str, e2e_s: float = 0.0,
                       egress_s: float = 0.0, trace=None,
                       error: Optional[str] = None) -> None:
        """Append one structured per-batch record to the flight
        recorder: sequence, ring slot, per-host-stage timings, overload
        state, trace id, commit outcome — the black-box row an anomaly
        snapshot serializes.  Pure host dict work, no device access."""
        rec = {
            "seq": int(plan.seq),
            "reason": plan.reason,
            "rows": int(plan.n_events),
            "fill": round(plan.fill, 4),
            "slot": getattr(out, "slot", None),
            "replay_depth": int(replay_depth),
            "wait_ms": round(plan.max_wait_s * 1e3, 3),
            "dispatch_ms": round(plan.dispatch_s * 1e3, 3),
            "egress_ms": round(egress_s * 1e3, 3),
            "e2e_ms": round(e2e_s * 1e3, 3),
            "overload": (self.overload.state.name
                         if self.overload is not None else "NORMAL"),
            "trace_id": getattr(trace, "trace_id", None),
            "commit": commit,
        }
        if error is not None:
            rec["error"] = error
        self.flightrec.record(**rec)

    @hot_path
    def _dispatch_plan(self, plan: BatchPlan, replay_depth: int = 0,
                       stall: bool = True) -> None:
        # chaos hook: a step-dispatch failure (device OOM, donation bug)
        # — the plan stays outstanding, so the commit gate fails closed
        faults.fire("dispatcher.step")
        if stall and replay_depth == 0:
            # Re-injected plans (depth > 0, which includes everything the
            # egress worker itself submits) skip the wait so the worker
            # can never block on its own backlog.
            self._stall_for_egress_room()
        self._stage_plan(plan)
        trace = self.tracer.trace("pipeline.plan")
        # the batcher wait of the oldest row = the "batch assemble" stage
        trace.record("batch.assemble", plan.max_wait_s,
                     rows=plan.n_events, fill=round(plan.fill, 3))
        self._m_assemble.observe(plan.max_wait_s)
        t_dispatch = time.perf_counter()
        with self._step_lock:
            if plan.packed_i is not None:
                from sitewhere_tpu.pipeline.packed import (
                    PackedView,
                    start_host_copy,
                )

                tables = self._tables_packed()
                epoch = self.state_manager.current_packed
                ps = epoch
                # staged pair (H2D already in flight) when the probe
                # allowed it; the raw numpy buffers otherwise (the jitted
                # call then transfers synchronously — CPU/older-JAX path)
                bi, bf = plan.staged or (plan.packed_i, plan.packed_f)
                if self.mesh is not None:
                    from sitewhere_tpu.pipeline.sharded import (
                        place_packed_batch,
                        place_packed_state,
                    )

                    if plan.staged is None:
                        bi, bf = place_packed_batch(self.mesh, bi, bf)
                    ps = place_packed_state(self.mesh, ps)
                # breaker at FALLBACK: the chip is presumed dead — route
                # the same jitted program to a CPU device (single-chip
                # path only; a mesh program keeps its own placement)
                step_fn = self._packed_step
                if self.mesh is None:
                    from sitewhere_tpu.runtime.devguard import FALLBACK

                    if self.breaker.level >= FALLBACK:
                        fallback = self._cpu_packed_step()
                        if fallback is not None:
                            step_fn = fallback
                            self._m_fault["cpu_fallback_steps"].inc()
                wd = self.watchdog.begin(plan)
                self._wd_tokens[id(plan)] = wd
                try:
                    if faults.device_active():
                        # fires against the retained HOST copies, so the
                        # injection point is mesh-agnostic — per-shard
                        # containment drills rely on it firing here too
                        faults.device_fire("device.dispatch",
                                           values=plan.packed_f,
                                           valid=plan.packed_i[0] != 0)
                    with trace.span("step.dispatch").tag(
                            "rows", plan.n_events):
                        new_ps, oi, metrics, present = step_fn(
                            tables, ps, bi, bf)
                        self.state_manager.commit_packed(
                            new_ps, present_now=present, read_epoch=epoch)
                    # Start the egress fetches NOW, asynchronously: the
                    # copies complete in the background while later plans
                    # step, so the blocking np.asarray at the window's
                    # egress end finds the bytes already on the host
                    # (≈0 RTT in steady state).
                    start_host_copy(oi, metrics,
                                    on_error=self._on_host_copy_error)
                except Exception as e:
                    self._wd_end(plan)
                    self._contain_step_failure(plan, e, replay_depth,
                                               trace)
                    return
                dt = time.perf_counter() - t_dispatch
                self._m_stage["dispatch"].observe(dt)
                plan.dispatch_s = dt   # flight-record stage attribution
                self._window_step(
                    plan,
                    PackedView(oi, metrics, present,
                               on_fetch=self._m_host_syncs.inc),
                    replay_depth, trace)
                return
            batch = plan.batch
            state = self.state_manager.current
            if self.mesh is not None:
                from sitewhere_tpu.pipeline.sharded import place_batch

                registry = self._placed("registry", self.registry_provider())
                rules = self._placed("rules", self.rules_provider(),
                                     replicated=True)
                zones = self._placed("zones", self.zones_provider(),
                                     replicated=True)
                # State changes identity every commit, so caching would
                # never hit; device_put is a no-op once the epoch already
                # carries the mesh sharding (i.e. after the first step).
                from sitewhere_tpu.pipeline.sharded import _specs_sharded

                state = jax.tree_util.tree_map(
                    self._mesh_put, state, _specs_sharded(state))
                batch = place_batch(self.mesh, batch)
            else:
                registry = self.registry_provider()
                rules = self.rules_provider()
                zones = self.zones_provider()
            with trace.span("step.dispatch").tag("rows", plan.n_events):
                new_state, out = self._step(registry, state, rules, zones,
                                            batch)
                self.state_manager.commit(new_state,
                                          present_now=out.present_now)
            dt = time.perf_counter() - t_dispatch
            self._m_stage["dispatch"].observe(dt)
            plan.dispatch_s = dt
            self._window_step(plan, out, replay_depth, trace)

    def _contain_step_failure(self, plan: BatchPlan, exc,
                              replay_depth: int, trace) -> None:
        """A single-step packed dispatch failed: bisect the batch
        host-side until the poison rows are isolated (runs under
        ``_step_lock``).

        The full valid-row set is retried FIRST — a transient device
        fault recovers in one extra dispatch with zero loss.  A subset
        that still faults splits in half; singles that fault are poison
        and dead-letter replayably as ``device-poison`` (the raw
        columns ride the document, so ``requeue_dead_letter`` can
        re-ingest them after the producer is fixed).  Every CLEAN
        subset dispatches, commits, and windows normally — committed
        rows are never lost, only isolated poison rows leave the
        pipeline, and they leave with a paper trail.

        Subsets mask rows via ``valid=0`` columns (device semantics
        identical to a short batch), so disjoint subsets never double
        count and per-device writes keep their time-ordered winner
        scatter semantics regardless of subset order.
        """
        self._m_fault["step_faults"].inc()
        self._record_device_fault(plan.seq, (plan,))
        logger.warning("packed step failed for seq=%d (%d rows): %s — "
                       "bisecting", plan.seq, plan.n_events, exc)
        if self.flightrec is not None:
            self._flight_record(
                plan, None, replay_depth, commit="device-fault",
                trace=trace, error=f"{type(exc).__name__}: {exc}")
            self.flightrec.anomaly(
                "device-fault",
                detail=f"step seq={plan.seq} failed: "
                       f"{type(exc).__name__}: {exc}")
        try:
            valid_rows = np.nonzero(np.asarray(plan.packed_i[0]) != 0)[0]
            poison: List[int] = []
            stack = [valid_rows]
            while stack:
                rows = stack.pop()
                if rows.size == 0:
                    continue
                self._m_fault["bisect_rounds"].inc()
                if self._try_subset(plan, rows, replay_depth, trace):
                    continue
                if rows.size == 1:
                    poison.append(int(rows[0]))
                    continue
                mid = rows.size // 2
                stack.append(rows[mid:])
                stack.append(rows[:mid])
            if poison:
                self._m_fault["poison_rows"].inc(len(poison))
                logger.warning("isolated %d poison row(s) in seq=%d — "
                               "dead-lettering", len(poison), plan.seq)
                self._dead_letter_poison(plan, poison, exc)
        finally:
            # the original plan never egresses — its outstanding slot
            # (incremented at _take) retires here; clean subsets above
            # balanced their own increments through normal egress
            with self._lock:
                self._plans_outstanding -= 1

    def _try_subset(self, plan: BatchPlan, rows: np.ndarray,
                    replay_depth: int, trace, step_fn=None) -> bool:
        """Dispatch ``plan`` with only ``rows`` valid; True on success.

        Skips ``plan.staged`` on purpose: the bisect path rebuilds the
        batch from the retained HOST buffers (``packed_i``/``packed_f``)
        so the masked columns are exactly what the device sees.
        ``step_fn`` overrides the packed step — the demoted-shard
        sidecar routes FALLBACK-level shards through the CPU step."""
        bi = np.array(plan.packed_i, copy=True)
        mask = np.zeros(bi.shape[1], dtype=bool)
        mask[rows] = True
        bi[0] = np.where(mask, bi[0], 0)
        bf = plan.packed_f
        if step_fn is None:
            step_fn = self._packed_step
        try:
            if faults.device_active():
                faults.device_fire("device.dispatch", values=bf,
                                   valid=bi[0] != 0)
            tables = self._tables_packed()
            epoch = self.state_manager.current_packed
            with self._lock:
                self._plans_outstanding += 1
            try:
                new_ps, oi, metrics, present = step_fn(
                    tables, epoch, bi, bf)
                # surface async execution faults HERE, inside the
                # containment, not at the egress fetch
                jax.block_until_ready(new_ps)
                self.state_manager.commit_packed(
                    new_ps, present_now=present, read_epoch=epoch)
            except Exception:
                with self._lock:
                    self._plans_outstanding -= 1
                raise
        except Exception:
            return False
        from sitewhere_tpu.pipeline.packed import (
            PackedView,
            start_host_copy,
        )

        start_host_copy(oi, metrics, on_error=self._on_host_copy_error)
        self._window_step(
            plan,
            PackedView(oi, metrics, present,
                       on_fetch=self._m_host_syncs.inc),
            replay_depth, trace)
        return True

    def _dead_letter_poison(self, plan: BatchPlan, rows: List[int],
                            exc) -> None:
        """Dead-letter isolated poison rows replayably: the document
        carries the raw host columns, so the ``device-poison`` requeue
        branch (instance.py) can rebuild and re-ingest the exact rows
        once the producer-side corruption is fixed."""
        if self.dead_letters is None:
            return
        idx = np.asarray(rows, dtype=np.int64)
        columns = {
            field: np.asarray(col)[idx].tolist()
            for field, col in plan.host_cols.items()
        }
        dead_letter(self.dead_letters, {
            "kind": "device-poison",
            "error": f"{type(exc).__name__}: {exc}",
            "seq": int(plan.seq),
            "count": len(rows),
            "columns": columns,
        }, metrics=self.metrics)
        if self.usage_ledger is not None and "tenant_id" in columns:
            self.usage_ledger.charge_rows_host(
                np.asarray(columns["tenant_id"], np.int64),
                "dead_letter_rows")

    def _cpu_packed_step(self):
        """Lazily build (and cache) the packed step jitted for a CPU
        device — the breaker's FALLBACK level.  Returns None when no CPU
        device is addressable (the caller then keeps the default path:
        demoted single-step beats a dead fallback)."""
        if self._cpu_step is False:
            return None
        if self._cpu_step is None:
            try:
                from sitewhere_tpu.pipeline.packed import (
                    packed_pipeline_step,
                )

                cpu = jax.devices("cpu")[0]
                jitted = jax.jit(packed_pipeline_step)

                def run(tables, ps, bi, bf, _cpu=cpu, _fn=jitted):
                    tables, ps, bi, bf = jax.device_put(
                        (tables, ps, bi, bf), _cpu)
                    return _fn(tables, ps, bi, bf)

                self._cpu_step = run
            except Exception as e:
                logger.warning("CPU fallback unavailable: %s", e)
                self._cpu_step = False
                return None
        return self._cpu_step

    def _offloaded(self) -> bool:
        """Is the supervised egress worker accepting work?  False before
        start(), after stop(), with ``egress_offload=False``, and once
        the worker has escalated terminally — every caller then falls
        back to the inline synchronous egress."""
        sup = self._egress_super
        return sup is not None and sup.alive and not sup.escalated

    @hot_path
    def _window_step(self, plan, out, replay_depth: int, trace) -> None:
        """Window the dispatched step in flight (dispatch is async).
        Offloaded: hand the window to the egress worker and return — the
        dispatch thread's step N+1 overlaps the worker's egress of N.
        Inline fallback: egress the oldest plans beyond the window on
        THIS thread while the device computes.  Called under _step_lock."""
        self.steps += 1
        self._m_steps.inc()
        self._inflight.append((plan, out, replay_depth, trace))
        if self._offloaded():
            self._m_inflight.set(len(self._inflight))
            self._egress_evt.set()
            return
        while len(self._inflight) > self.inflight_depth:
            self._egress_guarded(self._inflight.popleft())

    def _drain_inflight(self, max_n: Optional[int] = None) -> None:
        if self._offloaded():
            # The worker owns draining: wake it and return.  Callers that
            # need COMPLETION gate on the accounting that already covers
            # offloaded egress — flush() waits for _plans_outstanding to
            # hit zero, the commit path re-checks _inflight next tick.
            self._egress_evt.set()
            return
        with self._step_lock:
            # Egress may re-inject (replay, derived alerts), which runs a
            # new step and appends it to the window — loop until settled
            # (bounded by max_replay_depth).
            n = 0
            while self._inflight and (max_n is None or n < max_n):
                self._egress_guarded(self._inflight.popleft())
                n += 1

    def _egress_worker(self) -> None:
        """Egress offload loop (runs under a Supervisor): pull dispatched
        steps off the window FIFO and fan them out, so the dispatch
        thread never blocks on a device→host fetch or a slow sink.  An
        egress exception propagates — the Supervisor counts the death,
        restarts the loop with backoff, and the failed plan stays
        outstanding (the commit gate fails closed; journal replay
        recovers its rows after a restart: at-least-once)."""
        while True:
            item = None
            with self._step_lock:
                if self._inflight:
                    item = self._inflight.popleft()
                    self._egress_busy = True
                elif self._egress_stop.is_set():
                    return
            if item is None:
                self._egress_evt.wait(0.01)
                self._egress_evt.clear()
                continue
            try:
                self._egress_guarded(item)
            finally:
                self._egress_busy = False
                self._room_evt.set()

    def _egress_guarded(self, item) -> None:
        """:meth:`_egress` with crash accounting — shared by the offload
        worker AND the inline fallback paths, so an egress failure is
        counted and flight-recorded (the crashed plan's record with its
        trace id, THEN the anomaly dump: the snapshot must contain the
        batch that died) no matter which thread ran it."""
        try:
            try:
                self._egress(*item)
            except Exception as e:
                self.egress_failures += 1
                self._m_egress_fail.inc()
                if self.flightrec is not None:
                    self._flight_record(
                        item[0], item[1], item[2], commit="failed",
                        trace=item[3],
                        error=f"{type(e).__name__}: {e}")
                    self.flightrec.anomaly("egress-crash", detail=str(e))
                plan = item[0]
                if (self._copy_suspect and plan.packed_i is not None
                        and item[2] == 0):
                    # the async D2H copy for this window faulted
                    # (_on_host_copy_error flagged it); the egress fetch
                    # hit the dead buffer.  Re-dispatch the plan
                    # single-step — the state re-step is at-least-once,
                    # identical to journal replay.  Ring siblings that
                    # shared the dead fetch still fail closed and
                    # recover via replay: only the FIRST faulted plan
                    # retries inline.
                    self._copy_suspect = False
                    logger.warning(
                        "egress failed after host-copy fault; "
                        "re-dispatching seq=%d single-step", plan.seq)
                    self._dispatch_plan(plan, 1, stall=False)
                    return
                raise
        finally:
            # watchdog retire happens whether egress succeeded, failed,
            # or handed off to a re-dispatch (the retry registers its
            # own entry); the pop is idempotent for bisected subsets
            self._wd_end(item[0])

    @hot_path
    def _egress(self, plan: BatchPlan, out, replay_depth: int,
                trace=None) -> None:
        """Host fan-out of one step's outputs.

        The input batch never leaves the host (``plan.host_cols``); only
        step outputs are fetched, and the rare-row masks (unregistered,
        derived alerts) only when their metric counters are nonzero.
        """
        from sitewhere_tpu.runtime.tracing import _NOOP_TRACE

        # chaos hook: an egress failure mid-window — the plan has already
        # stepped but never completes, so _plans_outstanding stays
        # elevated and the journal offset is NEVER committed past it
        # (at-least-once: a restart replays the record).  Offloaded, the
        # raise kills the egress WORKER mid-window; its supervisor
        # restarts the loop and the window's remaining plans still drain.
        faults.fire("dispatcher.egress")
        t_egress = time.perf_counter()
        if trace is None:
            trace = _NOOP_TRACE
        host_cols = plan.host_cols
        if not hasattr(out, "_fetch"):
            # unpacked fallback: the as_numpy/np.asarray below IS a
            # blocking device→host sync (packed/ring views count their
            # own lazy fetch via on_fetch instead)
            self._m_host_syncs.inc()
        with trace.span("egress.fetch-outputs"):
            m = as_numpy(out.metrics)
            # packed/ring views hand back the host mask memoized on the
            # shared fetch; only the unpacked fallback still pays a
            # device→host conversion here
            accepted = (out.accepted if hasattr(out, "_fetch")
                        else as_numpy(out.accepted))
            cols = self._columns(host_cols, out)
        for key in ("processed", "accepted", "unregistered", "unassigned",
                    "threshold_alerts", "zone_alerts"):
            count = int(getattr(m, key))
            self.totals[key] += count
            if count:
                self._m_totals[key].inc(count)
        # On-device occupancy telemetry: the packed views expose the
        # TELEMETRY_SCALARS block from the SAME fetched metrics vector
        # (zero additional syncs); the unpacked fallback still surfaces
        # the counts derivable from the step metrics alone.
        self._m_occ["rows_admitted"].set(int(m.processed))
        self._m_occ["rules_fired"].set(
            int(m.threshold_alerts) + int(m.zone_alerts))
        # genuinely lost rows: the device counter is width - valid,
        # which on a partial plan mostly counts batch PADDING — the
        # plan's real row count is host knowledge, so subtract here
        self._m_occ["rows_invalid"].set(
            max(0, int(plan.n_events) - int(m.processed)))
        telemetry = getattr(out, "telemetry", None)
        if telemetry:
            for key in ("state_writes", "presence_merges"):
                if key in telemetry:
                    self._m_occ[key].set(telemetry[key])
            # Numeric-integrity quarantine: the device counted this
            # plan's NaN/Inf rows on the SAME packed metrics vector
            # (zero extra syncs) — the per-device host attribution scan
            # below runs only on the rare nonzero path.
            nf = int(telemetry.get("rows_nonfinite", 0))
            if nf:
                self._m_quar_rows.inc(nf)
                self._scan_quarantine(plan, replay_depth)
        # Tenant metering: fold the device-side per-tenant scatter block
        # (same fetched vector — zero extra syncs) into the usage ledger
        if self.usage_ledger is not None:
            self._meter_plan(out, host_cols)
        # monotonic receive time of the plan's oldest row — the watermark
        # the per-stage ingest→seal / ingest→ack gauges measure from
        ingest_t0 = plan.created_at - plan.max_wait_s

        refs = host_cols["payload_ref"]
        journaled = refs != NULL_ID
        if journaled.any():
            self._max_egressed_ref = max(
                self._max_egressed_ref, int(refs[journaled].max()))

        # 1. persistence (event-management analog).  Replay below the
        # committed offset (checkpoint-restore floor) skips rows already
        # durably stored — their state/analytics effects still re-run.
        store_mask = accepted
        if self.store_dedup_floor > 0:
            store_mask = accepted & ((refs == NULL_ID)
                                     | (refs >= self.store_dedup_floor))
        if self.event_store is not None and store_mask.any():
            with trace.span("egress.persist").tag(
                    "rows", int(store_mask.sum())):
                self.event_store.append_columns(cols, mask=store_mask)
            self._m_seal.set(time.monotonic() - ingest_t0)
        elif accepted.any() and (self.outbound is not None
                                 or self.analytics is not None):
            # the store path would have fetched the enrichment columns
            # (releasing the step output); without it, fetch-and-release
            # here so async outbound/analytics queues holding the view
            # never pin this step's device buffers.  With no async
            # consumer at all, the view dies with this frame and the
            # device sync is genuinely skipped.
            release = getattr(cols, "release_output", None)
            if release is not None:
                release()
        # chaos kill point: stored (possibly sealed) but the offset
        # commit below never runs — a restart must replay this plan
        faults.crosspoint("crash.mid_egress")

        # 2. enriched fan-out (outbound connectors + rule processor hosts)
        #    — the trace rides along so the async delivery span joins it
        if self.outbound is not None and accepted.any():
            with trace.span("egress.outbound"):
                self.outbound.submit(cols, accepted, trace=trace,
                                     ingest_t0=ingest_t0)

        # 2b. streaming analytics: live window/CEP query evaluation
        #     (non-blocking offer; sheds itself from SHEDDING up as a
        #     non-priority consumer — see QueryRunner.submit_live)
        if self.analytics is not None and accepted.any():
            with trace.span("egress.analytics"):
                # the committed offset rides along as the runner's
                # fully-applied watermark: queue order guarantees every
                # batch carrying rows of records below it was offered
                # (and thus evaluates) before this one
                self.analytics.submit_live(
                    cols, accepted, trace=trace,
                    committed=(int(self.journal_reader.committed)
                               if self.journal_reader is not None
                               else None))

        # 2c. tenant rule programs (rules/engine.RuleEngineRunner):
        #     compiled per-structure kernels over the same accepted
        #     enriched batch; fired programs come back through
        #     inject_rule_alerts as first-class ALERT events
        if self.rules_engine is not None and accepted.any():
            with trace.span("egress.rules"):
                self.rules_engine.submit_live(
                    cols, accepted, trace=trace,
                    committed=(int(self.journal_reader.committed)
                               if self.journal_reader is not None
                               else None))

        # 3. command invocations (command-delivery analog)
        cmd_mask = accepted & (cols["event_type"] == EventType.COMMAND_INVOCATION)
        if self.on_command_rows is not None and cmd_mask.any():
            self.totals["commands"] += int(cmd_mask.sum())
            with trace.span("egress.commands"):
                self.on_command_rows(cols, cmd_mask, trace=trace)

        # 4. auto-registration + replay (device-registration analog)
        if int(m.unregistered) > 0:
            with trace.span("egress.registration"):
                self._handle_unregistered(host_cols, out, replay_depth)

        # 5. derived alerts re-injection (rule outputs become first-class
        #    events, reference ZoneTestRuleProcessor fires alerts back
        #    through event management) — fetched only when rules fired
        if int(m.threshold_alerts) + int(m.zone_alerts) > 0:
            with trace.span("egress.derived-alerts"):
                self._reinject_derived(plan, out, replay_depth)

        # Egress complete: record the plan's end-to-end latency (batcher
        # wait of its oldest row + emit→egress) and release it from the
        # commit gate.  On an exception above the count stays elevated —
        # commits stop (fail closed) rather than risk committing past an
        # un-egressed record.  The deque append shares _lock with
        # metrics_snapshot's copy (deques error on mutation-mid-iteration).
        lat = max(0.0, time.monotonic() - plan.created_at) + plan.max_wait_s
        with self._lock:
            self.latencies_s.append(lat)
            self._plans_outstanding -= 1
        # Close the trace: for tail candidates this IS the retention
        # decision (errored/slow traces flip to sampled, so the async
        # outbound/command spans still land in the ring).  The e2e
        # histogram exemplar uses the post-decision sampled flag — only
        # traces an operator can actually open are linked.
        trace.end()
        self._m_e2e.observe(
            lat, trace_id=(trace.trace_id if trace.sampled else None))
        self._m_queue.set(self.batcher.pending)
        self._m_inflight.set(len(self._inflight))
        egress_dt = time.perf_counter() - t_egress
        self._m_stage["egress"].observe(egress_dt)
        if self.flightrec is not None:
            self._flight_record(plan, out, replay_depth, commit="ok",
                                e2e_s=lat, egress_s=egress_dt,
                                trace=trace)

    def _columns(self, host_cols: Dict[str, np.ndarray], out):
        """Egress columns as a zero-copy view (see :class:`EgressColumns`)
        — no per-batch dict build, no eager enrichment fetches (the
        retired ROADMAP-2 worklist entry: the 4.0 ms dispatch-bookkeeping
        suspect)."""
        return EgressColumns(host_cols, out)

    def _meter_plan(self, out, host_cols: Dict[str, np.ndarray]) -> None:
        """Bill one egressed plan to its tenants (tenant metering plane).

        The device already bucketed accepted rows / state writes /
        nonfinite rows by ``tenant_id % TENANT_METER_SLOTS`` inside the
        compiled step; the ledger resolves buckets against the plan's
        retained host tenant column (exact attribution, collision-
        apportioned) — no per-row host work on the common path.  The
        decode stage's running-total delta rides along so decode time
        is row-share-attributed to the same tenants."""
        block = getattr(out, "tenant_meter", None)
        tenants = host_cols.get("tenant_id") if host_cols else None
        if block is None or tenants is None:
            return
        decode_total = self._m_stage["decode"].total
        decode_s = max(0.0, decode_total - self._meter_decode_mark)
        self._meter_decode_mark = decode_total
        try:
            self.usage_ledger.charge_device_block(
                block, tenants, decode_s=decode_s)
            self.usage_ledger.publish(min_interval_s=1.0)
        except Exception:
            logger.exception("tenant metering failed for one plan")

    def _scan_quarantine(self, plan: BatchPlan, replay_depth: int) -> None:
        """Per-device attribution of the plan's nonfinite rows (called
        ONLY when the device-counted ``rows_nonfinite`` telemetry scalar
        is nonzero — never on the clean path).

        The device already masked these rows out of state, rules, and
        analytics (pipeline/step.py) and counted them per device in
        ``DeviceState.nonfinite_count``; this host scan re-derives the
        row set from the RETAINED numpy columns to accumulate a
        per-device strike count.  A device crossing
        ``quarantine_after`` cumulative poison rows emits ONE
        STATE_CHANGE (``STATE_CHANGE_QUARANTINED``) through the normal
        re-injection egress — downstream consumers see the quarantine
        exactly like a presence transition."""
        host = plan.host_cols
        if not host or "device_id" not in host:
            return
        valid = np.asarray(host["valid"]) != 0 if "valid" in host \
            else np.asarray(plan.packed_i[0]) != 0
        finite = np.ones(valid.shape, dtype=bool)
        for field in ("value", "lat", "lon", "elevation"):
            col = host.get(field)
            if col is not None:
                finite &= np.isfinite(np.asarray(col, dtype=np.float32))
        bad = valid & ~finite
        if not bad.any():
            return
        devs = np.asarray(host["device_id"])[bad].tolist()
        tens = (np.asarray(host["tenant_id"])[bad].tolist()
                if "tenant_id" in host else [0] * len(devs))
        newly = []
        for dev, ten in zip(devs, tens):
            if dev < 0:
                continue
            seen = self._nonfinite_seen.get(dev, 0) + 1
            self._nonfinite_seen[dev] = seen
            if (seen >= self.quarantine_after
                    and dev not in self._quarantined):
                self._quarantined.add(dev)
                newly.append((int(dev), int(ten)))
        self._m_quar_devices.set(len(self._quarantined))
        if not newly:
            return
        self._m_quar_changes.inc(len(newly))
        logger.warning("quarantined %d device(s) for nonfinite values: %s",
                       len(newly), [d for d, _ in newly])
        if self.flightrec is not None:
            # ring record BEFORE the anomaly dump so the snapshot's own
            # evidence includes which devices tripped and on which plan
            # (tools/flightrec_timeline.py renders kind-style records)
            self.flightrec.record(
                kind="quarantine", seq=int(plan.seq),
                rows=len(devs), devices=[d for d, _ in newly],
                strikes=self.quarantine_after)
            self.flightrec.anomaly(
                "device-quarantine",
                detail=f"devices {[d for d, _ in newly]} crossed "
                       f"{self.quarantine_after} nonfinite rows")
        if replay_depth < self.max_replay_depth:
            import jax.numpy as jnp

            from sitewhere_tpu.state.presence import (
                STATE_CHANGE_QUARANTINED,
                state_changes_for,
            )

            n = len(newly)
            batch = state_changes_for(
                np.asarray([d for d, _ in newly], np.int32),
                np.asarray([t for _, t in newly], np.int32),
                int(time.time()))
            batch = batch.replace(
                alert_code=jnp.full(n, STATE_CHANGE_QUARANTINED,
                                    jnp.int32))
            self.inject_batch(batch, np.ones(n, dtype=bool),
                              replay_depth + 1)

    def _handle_unregistered(self, host_cols, out, replay_depth: int) -> None:
        mask = np.asarray(out.unregistered)
        if not mask.any():
            return
        refs = host_cols["payload_ref"][mask]
        requests: List[DecodedRequest] = []
        unreplayable: List[int] = []
        if self.journal is not None and self.registration is not None:
            # resolve original requests from the journal for replay;
            # rows from one multi-event payload share an offset, so decode
            # each distinct ref once
            from sitewhere_tpu.ingest.decoders import JsonLinesDecoder

            decoder = JsonLinesDecoder()  # handles envelopes AND NDJSON
            unreplayable = [int(r) for r in refs if int(r) == NULL_ID]
            for ref in dict.fromkeys(int(r) for r in refs if int(r) != NULL_ID):
                try:
                    # host-plane lines (registrations, stream data) were
                    # handled at first ingest; only events replay — a
                    # host-plane request would wedge the batcher
                    requests.extend(
                        r for r in decoder(self.journal.read_one(ref))
                        if r.event_type is not None)
                except Exception:
                    logger.debug("unreplayable payload ref %d", ref)
                    unreplayable.append(ref)
        else:
            unreplayable = [int(r) for r in refs]
        # every unreplayable row dead-letters, even when siblings replay
        if unreplayable and self.dead_letters is not None:
            dead_letter(self.dead_letters,
                        {"kind": "unregistered", "count": len(unreplayable),
                         "refs": unreplayable})
        if self.registration is None or not requests:
            return
        # A multi-event payload shares one journal ref across rows, so the
        # re-decode above returns EVERY event in the payload — drop only
        # the siblings THIS plan processed normally (their dense id
        # appears on a non-unregistered row of the same payload).  A
        # token that raced to registration between intake and egress
        # resolves to an id outside this plan's processed set and is
        # still replayed — filtering must never lose an event.
        replayed_refs = np.isin(
            host_cols["payload_ref"],
            [int(r) for r in dict.fromkeys(int(r) for r in refs)
             if int(r) != NULL_ID])
        sibling_processed = {
            int(i)
            for i in host_cols["device_id"][replayed_refs & ~mask]
            if int(i) != NULL_ID
        }
        if sibling_processed:
            requests = [
                r for r in requests
                if self.batcher.resolve_device(r.device_token)
                not in sibling_processed
            ]
        if not requests:
            return
        replay = self.registration.process_unregistered(requests)
        if replay and replay_depth < self.max_replay_depth:
            self.totals["replayed"] += len(replay)

            def intake():
                out = []
                for req in replay:
                    tenant_id = self.resolve_tenant(
                        req.metadata.get("tenant", "default")
                        if req.metadata else "default"
                    )
                    plan = self.batcher.add(req, tenant_id=tenant_id,
                                            payload_ref=NULL_ID)
                    if plan is not None:
                        out.append(plan)
                return out

            self._run_plans(self._take(intake), replay_depth + 1)

    def _reinject_derived(self, plan: BatchPlan, out,
                          replay_depth: int) -> None:
        if replay_depth >= self.max_replay_depth:
            return
        if hasattr(out, "derived_cols"):
            # Packed path: reconstruct the (rare) derived rows from host
            # columns + the packed output block — no same-width EventBatch
            # round-trip off the device.
            rows = np.nonzero(out.derived_valid)[0]
            if rows.size == 0:
                return
            self.totals["derived_alerts"] += int(rows.size)
            cols = out.derived_cols(plan.host_cols, rows)
            self._run_plans(self._take(
                lambda: self.batcher.add_arrays(_copy=False, **cols)),
                replay_depth + 1)
            return
        derived = as_numpy(out.derived_alerts)
        mask = np.asarray(derived.valid)
        count = int(mask.sum())
        if count == 0:
            return
        self.totals["derived_alerts"] += count
        self.inject_batch(derived, mask, replay_depth + 1)

    def inject_batch(self, batch: EventBatch, mask: np.ndarray,
                     replay_depth: int = 0) -> None:
        """Re-inject an already-dense event batch (derived alerts, presence
        STATE_CHANGEs) through the pipeline as first-class events —
        columnar: one mask-select per field, no per-row work."""
        from sitewhere_tpu.ingest.batcher import _COL_FIELDS

        host = as_numpy(batch)
        rows = np.nonzero(np.asarray(mask))[0]
        if rows.size == 0:
            return
        cols = {f: np.asarray(getattr(host, f))[rows] for f in _COL_FIELDS}
        # fancy-indexed gathers above are fresh arrays — skip the copy
        self._run_plans(self._take(
            lambda: self.batcher.add_arrays(_copy=False, **cols)),
            replay_depth)

    def inject_rule_alerts(self, cols: Dict[str, np.ndarray]) -> int:
        """Re-inject fired tenant-program alerts as first-class ALERT
        events (the BYO-rules half of the derived-alert contract).

        Called from the rule engine's worker thread — the dispatcher
        lock is an RLock and ``_take``/``_run_plans`` serialize against
        live intake, so the injection is just another intake edge.  The
        engine builds the columns with ``update_state=False`` (derived
        alerts never re-fold trailing state) and the kernels mask ALERT
        rows at eval, so the path cannot self-amplify."""
        n = int(np.asarray(cols["device_id"]).size)
        if n == 0:
            return 0
        self.totals["derived_alerts"] += n
        self.totals["rule_program_alerts"] = (
            self.totals.get("rule_program_alerts", 0) + n)
        self._run_plans(self._take(
            lambda: self.batcher.add_arrays(_copy=False, **cols)))
        return n

    def requeue_rows(self, cols: Dict[str, np.ndarray]) -> int:
        """Re-ingest raw event columns through the normal batch path —
        the ``device-poison`` dead-letter requeue (instance.py): the
        isolated rows re-enter exactly like fresh ingest once the
        producer-side corruption is fixed.  Returns the row count."""
        n = int(np.asarray(cols["device_id"]).size)
        if n == 0:
            return 0
        self._run_plans(self._take(
            lambda: self.batcher.add_arrays(_copy=False, **cols)))
        return n

    def oldest_unsealed_wait_s(self) -> float:
        """LIVE ingest→seal watermark: age of the oldest event admitted
        but not yet through egress — the overload controller's lag
        signal.  The last-value seal gauge can't serve here: one slow
        plan (a jit compile) pins it at a historical spike for as long
        as anything is busy, reading as sustained overload when the
        system is actually healthy.  This measure self-decays: work
        seals, the wait disappears.  Lock-free reads (a torn read only
        skews one sample)."""
        if self.steps == 0:
            # warm-up gate: before the FIRST step completes, rows wait
            # on the jit compile (seconds), which is boot cost — not
            # overload.  Compiles are shape-cached after this; the
            # other signals (backlog fractions) still guard a wedged
            # boot.
            return 0.0
        now = time.monotonic()
        wait = 0.0
        oldest = self.batcher._oldest
        if oldest is not None and self.batcher.pending > 0:
            wait = now - oldest
        try:
            plan = self._inflight[0][0]
            wait = max(wait, now - plan.created_at + plan.max_wait_s)
        except IndexError:
            pass
        # Ring-held plans are in flight too (emitted, not yet stepped):
        # with multiple steps buffered for a chained dispatch, the
        # overload signal must reflect the OLDEST of them, not only the
        # already-windowed steps — otherwise a wedged ring reads healthy.
        try:
            plan = self._ring[0]
            wait = max(wait, now - plan.created_at + plan.max_wait_s)
        except IndexError:
            pass
        return max(0.0, wait)

    def metrics_snapshot(self) -> Dict[str, object]:
        with self._lock:
            pending = self.batcher.pending
            samples = list(self.latencies_s)
        snap: Dict[str, object] = {
            "steps": self.steps,
            "pending_rows": pending,
            # device-resident dispatch loop surface: how often the host
            # touched the device, and how much of the traffic rode chains
            "host_syncs": int(self._m_host_syncs.value),
            "ring_depth": self.ring_depth,
            "ring_chains": int(self._m_ring_chains.value),
            "ring_flushed_plans": int(self._m_ring_flushes.value),
            "device_fault": {
                "breaker": self.breaker.snapshot(),
                "watchdog": self.watchdog.snapshot(),
                "quarantined_devices": len(self._quarantined),
            },
            **self.totals,
        }
        if samples:
            lat = np.asarray(samples)
            snap["latency_p50_ms"] = round(float(np.percentile(lat, 50)) * 1e3, 3)
            snap["latency_p99_ms"] = round(float(np.percentile(lat, 99)) * 1e3, 3)
        return snap
