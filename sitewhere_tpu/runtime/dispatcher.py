"""Pipeline dispatcher: the host loop driving the fused TPU step.

This is the TPU reshape of the reference's inbound-processing service
(``InboundPayloadProcessingLogic.java:135-159`` — Kafka poll → per-record
thread-pool tasks → per-event gRPC) plus the enrichment forwarding
(``OutboundPayloadEnrichmentLogic.java:54-88``) and the fan-out consumers:
instead of processes connected by Kafka topics, ONE host thread cycles

    batcher → jitted pipeline step (device) → routed host egress

where egress covers everything the reference spreads over five services:

- accepted rows  → event store append (event-management persistence)
- enriched cols  → outbound connector workers (outbound-connectors) —
  which also host rule-processor callbacks (rule-processing)
- command rows   → command processor (command-delivery)
- unregistered   → registration manager → replay (device-registration,
  reprocess topic)
- derived alerts + presence state-changes → re-injected into the batcher
- new state      → DeviceStateManager.commit (device-state), sweep-safe

Double-buffering: while the device computes step N, the host assembles
batch N+1 and drains egress N-1 (egress handoff is queue-based; JAX
dispatch is async until outputs are fetched).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, List, Optional

import jax
import numpy as np

from sitewhere_tpu.ids import NULL_ID
from sitewhere_tpu.ingest.batcher import Batcher, BatchPlan
from sitewhere_tpu.ingest.decoders import DecodedRequest
from sitewhere_tpu.ingest.journal import Journal
from sitewhere_tpu.pipeline.step import pipeline_step
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent
from sitewhere_tpu.schema import EventBatch, EventType, as_numpy

logger = logging.getLogger("sitewhere_tpu.dispatcher")


class PipelineDispatcher(LifecycleComponent):
    """Owns the ingest→step→egress loop for one instance.

    Collaborators are duck-typed providers so tenants/tests can compose
    subsets:

    - ``registry_provider()`` / ``zones_provider()`` / ``rules_provider()``
      → current device-resident epochs (RegistryMirror / RuleManager)
    - ``state_manager`` → DeviceStateManager (commit + sweeps)
    - ``event_store`` → accepted-row persistence (append_columns)
    - ``outbound`` → OutboundConnectorsManager (submit cols+mask)
    - ``on_command_rows(cols, idx)`` → command-delivery hook
    - ``registration`` → RegistrationManager (process_unregistered)
    """

    def __init__(
        self,
        batcher: Batcher,
        registry_provider: Callable[[], object],
        state_manager,
        rules_provider: Callable[[], object],
        zones_provider: Callable[[], object],
        event_store=None,
        outbound=None,
        registration=None,
        on_command_rows: Optional[Callable[[Dict[str, np.ndarray], np.ndarray], None]] = None,
        journal: Optional[Journal] = None,
        dead_letters: Optional[Journal] = None,
        resolve_tenant: Optional[Callable[[str], int]] = None,
        max_replay_depth: int = 4,
        name: str = "pipeline-dispatcher",
    ):
        super().__init__(name)
        self.batcher = batcher
        self.registry_provider = registry_provider
        self.rules_provider = rules_provider
        self.zones_provider = zones_provider
        self.state_manager = state_manager
        self.event_store = event_store
        self.outbound = outbound
        self.registration = registration
        self.on_command_rows = on_command_rows
        self.journal = journal
        self.dead_letters = dead_letters
        self.resolve_tenant = resolve_tenant or (lambda token: 0)
        self.max_replay_depth = max_replay_depth
        # No donation of `state`: DeviceStateManager.commit's sweep-merge
        # and concurrent readers still reference the previous epoch.
        self._step = jax.jit(pipeline_step)
        self._lock = threading.Lock()
        # Serializes read-state → step → commit → egress across the loop
        # thread, source threads, and the presence thread: two concurrent
        # steps from the same snapshot would lose the first commit's state
        # merges.  RLock: replay/derived re-injection recurses.
        self._step_lock = threading.RLock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # host-aggregated counters (metrics endpoint surface)
        self.steps = 0
        self.totals: Dict[str, int] = {
            "processed": 0, "accepted": 0, "unregistered": 0,
            "unassigned": 0, "threshold_alerts": 0, "zone_alerts": 0,
            "replayed": 0, "derived_alerts": 0, "commands": 0,
        }

    # -- ingest entry points (wired as InboundEventSource.on_event) ---------

    def ingest(self, req: DecodedRequest, payload: bytes = b"") -> None:
        """Queue one decoded request (journal it first: at-least-once)."""
        ref = NULL_ID
        if self.journal is not None and payload:
            ref = self.journal.append(payload)
        tenant_id = self.resolve_tenant(req.metadata.get("tenant", "default")
                                        if req.metadata else "default")
        with self._lock:
            plan = self.batcher.add(req, tenant_id=tenant_id, payload_ref=ref)
        if plan is not None:
            self._run_plan(plan)

    def ingest_registration(self, req: DecodedRequest, payload: bytes = b"") -> None:
        if self.registration is not None:
            self.registration.handle_registration(req)

    def ingest_failed_decode(self, payload: bytes, source_id: str, error) -> None:
        if self.dead_letters is not None:
            self.dead_letters.append_json(
                {"kind": "failed-decode", "source": source_id,
                 "error": str(error), "payload": payload.hex()}
            )

    # -- the loop -----------------------------------------------------------

    def start(self) -> None:
        super().start()
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name=f"{self.name}-loop", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        self.flush()
        super().stop()

    def _loop(self) -> None:
        while not self._stop.wait(self.batcher.deadline_s / 2):
            try:
                with self._lock:
                    plan = self.batcher.poll()  # deadline-driven partial emit
                if plan is not None:
                    self._run_plan(plan)
            except Exception:
                logger.exception("dispatch cycle failed")

    def flush(self) -> None:
        """Force pending rows through (tests/shutdown)."""
        with self._lock:
            plan = self.batcher.flush()
        if plan is not None:
            self._run_plan(plan)

    # -- one step -----------------------------------------------------------

    def _run_plan(self, plan: BatchPlan, replay_depth: int = 0) -> None:
        with self._step_lock:
            batch = plan.batch
            state = self.state_manager.current
            new_state, out = self._step(
                self.registry_provider(), state,
                self.rules_provider(), self.zones_provider(), batch,
            )
            self.state_manager.commit(new_state, batch=batch,
                                      accepted=out.accepted)
            self._egress(batch, out, replay_depth)
            self.steps += 1

    def _egress(self, batch: EventBatch, out, replay_depth: int) -> None:
        """Host fan-out of one step's outputs (device→host copy happens
        here, once, for the whole struct)."""
        host_batch = as_numpy(batch)
        host_out = as_numpy(out)
        accepted = host_out.accepted
        m = host_out.metrics
        for key in ("processed", "accepted", "unregistered", "unassigned",
                    "threshold_alerts", "zone_alerts"):
            self.totals[key] += int(getattr(m, key))

        cols = self._columns(host_batch, host_out)

        # 1. persistence (event-management analog)
        if self.event_store is not None and accepted.any():
            self.event_store.append_columns(cols, mask=accepted)

        # 2. enriched fan-out (outbound connectors + rule processor hosts)
        if self.outbound is not None and accepted.any():
            self.outbound.submit(cols, accepted)

        # 3. command invocations (command-delivery analog)
        cmd_mask = accepted & (host_batch.event_type == EventType.COMMAND_INVOCATION)
        if self.on_command_rows is not None and cmd_mask.any():
            self.totals["commands"] += int(cmd_mask.sum())
            self.on_command_rows(cols, cmd_mask)

        # 4. auto-registration + replay (device-registration analog)
        self._handle_unregistered(host_batch, host_out, replay_depth)

        # 5. derived alerts re-injection (rule outputs become first-class
        #    events, reference ZoneTestRuleProcessor fires alerts back
        #    through event management)
        self._reinject_derived(host_out, replay_depth)

    def _columns(self, host_batch, host_out) -> Dict[str, np.ndarray]:
        cols = {
            name: getattr(host_batch, name)
            for name in (
                "device_id", "tenant_id", "event_type", "ts_s", "ts_ns",
                "mtype_id", "value", "lat", "lon", "elevation",
                "alert_code", "alert_level", "command_id", "payload_ref",
            )
        }
        for name in ("device_type_id", "assignment_id", "area_id",
                     "customer_id", "asset_id"):
            cols[name] = getattr(host_out, name)
        return cols

    def _handle_unregistered(self, host_batch, host_out, replay_depth: int) -> None:
        mask = host_out.unregistered
        if not mask.any():
            return
        refs = host_batch.payload_ref[mask]
        requests: List[DecodedRequest] = []
        unreplayable: List[int] = []
        if self.journal is not None and self.registration is not None:
            # resolve original requests from the journal for replay
            from sitewhere_tpu.ingest.decoders import JsonDecoder

            decoder = JsonDecoder()
            for ref in refs:
                if int(ref) == NULL_ID:
                    unreplayable.append(int(ref))
                    continue
                try:
                    requests.extend(decoder(self.journal.read_one(int(ref))))
                except Exception:
                    logger.debug("unreplayable payload ref %d", int(ref))
                    unreplayable.append(int(ref))
        else:
            unreplayable = [int(r) for r in refs]
        # every unreplayable row dead-letters, even when siblings replay
        if unreplayable and self.dead_letters is not None:
            self.dead_letters.append_json(
                {"kind": "unregistered", "count": len(unreplayable),
                 "refs": unreplayable}
            )
        if self.registration is None or not requests:
            return
        replay = self.registration.process_unregistered(requests)
        if replay and replay_depth < self.max_replay_depth:
            self.totals["replayed"] += len(replay)
            plans = []
            with self._lock:
                for req in replay:
                    tenant_id = self.resolve_tenant(
                        req.metadata.get("tenant", "default")
                        if req.metadata else "default"
                    )
                    plan = self.batcher.add(req, tenant_id=tenant_id,
                                            payload_ref=NULL_ID)
                    if plan is not None:
                        plans.append(plan)
            for plan in plans:
                self._run_plan(plan, replay_depth + 1)

    def _reinject_derived(self, host_out, replay_depth: int) -> None:
        derived = host_out.derived_alerts
        mask = np.asarray(derived.valid)
        count = int(mask.sum())
        if count == 0 or replay_depth >= self.max_replay_depth:
            return
        self.totals["derived_alerts"] += count
        self.inject_batch(derived, mask, replay_depth + 1)

    def inject_batch(self, batch: EventBatch, mask: np.ndarray,
                     replay_depth: int = 0) -> None:
        """Re-inject an already-dense event batch (derived alerts, presence
        STATE_CHANGEs) through the pipeline as first-class events."""
        host = as_numpy(batch)
        rows = np.nonzero(mask)[0]
        plans = []
        with self._lock:
            for i in rows:
                plan = self.batcher.add_dense(
                    device_id=int(host.device_id[i]),
                    tenant_id=int(host.tenant_id[i]),
                    event_type=int(host.event_type[i]),
                    ts_s=int(host.ts_s[i]),
                    ts_ns=int(host.ts_ns[i]),
                    mtype_id=int(host.mtype_id[i]),
                    value=float(host.value[i]),
                    lat=float(host.lat[i]),
                    lon=float(host.lon[i]),
                    elevation=float(host.elevation[i]),
                    alert_code=int(host.alert_code[i]),
                    alert_level=int(host.alert_level[i]),
                    command_id=int(host.command_id[i]),
                    payload_ref=int(host.payload_ref[i]),
                    update_state=bool(host.update_state[i]),
                )
                if plan is not None:
                    plans.append(plan)
        for plan in plans:
            self._run_plan(plan, replay_depth)

    def metrics_snapshot(self) -> Dict[str, object]:
        with self._lock:
            pending = self.batcher.pending
        return {
            "steps": self.steps,
            "pending_rows": pending,
            **self.totals,
        }
