"""Device-tier guards: a dispatch circuit breaker and a hung-step watchdog.

The fused K-step chain (pipeline/packed.py) turned the whole device tier
into ONE fault domain: a single dispatch failure used to strand a donated
carry, and a wedged chip used to look exactly like an idle one.  The two
guards here give the dispatcher the policy half of its containment
protocol; the mechanism half (re-park, re-lease, bisect) lives in
``runtime/dispatcher.py``.

:class:`DeviceBreaker` — repeated device faults across DISTINCT batches
demote dispatch down a ladder: chained (K-step rings, donated carry) →
single-step (one batch per dispatch, bisectable) → CPU fallback (the
chip is presumed dead).  A one-off fault never trips it; after
``cooldown_s`` a half-open probe re-admits one chained dispatch, and a
probe success restores chained dispatch fully.  Mirrors the overload
ladder's shape (runtime/overload.py) so operators read one idiom.

:class:`DeviceWatchdog` — refcounted in-flight dispatch tracking with a
soft and a hard wall-clock budget, both calibrated from the measured
``device.stage_ms``.  Past the soft budget the dispatcher dumps the
in-flight ring's records to the flight recorder (the chip is *slow*);
past the hard budget the device tier is marked unhealthy and the flag
rides the heartbeat so peers park forwards (the chip is *wedged*).  The
flag self-clears when every tracked dispatch drains.

Both guards take an injectable ``clock`` so tests drive them with fake
time, and both are lock-cheap on the happy path: ``allow_chain`` is one
attribute read while the breaker is closed, and ``begin``/``end`` touch
one small dict under a lock at plan granularity.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

__all__ = [
    "CHAINED",
    "SINGLE_STEP",
    "FALLBACK",
    "BREAKER_LEVELS",
    "DeviceBreaker",
    "ShardBreakers",
    "DeviceWatchdog",
]

# Breaker ladder levels, most to least capable.
CHAINED = 0        # K-step fused rings, donated carry
SINGLE_STEP = 1    # one plan per dispatch — bisectable, no donation
FALLBACK = 2       # route the packed step to a CPU device

BREAKER_LEVELS = ("chained", "single-step", "cpu-fallback")


class _Entry:
    __slots__ = ("started", "records", "parts", "soft_fired")

    def __init__(self, started: float, records, parts: int):
        self.started = started
        self.records = records
        self.parts = max(1, int(parts))
        self.soft_fired = False


class DeviceBreaker:
    """Demote dispatch after repeated device faults; probe back up.

    ``record_fault(seq)`` counts faults from DISTINCT batch sequence
    numbers inside a sliding ``window_s`` — the bisect protocol may
    re-fault the same batch several times while isolating poison rows,
    and that must count as ONE strike.  ``threshold`` distinct strikes
    escalate the level one rung (chained → single-step → cpu-fallback)
    and start the cooldown.  After ``cooldown_s`` the breaker half-opens:
    ``allow_chain`` admits chained dispatch again, and the next
    ``record_success(chained=True)`` restores :data:`CHAINED`; a fault
    during the probe re-closes it and restarts the cooldown.
    """

    def __init__(self, threshold: int = 3, window_s: float = 60.0,
                 cooldown_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic,
                 on_trip: Optional[Callable[[int], None]] = None,
                 on_restore: Optional[Callable[[], None]] = None):
        self.threshold = max(1, int(threshold))
        self.window_s = float(window_s)
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self.on_trip = on_trip
        self.on_restore = on_restore
        self._lock = threading.Lock()
        self._level = CHAINED
        self._strikes: List[tuple] = []    # (monotonic_s, batch_seq)
        self._tripped_at = 0.0
        self._probing = False
        self.trips = 0
        self.restores = 0

    @property
    def level(self) -> int:
        return self._level

    @property
    def level_name(self) -> str:
        return BREAKER_LEVELS[self._level]

    def allow_chain(self) -> bool:
        """True when chained (ring) dispatch is admitted.

        Closed-breaker fast path is one attribute read; a stale read
        merely lets one extra chain through, which the fault path then
        contains — same tolerance as the fault registry's fast gate.
        """
        if self._level == CHAINED:
            return True
        with self._lock:
            if self._level == CHAINED:
                return True
            if self._probing:
                return True
            if self._clock() - self._tripped_at >= self.cooldown_s:
                self._probing = True
                return True
            return False

    def record_fault(self, seq: int) -> bool:
        """Count one device fault for batch ``seq``; True if it tripped."""
        trip_to = None
        with self._lock:
            now = self._clock()
            if self._probing:
                # probe failed: re-close and restart the cooldown
                self._probing = False
                self._tripped_at = now
            horizon = now - self.window_s
            self._strikes = [s for s in self._strikes if s[0] >= horizon]
            if not any(s[1] == seq for s in self._strikes):
                self._strikes.append((now, int(seq)))
            if len(self._strikes) >= self.threshold \
                    and self._level < FALLBACK:
                self._level += 1
                self._strikes = []
                self._tripped_at = now
                self.trips += 1
                trip_to = self._level
        if trip_to is not None and self.on_trip is not None:
            self.on_trip(trip_to)
        return trip_to is not None

    def record_success(self, chained: bool = False) -> None:
        """A dispatch drained clean; a CHAINED success closes the breaker."""
        restored = False
        with self._lock:
            if chained and self._level != CHAINED:
                self._level = CHAINED
                self._probing = False
                self._strikes = []
                self.restores += 1
                restored = True
        if restored and self.on_restore is not None:
            self.on_restore()

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "level": self._level,
                "levelName": BREAKER_LEVELS[self._level],
                "strikes": len(self._strikes),
                "probing": self._probing,
                "trips": self.trips,
                "restores": self.restores,
            }


class ShardBreakers:
    """Per-shard breaker bank for mesh dispatch: one :class:`DeviceBreaker`
    per mesh shard, so a sick chip demotes ITS shard without demoting the
    whole mesh.

    The fused chain is ONE SPMD program over every shard, so "demote a
    shard" cannot mean "run the program without it" — the mesh shape is
    fixed.  It means the dispatcher masks the demoted shard's batch rows
    out of the chained dispatch and side-routes them (single-step, or the
    CPU fallback once the shard's breaker reaches :data:`FALLBACK`),
    while the healthy shards keep the full 1/K host-sync economy.  The
    bank therefore answers two questions separately:

    - :meth:`allow_chain` — may a chained dispatch run at all?  True
      while ANY shard admits it (demoted shards ride masked); False only
      when every shard is demoted and cooling.
    - :meth:`demoted_shards` — which shards must be masked + side-routed
      right now.  A shard whose cooldown expired half-opens here: it is
      NOT reported demoted, so its rows rejoin the next chain as the
      probe, and :meth:`record_success` for the participating shards
      closes it (or a fault attributed back to it re-trips it).

    ``record_fault(seq, shard=None)`` strikes one shard when the fault
    is attributable (nonfinite rows land in a shard's batch segment) and
    every shard when it is not — an unattributable chain fault must not
    leave the tier un-guarded.  Callbacks carry the shard index:
    ``on_trip(shard, level)`` / ``on_restore(shard)``.
    """

    def __init__(self, n_shards: int, threshold: int = 3,
                 window_s: float = 60.0, cooldown_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic,
                 on_trip: Optional[Callable[[int, int], None]] = None,
                 on_restore: Optional[Callable[[int], None]] = None):
        self.n_shards = max(1, int(n_shards))
        self.on_trip = on_trip
        self.on_restore = on_restore
        self._shards = [
            DeviceBreaker(threshold, window_s, cooldown_s, clock,
                          on_trip=self._make_trip(s),
                          on_restore=self._make_restore(s))
            for s in range(self.n_shards)
        ]

    def _make_trip(self, shard: int) -> Callable[[int], None]:
        def fire(level: int, _shard=shard) -> None:
            if self.on_trip is not None:
                self.on_trip(_shard, level)
        return fire

    def _make_restore(self, shard: int) -> Callable[[], None]:
        def fire(_shard=shard) -> None:
            if self.on_restore is not None:
                self.on_restore(_shard)
        return fire

    @property
    def level(self) -> int:
        """Worst (most-demoted) shard level — the tier-wide summary."""
        return max(b.level for b in self._shards)

    @property
    def level_name(self) -> str:
        return BREAKER_LEVELS[self.level]

    @property
    def trips(self) -> int:
        return sum(b.trips for b in self._shards)

    @property
    def restores(self) -> int:
        return sum(b.restores for b in self._shards)

    def level_of(self, shard: int) -> int:
        return self._shards[shard].level

    def allow_chain(self) -> bool:
        """True while at least one shard admits chained dispatch (the
        others ride the chain masked, side-routed by the dispatcher)."""
        return any(b.allow_chain() for b in self._shards)

    def demoted_shards(self) -> tuple:
        """Shards the next chained dispatch must mask + side-route.
        Half-open probes are deliberately NOT demoted — their rows ride
        the chain as the probe."""
        return tuple(s for s, b in enumerate(self._shards)
                     if not b.allow_chain())

    def suspect_shards(self) -> tuple:
        """Shards with an elevated level OR live strikes — the best
        available attribution when something ELSE (the hung-step
        watchdog) needs to name a culprit."""
        return tuple(s for s, b in enumerate(self._shards)
                     if b.level != CHAINED or b._strikes)

    def record_fault(self, seq: int, shard: Optional[int] = None) -> bool:
        """Strike ``shard`` (or ALL shards when unattributable)."""
        if shard is not None:
            return self._shards[shard].record_fault(seq)
        tripped = False
        for b in self._shards:
            tripped = b.record_fault(seq) or tripped
        return tripped

    def record_success(self, chained: bool = False,
                       shards: Optional[object] = None,
                       masked: tuple = ()) -> None:
        """A dispatch drained clean for ``shards`` (None = all except
        ``masked``).  A chained success closes only the PARTICIPATING
        shards' breakers — a masked shard proved nothing."""
        if shards is None:
            shards = [s for s in range(self.n_shards) if s not in masked]
        for s in shards:
            self._shards[s].record_success(chained)

    def snapshot(self) -> dict:
        shards = [b.snapshot() for b in self._shards]
        return {
            "level": max(s["level"] for s in shards),
            "levelName": BREAKER_LEVELS[max(s["level"] for s in shards)],
            "strikes": sum(s["strikes"] for s in shards),
            "probing": any(s["probing"] for s in shards),
            "trips": sum(s["trips"] for s in shards),
            "restores": sum(s["restores"] for s in shards),
            "shards": shards,
        }


class DeviceWatchdog:
    """Budgeted wall-clock tracking of in-flight device dispatches.

    ``begin(records, parts)`` registers a dispatch (a ring of K plans
    passes ``parts=K``; each plan's egress calls :meth:`end` once) and
    returns a token; :meth:`check` — called from the dispatch loop's
    idle tick — compares the OLDEST live entry against the budgets:

    - past ``soft_s``: ``on_soft(records, elapsed_s)`` fires once per
      entry (flight-recorder anomaly with the in-flight slot records);
    - past ``hard_s``: the tier is marked :attr:`unhealthy` and
      ``on_unhealthy(records, elapsed_s)`` fires once per episode — the
      flag rides the heartbeat (rpc/health.py) so peers park forwards.

    The flag clears (``on_recovered``) when every tracked dispatch
    drains — a wedged chip that comes back needs no operator action.
    Budgets come from :meth:`calibrate` against the measured per-step
    latency, floored so a CPU test host never false-trips.
    """

    def __init__(self, soft_s: float = 1.0, hard_s: float = 10.0,
                 clock: Callable[[], float] = time.monotonic,
                 on_soft: Optional[Callable[[object, float], None]] = None,
                 on_unhealthy: Optional[Callable[[object, float], None]] = None,
                 on_recovered: Optional[Callable[[], None]] = None):
        self.soft_s = float(soft_s)
        self.hard_s = float(hard_s)
        self._clock = clock
        self.on_soft = on_soft
        self.on_unhealthy = on_unhealthy
        self.on_recovered = on_recovered
        self._lock = threading.Lock()
        self._entries: Dict[int, _Entry] = {}
        self._next_token = 0
        self._unhealthy = False
        self.soft_trips = 0
        self.hard_trips = 0

    @property
    def unhealthy(self) -> bool:
        return self._unhealthy

    def calibrate(self, stage_ms: float, *, soft_multiple: float = 50.0,
                  hard_multiple: float = 400.0, soft_floor_s: float = 0.25,
                  hard_floor_s: float = 2.0) -> None:
        """Derive budgets from the measured ``device.stage_ms``.

        Multiples are generous by design: the budgets exist to catch a
        WEDGED chip, not a slow batch — queueing, retrace, and host
        copies all legitimately stack on top of one stage time.
        """
        stage_s = max(0.0, float(stage_ms)) / 1000.0
        self.soft_s = max(float(soft_floor_s), stage_s * float(soft_multiple))
        self.hard_s = max(float(hard_floor_s), self.soft_s / max(
            float(soft_multiple), 1e-9) * float(hard_multiple))

    def begin(self, records, parts: int = 1) -> int:
        """Register one in-flight dispatch.  ``records`` is an OPAQUE
        payload handed back verbatim to ``on_soft``/``on_unhealthy`` —
        callers pass already-live objects (the plan, the ring's plan
        list) so the per-batch hot path allocates nothing here; the
        callback renders them only when a budget actually trips."""
        with self._lock:
            self._next_token += 1
            token = self._next_token
            self._entries[token] = _Entry(self._clock(), records, parts)
            return token

    def end(self, token: Optional[int]) -> None:
        if token is None:
            return
        recovered = False
        with self._lock:
            entry = self._entries.get(token)
            if entry is None:
                return
            entry.parts -= 1
            if entry.parts <= 0:
                del self._entries[token]
            if self._unhealthy and not self._entries:
                self._unhealthy = False
                recovered = True
        if recovered and self.on_recovered is not None:
            self.on_recovered()

    def check(self, now: Optional[float] = None) -> bool:
        """Evaluate budgets; returns the (possibly new) unhealthy flag."""
        soft_fire = None
        hard_fire = None
        with self._lock:
            if not self._entries:
                return self._unhealthy
            if now is None:
                now = self._clock()
            oldest = min(self._entries.values(), key=lambda e: e.started)
            elapsed = now - oldest.started
            if elapsed > self.soft_s and not oldest.soft_fired:
                oldest.soft_fired = True
                self.soft_trips += 1
                soft_fire = (oldest.records, elapsed)
            if elapsed > self.hard_s and not self._unhealthy:
                self._unhealthy = True
                self.hard_trips += 1
                hard_fire = (oldest.records, elapsed)
        if soft_fire is not None and self.on_soft is not None:
            self.on_soft(*soft_fire)
        if hard_fire is not None and self.on_unhealthy is not None:
            self.on_unhealthy(*hard_fire)
        return self._unhealthy

    def snapshot(self) -> dict:
        with self._lock:
            oldest_s = 0.0
            if self._entries:
                now = self._clock()
                oldest_s = now - min(e.started
                                     for e in self._entries.values())
            return {
                "inflight": len(self._entries),
                "oldestS": oldest_s,
                "softS": self.soft_s,
                "hardS": self.hard_s,
                "unhealthy": self._unhealthy,
                "softTrips": self.soft_trips,
                "hardTrips": self.hard_trips,
            }
