"""Host runtime kernel: lifecycle, config, metrics, logging.

Replaces the reference's L1/L2 layers (``sitewhere-core-lifecycle`` +
``sitewhere-microservice``) with a slim host runtime: hierarchical
lifecycle components, a typed config tree with env overrides (instead of
ZooKeeper XML), and in-process metrics (instead of Dropwizard+Kafka).
"""

from sitewhere_tpu.runtime.lifecycle import (  # noqa: F401
    LifecycleComponent,
    LifecycleState,
    LifecycleError,
)
from sitewhere_tpu.runtime.metrics import MetricsRegistry  # noqa: F401
from sitewhere_tpu.runtime.config import Config  # noqa: F401
