"""Span tracing: head + tail sampling, cross-boundary context propagation.

Reference: OpenTracing + Jaeger with a 1% probabilistic sampler
(``microservice/MicroserviceConfiguration.java:53-57``), spans around
lifecycle ops and gRPC client/server interceptors
(``grpc/client/common/tracing/ClientTracingInterceptor.java``).  The
pipeline here is one process per host, so spans are the host stages
wrapped around the one device program: batch assemble (batcher wait),
step dispatch, and each egress leg — plus the RPC legs when a trace
crosses hosts.

Two samplers compose:

- **Head sampling** (the Jaeger 1% analog): the decision is made ONCE at
  the trace root, so a sampled trace carries every stage span.  Sampled
  spans land in the finished ring as they close.
- **Tail sampling**: every *unsampled* trace still records its spans into
  a bounded pending buffer; when the trace ends (or is evicted), it is
  RETAINED if any span errored or the trace exceeded the latency
  threshold, and dropped otherwise.  The traces an operator actually
  needs — the failed and the slow — are therefore always kept, at a
  per-plan (never per-event) bookkeeping cost.

Cross-boundary propagation: :meth:`Trace.propagate` stamps the trace
context into a header dict (the RPC fabric's JSON headers lane,
``rpc/wire.py``) and :meth:`Tracer.join` continues it on the far side,
so one trace spans ingest → dispatch → seal → fan-out → remote delivery
with the same ``trace_id`` on every host.
"""

from __future__ import annotations

import collections
import random
import threading
import time
from typing import Dict, List, Optional

# Trace-context header keys (carried in the RPC frame's JSON headers
# lane next to authorization/tenant — see rpc/wire.py).
TRACE_ID_HEADER = "trace-id"
PARENT_ID_HEADER = "parent-id"
TRACE_SAMPLED_HEADER = "trace-sampled"

_ids = random.Random()
_ids_lock = threading.Lock()


def _new_id() -> str:
    with _ids_lock:
        return f"{_ids.getrandbits(64):016x}"


class _NoopSpan:
    """Unsampled: every operation is a no-op (hot-path cost ≈ one branch)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def tag(self, key: str, value) -> "_NoopSpan":
        return self

    @property
    def error(self):
        return None

    @error.setter
    def error(self, value) -> None:
        pass   # unsampled: discard (callers may flag failures uniformly)


_NOOP = _NoopSpan()


class Span:
    __slots__ = ("tracer", "trace_id", "span_id", "parent_id", "name",
                 "start_s", "duration_s", "tags", "error", "trace", "_t0")

    def __init__(self, tracer: "Tracer", trace_id: str, name: str,
                 parent_id: Optional[str] = None,
                 trace: Optional["Trace"] = None):
        self.tracer = tracer
        self.trace_id = trace_id
        self.span_id = _new_id()
        self.parent_id = parent_id
        self.name = name
        self.start_s = time.time()
        self.duration_s: Optional[float] = None
        self.tags: Dict[str, object] = {}
        self.error: Optional[str] = None
        self.trace = trace

    def tag(self, key: str, value) -> "Span":
        self.tags[key] = value
        return self

    def __enter__(self) -> "Span":
        self._t0 = time.perf_counter()  # type: ignore[attr-defined]
        return self

    def __exit__(self, exc_type, exc, tb):
        self.duration_s = time.perf_counter() - self._t0  # type: ignore
        if exc is not None:
            self.error = f"{exc_type.__name__}: {exc}"
        self.tracer._finish(self)
        return False

    def to_dict(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "start_s": round(self.start_s, 6),
            "duration_ms": (round(self.duration_s * 1e3, 3)
                            if self.duration_s is not None else None),
            "tags": self.tags,
            "error": self.error,
        }


class Trace:
    """A live trace handle: spawn child spans under one trace id.

    ``sampled=True`` means retention is already decided (head-sampled
    here, or upstream on the propagating side): spans flush straight to
    the finished ring.  ``sampled=False`` means the trace is a
    tail-sampling candidate: spans buffer until :meth:`end` decides.
    """

    __slots__ = ("tracer", "trace_id", "root_id", "sampled", "decided")

    def __init__(self, tracer: "Tracer", trace_id: str,
                 root_id: Optional[str], sampled: bool = True):
        self.tracer = tracer
        self.trace_id = trace_id
        self.root_id = root_id
        self.sampled = sampled
        # retention state: head-sampled traces are born decided; tail
        # candidates flip decided (and maybe sampled) at end()/eviction
        # — guarded by the tracer's lock, so a late async span can
        # never re-open a decided trace's pending entry
        self.decided = sampled

    def span(self, name: str, parent: Optional[Span] = None):
        return Span(self.tracer, self.trace_id, name,
                    parent_id=(parent.span_id if isinstance(parent, Span)
                               else self.root_id),
                    trace=self)

    def record(self, name: str, duration_s: float, **tags) -> None:
        """Record an already-measured stage (e.g. batcher wait) as a span."""
        span = Span(self.tracer, self.trace_id, name, parent_id=self.root_id,
                    trace=self)
        span.start_s = time.time() - duration_s
        span.duration_s = duration_s
        span.tags.update(tags)
        self.tracer._finish(span)

    def propagate(self, headers: Dict[str, str],
                  parent: Optional[Span] = None) -> Dict[str, str]:
        """Stamp the trace context into ``headers`` (in place) so the
        receiving side can :meth:`Tracer.join` it.  ``parent`` names the
        client-side span the remote spans should hang off."""
        headers[TRACE_ID_HEADER] = self.trace_id
        parent_id = (parent.span_id if isinstance(parent, Span)
                     else self.root_id)
        if parent_id:
            headers[PARENT_ID_HEADER] = parent_id
        headers[TRACE_SAMPLED_HEADER] = "1" if self.sampled else "0"
        return headers

    def end(self) -> None:
        """Close the trace: applies the tail-sampling retention decision
        for pending traces (no-op for head-sampled ones).  Safe to call
        once per trace from the side that created it; spans finished
        AFTER end() (async egress legs) go straight to the ring when the
        trace was retained and are discarded when it was dropped — they
        never re-open the pending entry.  Exception: a late ERRORED span
        (with ``tail_errors`` on) re-opens retention, so an async
        delivery failure is never invisible."""
        self.tracer._end_trace(self)


class _NoopTrace:
    __slots__ = ()

    trace_id = None
    sampled = False
    decided = True

    def span(self, name: str, parent=None):
        return _NOOP

    def record(self, name: str, duration_s: float, **tags) -> None:
        pass

    def propagate(self, headers: Dict[str, str], parent=None) -> Dict[str, str]:
        return headers

    def end(self) -> None:
        pass


_NOOP_TRACE = _NoopTrace()


class _PendingTrace:
    __slots__ = ("spans", "started")

    def __init__(self):
        self.spans: List[Span] = []
        self.started = time.monotonic()


class Tracer:
    """Head + tail sampling tracer with a bounded finished-span ring.

    - ``sample_rate``: probabilistic head sampler (decision per trace).
    - ``tail_errors``: retain any unsampled trace with an errored span.
    - ``tail_latency_s``: retain any unsampled trace whose span extent
      meets/exceeds this many seconds (``None`` disables the check).
    - ``pending_capacity``: bound on concurrently-pending (undecided)
      traces; the oldest is evicted-and-decided when exceeded, so an
      abandoned trace can never leak.

    With both tail knobs off (the default), unsampled traces cost one
    branch — exactly the old head-only behavior.
    """

    def __init__(self, sample_rate: float = 0.01, capacity: int = 2048,
                 tail_latency_s: Optional[float] = None,
                 tail_errors: bool = False,
                 pending_capacity: int = 512,
                 tail_anomaly_window_s: float = 30.0,
                 seed: int = 0xC0FFEE):
        self.sample_rate = float(sample_rate)
        self.tail_latency_s = tail_latency_s
        self.tail_errors = bool(tail_errors)
        self.pending_capacity = int(pending_capacity)
        # anomaly-overlap retention: note_anomaly() stamps a moment
        # (an overload state transition, an SLO alert); any tail-
        # candidate trace whose span extent overlaps the window
        # [stamp, stamp + tail_anomaly_window_s] is retained regardless
        # of the error/latency rules — the traces surrounding a state
        # transition are exactly the forensic record an operator needs,
        # and before this only errored/slow traces were guaranteed.
        self.tail_anomaly_window_s = float(tail_anomaly_window_s)
        self._anomalies: collections.deque = collections.deque(maxlen=64)
        self.anomalies_noted = 0
        self.retained_anomaly = 0
        self._spans: collections.deque = collections.deque(maxlen=capacity)
        self._pending: "collections.OrderedDict[str, _PendingTrace]" = \
            collections.OrderedDict()
        self._lock = threading.Lock()
        self._rng = random.Random(seed)
        self.started = 0
        self.sampled = 0
        self.joined = 0
        self.retained_tail = 0
        self.dropped_tail = 0

    @property
    def _tail_enabled(self) -> bool:
        return (self.tail_errors or self.tail_latency_s is not None
                or bool(self._anomalies))

    def note_anomaly(self, ts: Optional[float] = None) -> None:
        """Stamp an anomaly moment (wall-clock ``time.time`` space, the
        same clock spans carry): tail candidates overlapping the
        retention window from this stamp are ALWAYS kept.  Called by the
        overload controller on every state transition and by the SLO
        burn engine on alert."""
        with self._lock:
            self._anomalies.append(time.time() if ts is None else ts)
            self.anomalies_noted += 1

    def trace(self, name: str):
        """Trace root: head-sample, else tail-candidate, else no-op.

        The head decision is per-trace (reference: Jaeger probabilistic
        1%, ``MicroserviceConfiguration.java:55``) so sampled traces
        carry every stage span; with tail sampling on, unsampled traces
        still buffer pending the end-of-trace retention decision.
        """
        self.started += 1
        if self._rng.random() < self.sample_rate:
            self.sampled += 1
            return Trace(self, _new_id(), None, sampled=True)
        if not self._tail_enabled:
            return _NOOP_TRACE
        return Trace(self, _new_id(), None, sampled=False)

    def join(self, headers: Optional[Dict[str, str]]):
        """Continue a propagated trace from ``headers``; None when no
        trace context rides them.  The upstream head decision carries
        over; tail candidates are decided locally too, so an error on
        EITHER side of the boundary retains that side's spans."""
        if not headers:
            return None
        trace_id = headers.get(TRACE_ID_HEADER)
        if not trace_id:
            return None
        self.joined += 1
        sampled = headers.get(TRACE_SAMPLED_HEADER) == "1"
        if not sampled and not self._tail_enabled:
            return _NOOP_TRACE
        return Trace(self, str(trace_id),
                     headers.get(PARENT_ID_HEADER) or None, sampled=sampled)

    # -- span / trace completion --------------------------------------------

    def _finish(self, span: Span) -> None:
        trace = span.trace
        if trace is None or trace.sampled:
            with self._lock:
                self._spans.append(span)
            return
        with self._lock:
            if trace.sampled:
                # retention decided between the check above and the lock
                self._spans.append(span)
                return
            if trace.decided:
                # decided-and-dropped: late async spans drop too — EXCEPT
                # an errored one (an outbound worker failing after the
                # plan's drop decision): the error guarantee must hold
                # for async legs, so retention re-opens from this span
                # on (the pre-decision clean spans are already gone)
                if self.tail_errors and span.error:
                    trace.sampled = True
                    self._spans.append(span)
                    self.retained_tail += 1
                    self.dropped_tail -= 1
                return
            entry = self._pending.get(trace.trace_id)
            if entry is None:
                entry = self._pending[trace.trace_id] = _PendingTrace()
                if len(self._pending) > self.pending_capacity:
                    # abandoned trace (owner crashed before end()):
                    # decide now so its error spans still survive
                    _, evicted = self._pending.popitem(last=False)
                    self._decide_locked(evicted)
            entry.spans.append(span)

    def _end_trace(self, trace: Trace) -> None:
        with self._lock:
            if trace.decided:
                return
            entry = self._pending.pop(trace.trace_id, None)
            if entry is None:
                # nothing buffered — nothing kept; still COUNTED as a
                # drop so a late errored span's re-open (retained += 1,
                # dropped -= 1) can never push dropped_tail negative
                trace.decided = True
                self.dropped_tail += 1
                return
            # late spans (async egress legs, outbound workers) of a
            # retained trace go straight to the ring; of a dropped one
            # they are discarded — either way, never re-pended
            trace.sampled = self._decide_locked(entry)
            trace.decided = True

    def _decide_locked(self, entry: _PendingTrace) -> bool:
        """Apply the tail retention rule to one pending trace and mark
        its handle decided.  Caller holds ``_lock``."""
        spans = entry.spans
        keep = self.tail_errors and any(s.error for s in spans)
        if not keep and spans and (self.tail_latency_s is not None
                                   or self._anomalies):
            starts = [s.start_s for s in spans]
            ends = [s.start_s + (s.duration_s or 0.0) for s in spans]
            if self.tail_latency_s is not None:
                keep = (max(ends) - min(starts)) >= self.tail_latency_s
            if not keep and self._anomalies:
                # expire stamps whose retention window closed long ago
                horizon = time.time() - 2 * self.tail_anomaly_window_s
                while self._anomalies and self._anomalies[0] < horizon:
                    self._anomalies.popleft()
                # overlap: the trace's span extent intersects
                # [stamp, stamp + window] for any noted anomaly
                window = self.tail_anomaly_window_s
                if any(min(starts) <= ts + window and max(ends) >= ts
                       for ts in self._anomalies):
                    keep = True
                    self.retained_anomaly += 1
        if keep:
            self._spans.extend(spans)
            self.retained_tail += 1
        else:
            self.dropped_tail += 1
        if spans and spans[0].trace is not None:
            spans[0].trace.sampled = keep
            spans[0].trace.decided = True
        return keep

    # -- read side ------------------------------------------------------------

    def recent(self, limit: int = 100) -> List[dict]:
        with self._lock:
            spans = list(self._spans)[-limit:]
        return [s.to_dict() for s in spans]

    def stats(self) -> dict:
        with self._lock:
            buffered = len(self._spans)
            pending = len(self._pending)
        return {
            "sample_rate": self.sample_rate,
            "traces_started": self.started,
            "traces_sampled": self.sampled,
            "traces_joined": self.joined,
            "traces_retained_tail": self.retained_tail,
            "traces_retained_anomaly": self.retained_anomaly,
            "traces_dropped_tail": self.dropped_tail,
            "traces_pending": pending,
            "spans_buffered": buffered,
            "anomalies_noted": self.anomalies_noted,
        }
