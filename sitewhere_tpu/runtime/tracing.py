"""Minimal span tracing with probabilistic sampling.

Reference: OpenTracing + Jaeger with a 1% probabilistic sampler
(``microservice/MicroserviceConfiguration.java:53-57``), spans around
lifecycle ops and gRPC client/server interceptors
(``grpc/client/common/tracing/ClientTracingInterceptor.java``).  The
pipeline here is one process, so "distributed" tracing collapses to
per-plan traces whose spans are the host stages wrapped around the one
device program: batch assemble (batcher wait), step dispatch, and each
egress leg.  Finished spans land in a bounded ring the REST surface
exposes; the sampling decision is made ONCE per trace so a sampled trace
is always complete.
"""

from __future__ import annotations

import collections
import random
import threading
import time
from typing import Dict, List, Optional

_ids = random.Random()
_ids_lock = threading.Lock()


def _new_id() -> str:
    with _ids_lock:
        return f"{_ids.getrandbits(64):016x}"


class _NoopSpan:
    """Unsampled: every operation is a no-op (hot-path cost ≈ one branch)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def tag(self, key: str, value) -> "_NoopSpan":
        return self


_NOOP = _NoopSpan()


class Span:
    __slots__ = ("tracer", "trace_id", "span_id", "parent_id", "name",
                 "start_s", "duration_s", "tags", "error", "_t0")

    def __init__(self, tracer: "Tracer", trace_id: str, name: str,
                 parent_id: Optional[str] = None):
        self.tracer = tracer
        self.trace_id = trace_id
        self.span_id = _new_id()
        self.parent_id = parent_id
        self.name = name
        self.start_s = time.time()
        self.duration_s: Optional[float] = None
        self.tags: Dict[str, object] = {}
        self.error: Optional[str] = None

    def tag(self, key: str, value) -> "Span":
        self.tags[key] = value
        return self

    def __enter__(self) -> "Span":
        self._t0 = time.perf_counter()  # type: ignore[attr-defined]
        return self

    def __exit__(self, exc_type, exc, tb):
        self.duration_s = time.perf_counter() - self._t0  # type: ignore
        if exc is not None:
            self.error = f"{exc_type.__name__}: {exc}"
        self.tracer._finish(self)
        return False

    def to_dict(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "start_s": round(self.start_s, 6),
            "duration_ms": (round(self.duration_s * 1e3, 3)
                            if self.duration_s is not None else None),
            "tags": self.tags,
            "error": self.error,
        }


class Trace:
    """A sampled trace handle: spawn child spans under one trace id."""

    __slots__ = ("tracer", "trace_id", "root_id")

    def __init__(self, tracer: "Tracer", trace_id: str,
                 root_id: Optional[str]):
        self.tracer = tracer
        self.trace_id = trace_id
        self.root_id = root_id

    def span(self, name: str, parent: Optional[Span] = None):
        return Span(self.tracer, self.trace_id, name,
                    parent_id=(parent.span_id if isinstance(parent, Span)
                               else self.root_id))

    def record(self, name: str, duration_s: float, **tags) -> None:
        """Record an already-measured stage (e.g. batcher wait) as a span."""
        span = Span(self.tracer, self.trace_id, name, parent_id=self.root_id)
        span.start_s = time.time() - duration_s
        span.duration_s = duration_s
        span.tags.update(tags)
        self.tracer._finish(span)


class _NoopTrace:
    __slots__ = ()

    def span(self, name: str, parent=None):
        return _NOOP

    def record(self, name: str, duration_s: float, **tags) -> None:
        pass


_NOOP_TRACE = _NoopTrace()


class Tracer:
    """Probabilistic head-sampling tracer with a bounded finished-span ring."""

    def __init__(self, sample_rate: float = 0.01, capacity: int = 2048):
        self.sample_rate = float(sample_rate)
        self._spans: collections.deque = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._rng = random.Random(0xC0FFEE)
        self.started = 0
        self.sampled = 0

    def trace(self, name: str):
        """Head-sampled trace root: returns a live or no-op trace handle.

        The decision is per-trace (reference: Jaeger probabilistic 1%,
        ``MicroserviceConfiguration.java:55``) so sampled traces carry
        every stage span.
        """
        self.started += 1
        if self._rng.random() >= self.sample_rate:
            return _NOOP_TRACE
        self.sampled += 1
        return Trace(self, _new_id(), None)

    def _finish(self, span: Span) -> None:
        with self._lock:
            self._spans.append(span)

    def recent(self, limit: int = 100) -> List[dict]:
        with self._lock:
            spans = list(self._spans)[-limit:]
        return [s.to_dict() for s in spans]

    def stats(self) -> dict:
        with self._lock:
            buffered = len(self._spans)
        return {
            "sample_rate": self.sample_rate,
            "traces_started": self.started,
            "traces_sampled": self.sampled,
            "spans_buffered": buffered,
        }
