"""Instance assembly + bootstrap — the application shell.

Reference: ``service-instance-management`` bootstraps a SiteWhere instance:
it writes the instance template configuration into ZooKeeper, runs Groovy
user/tenant model initializers, and sets a bootstrapped marker so init is
idempotent (``microservice/InstanceManagementMicroservice.java``,
``templates/InstanceTemplateManager.java``,
``initializer/GroovyUserModelInitializer.java``, marker logic
``Microservice.java:516-518``).  The other 18 services then assemble
themselves around that config.

Here the whole platform runs as ONE process around one device mesh, so
this module is both: the bootstrap (templates → users/tenants/datasets,
idempotent via a marker file in the data dir) and the composition root
(:class:`Instance`) that wires every component — identity, device
management, event store, state, rules, dispatcher, ingest, outbound,
commands, streams, labels — into a single lifecycle tree.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
from typing import Callable, Dict, List, Optional

from sitewhere_tpu.commands.model import CommandInvocation
from sitewhere_tpu.commands.processing import CommandProcessor
from sitewhere_tpu.ids import NULL_ID, IdentityMap
from sitewhere_tpu.ingest.batcher import Batcher
from sitewhere_tpu.ingest.journal import Journal, JournalReader
from sitewhere_tpu.labels.manager import LabelGeneratorManager
from sitewhere_tpu.outbound.manager import OutboundConnectorsManager
from sitewhere_tpu.pipeline.rules import RuleManager
from sitewhere_tpu.runtime.config import Config
from sitewhere_tpu.runtime.dispatcher import PipelineDispatcher
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent
from sitewhere_tpu.security.jwt import TokenManagement
from sitewhere_tpu.security.users import UserManagement
from sitewhere_tpu.services.assets import AssetManagement
from sitewhere_tpu.services.batch_ops import BatchOperationManager
from sitewhere_tpu.services.device_management import DeviceManagement, RegistryMirror
from sitewhere_tpu.store.segmented import SegmentStore
from sitewhere_tpu.services.registration import RegistrationManager
from sitewhere_tpu.services.schedules import ScheduleManager
from sitewhere_tpu.services.streams import DeviceStreamManagement, DeviceStreamManager
from sitewhere_tpu.services.tenants import (
    MultitenantEngineManager,
    TenantEngine,
    TenantManagement,
)
from sitewhere_tpu.state.manager import DeviceStateManager
from sitewhere_tpu.state.presence import PresenceManager

logger = logging.getLogger("sitewhere_tpu.instance")


@dataclasses.dataclass
class InstanceTemplate:
    """Bootstrap template (reference instance templates: default users,
    tenants, and scripted dataset initializers — Python callables instead
    of Groovy scripts)."""

    template_id: str = "default"
    users: List[Dict[str, object]] = dataclasses.field(
        default_factory=lambda: [
            {
                "username": "admin",
                "password": "password",
                "first_name": "Admin",
                "last_name": "User",
                "authorities": ["ROLE_ADMIN"],
            }
        ]
    )
    tenants: List[Dict[str, object]] = dataclasses.field(
        default_factory=lambda: [
            {"token": "default", "name": "Default Tenant",
             "auth_token": "sitewhere1234567890"}
        ]
    )
    # dataset initializers run once per instance with the Instance as arg
    # (GroovyDeviceModelInitializer analog)
    dataset_initializers: List[Callable[["Instance"], None]] = dataclasses.field(
        default_factory=list
    )


class Instance(LifecycleComponent):
    """The composition root: one configured SiteWhere-TPU instance."""

    def __init__(self, config: Optional[Config] = None,
                 template: Optional[InstanceTemplate] = None,
                 recovery_decoder=None):
        super().__init__("instance")
        self.config = config or Config()
        self.template = template or InstanceTemplate()
        self.instance_id = self.config["instance.id"]
        self.data_dir = os.path.abspath(self.config["instance.data_dir"])
        os.makedirs(self.data_dir, exist_ok=True)

        cap = int(self.config["pipeline.registry_capacity"])
        width = int(self.config["pipeline.width"])
        n_shards = int(self.config["pipeline.n_shards"])

        # Multi-chip: one (shard, model) mesh over the visible devices; the
        # dispatcher runs the shard_map step and the batcher routes rows to
        # the owning shard (Kafka partitioning analog, SURVEY.md §2.4).
        if n_shards > 1:
            from sitewhere_tpu.parallel.mesh import make_mesh

            self.mesh = make_mesh(n_devices=n_shards)
        else:
            self.mesh = None

        # identity + security (a shared jwt secret lets peer hosts verify
        # each other's service tokens — reference: one instance-wide JWT
        # secret across all microservices)
        self.identity = IdentityMap(capacity=cap)
        self.users = UserManagement()
        jwt_secret = self.config.get("security.jwt_secret")
        self.tokens = TokenManagement(
            secret=jwt_secret.encode("utf-8") if jwt_secret else None)
        self.tenants = TenantManagement()

        # device system-of-record + device-resident mirrors
        self.mirror = RegistryMirror(capacity=cap)
        self.device_management = DeviceManagement(
            "default", self.identity, self.mirror
        )
        from sitewhere_tpu.schema import DEFAULT_EWMA_HALFLIVES_S

        ewma_halflives = tuple(self.config.get(
            "pipeline.ewma_halflives_s", DEFAULT_EWMA_HALFLIVES_S))
        self.rules = RuleManager(self.identity,
                                 ewma_halflives_s=ewma_halflives)
        self.device_state = self.add_child(DeviceStateManager(
            cap, self.identity,
            num_mtype_slots=int(self.config["pipeline.mtype_slots"]),
            tenant_id_of_device=self._tenant_ids_of_devices,
            num_ewma_scales=len(ewma_halflives),
        ))

        # instance-scoped metrics registry (the .prom exposition surface;
        # cross-cutting counters stay in metrics.global_registry()) —
        # created before the durable stores so the segment store's
        # store.* family registers here, not in the process-global one
        from sitewhere_tpu.runtime.metrics import MetricsRegistry

        self.metrics = MetricsRegistry()

        # durable stores — the log-structured sharded segment store
        # (sitewhere_tpu/store): parallel background seal off the hot
        # path, catalog-governed retention/compaction, packed hot tier.
        # On a mesh, segment shards key to MESH shards (the registry
        # block owning each device) instead of the tenant/device hash,
        # so one egress segment's columns append into one shard buffer —
        # they never scatter across store shards host-side.
        if self.mesh is not None:
            import numpy as np

            _rows_per_shard = max(1, cap // n_shards)

            def _mesh_store_key(dev, ten, _r=_rows_per_shard, _np=np):
                return _np.asarray(dev, _np.int64) // _r

            store_shard_key = _mesh_store_key
        else:
            store_shard_key = None
        self.event_store = self.add_child(SegmentStore(
            self.data_dir,
            flush_interval_s=0.25,
            retention_s=self.config.get("events.retention_s"),
            resident_bytes=int(self.config["events.resident_bytes"]),
            n_shards=(n_shards if self.mesh is not None
                      else int(self.config["events.shards"])),
            shard_key=store_shard_key,
            seal_workers=int(self.config["events.seal_workers"]),
            hot_bytes=int(self.config["events.hot_bytes"]),
            compact_interval_s=float(
                self.config["events.compact_interval_s"]),
            metrics=self.metrics,
        ))
        self.streams = self.add_child(DeviceStreamManagement(self.data_dir))
        self.stream_manager = self.add_child(DeviceStreamManager(
            self.device_management, self.streams
        ))
        self.labels = self.add_child(LabelGeneratorManager())
        self.ingest_journal = Journal(
            self.data_dir, name="ingest",
            fsync_every=int(self.config["journal.fsync_every"]),
            segment_bytes=int(self.config["journal.segment_bytes"]),
        )
        self.dead_letters = Journal(self.data_dir, name="dead-letters")
        # terminal seal failures dead-letter instead of pinning memory /
        # blocking the commit gate forever (EventStore.flush contract)
        self.event_store.dead_letters = self.dead_letters

        # span tracing: probabilistic head sampler (reference: Jaeger 1%,
        # MicroserviceConfiguration.java:53-57) PLUS tail-based retention —
        # traces with an errored span or end-to-end latency over the
        # threshold are ALWAYS kept, so the failed and the slow are
        # inspectable even at a 1% head rate
        from sitewhere_tpu.runtime.tracing import Tracer

        tail_ms = self.config.get("tracing.tail_latency_ms", 100.0)
        self.tracer = Tracer(
            sample_rate=float(self.config.get("tracing.sample_rate", 0.01)),
            tail_errors=bool(self.config.get("tracing.tail_errors", True)),
            tail_latency_s=(float(tail_ms) / 1e3
                            if tail_ms is not None else None),
            pending_capacity=int(
                self.config.get("tracing.pending_capacity", 512)))
        # runtime-uploadable scripts (ScriptSynchronizer analog)
        from sitewhere_tpu.runtime.scripting import ScriptManager

        self.scripts = ScriptManager(self.data_dir)

        # Flight recorder (runtime/flightrec.py): always-on bounded ring
        # of per-batch records, snapshotted to JSONL on anomaly (SLO
        # burn alert, egress crash, overload transition, supervisor
        # restart) and served at /api/instance/flightrecorder.
        self.flightrec = None
        if bool(self.config.get("flightrec.enabled", True)):
            from sitewhere_tpu.runtime.flightrec import FlightRecorder

            self.flightrec = FlightRecorder(
                data_dir=self.data_dir,
                capacity=int(self.config.get("flightrec.capacity", 2048)),
                min_snapshot_interval_s=float(self.config.get(
                    "flightrec.min_snapshot_interval_s", 5.0)),
                max_snapshots=int(self.config.get(
                    "flightrec.max_snapshots", 32)),
                metrics=self.metrics,
            )

        # SLO burn-rate engine (runtime/metrics.py BurnRateEngine):
        # multi-window burn evaluation against the BASELINE.json targets
        # (1M ev/s throughput, <10ms p99, shed rate), ticked by the
        # dispatcher loop; alerts emit slo.burn spans + dump the flight
        # recorder.  slo.throughput_eps=0 disables that objective (e.g.
        # a CPU-fallback deployment that can never meet the TPU number).
        self.slo = None
        if bool(self.config.get("slo.enabled", True)):
            from sitewhere_tpu.runtime.metrics import (
                BurnRateEngine,
                SloTargets,
            )

            self.slo = BurnRateEngine(
                targets=SloTargets(
                    throughput_eps=float(self.config.get(
                        "slo.throughput_eps", 1_000_000.0)),
                    p99_ms=float(self.config.get("slo.p99_ms", 10.0)),
                    shed_rate=float(self.config.get(
                        "slo.shed_rate", 0.01))),
                windows_s=(float(self.config.get("slo.fast_window_s",
                                                 60.0)),
                           float(self.config.get("slo.slow_window_s",
                                                 600.0))),
                error_budget=float(self.config.get(
                    "slo.error_budget", 0.05)),
                alert_burn=float(self.config.get("slo.alert_burn", 2.0)),
                min_samples=int(self.config.get("slo.min_samples", 5)),
                lag_tolerance_s=float(self.config.get(
                    "slo.lag_tolerance_s", 2.0)),
                sample_interval_s=float(self.config.get(
                    "slo.sample_interval_s", 1.0)),
                sample_fn=self._slo_sample,
                metrics=self.metrics,
                tracer=self.tracer,
                on_alert=self._on_slo_alert,
            )
        self._slo_last = {"processed": 0, "shed": 0, "admitted": 0,
                          "at": None}
        import threading as _threading

        # serializes the jax.profiler start/stop check-then-act pair
        self._profiler_lock = _threading.Lock()
        self._profiler_dir: Optional[str] = None

        # Overload control (runtime/overload.py): a watermark-driven
        # state machine over signals the pipeline already exports.  The
        # dispatcher ticks it every loop cycle; admission at ingest and
        # the degradation ladder (labels, analytics/search endpoints,
        # non-priority outbound fan-out) hang off its state.  Journal
        # append + seal + checkpoint are NEVER gated by it.
        self.overload = None
        if bool(self.config.get("overload.enabled", True)):
            from sitewhere_tpu.runtime.overload import (
                OverloadController,
                Watermarks,
            )

            from sitewhere_tpu.runtime.overload import TenantBudgets

            self.overload = OverloadController(
                watermarks=Watermarks().replace(
                    self.config.get("overload.watermarks") or {}),
                cooldown_s=float(self.config.get("overload.cooldown_s", 2.0)),
                hysteresis=float(self.config.get("overload.hysteresis", 0.7)),
                confirm_samples=int(self.config.get(
                    "overload.confirm_samples", 2)),
                sample_interval_s=float(self.config.get(
                    "overload.sample_interval_s", 0.1)),
                retry_after_s=float(self.config.get(
                    "overload.retry_after_s", 1.0)),
                degraded_telemetry_rate_per_s=float(self.config.get(
                    "overload.degraded_telemetry_rate_per_s", 10_000.0)),
                degraded_telemetry_burst=float(self.config.get(
                    "overload.degraded_telemetry_burst", 20_000.0)),
                budget_refresh_s=float(self.config.get(
                    "overload.budget_refresh_s", 5.0)),
                signals_fn=self._overload_signals,
                metrics=self.metrics,
                tracer=self.tracer,
            )
            # per-tenant budget overlays (tenants.<token>.overload.*):
            # configured ceilings that compose with — never replace —
            # the ledger's measured-share scaling (min of the two)
            self.overload.set_tenant_budgets(
                TenantBudgets.from_config(self.config.get("tenants")))
            self.labels.load_gate = self.overload.allow_optional
            if self.flightrec is not None:
                # every ladder move dumps the recorder: the batches
                # surrounding a transition are the evidence items 1-2
                # of the roadmap tune against
                self.overload.on_transition(
                    lambda old, new, signals: self._flightrec_dump_async(
                        f"overload-{new.name.lower()}",
                        f"{old.name}->{new.name}"))

        # Tenant metering plane (runtime/metering.py): sliding-window
        # per-tenant usage ledger fed by (a) the packed step's tenant
        # scatter block — riding the existing D2H fetch, zero extra
        # syncs — and (b) host-side charges from shed/dead-letter/seal/
        # outbound/analytics paths.  Feeds measured share back into the
        # overload ladder's DEGRADED per-tenant rate limits and exports
        # the governed ``tenant.*`` metric family.
        self.usage_ledger = None
        if bool(self.config.get("metering.enabled", True)):
            from sitewhere_tpu.runtime.metering import UsageLedger

            self.usage_ledger = UsageLedger(
                top_k=int(self.config.get("metering.top_k", 32)),
                window_s=float(self.config.get("metering.window_s", 60.0)),
                fair_share_frac=float(self.config.get(
                    "metering.fair_share_frac", 0.25)),
                min_rate_frac=float(self.config.get(
                    "metering.min_rate_frac", 0.1)),
            )
            self.usage_ledger.bind_metrics(
                self.metrics, resolve=self.identity.tenant.token_of)
            if self.overload is not None:
                self.overload.set_usage_ledger(
                    self.usage_ledger, resolve=self._tenant_dense_id)
            self.event_store.usage_ledger = self.usage_ledger

        # Metered quotas (runtime/metering.py QuotaTable): per-tenant
        # rule/analytics eval-seconds budgets over the ledger's sliding
        # window — deprioritize (live rows skipped) then refuse (429)
        # as the window fills; NEVER consulted on the ingest hot path.
        self.quotas = None
        if self.usage_ledger is not None and bool(self.config.get(
                "metering.quota.enabled", True)):
            from sitewhere_tpu.runtime.metering import QuotaTable

            self.quotas = QuotaTable(
                self.usage_ledger,
                default_eval_s=self.config.get(
                    "metering.quota.eval_s_per_window"),
                soft_frac=float(self.config.get(
                    "metering.quota.soft_frac", 0.8)),
                metrics=self.metrics,
            )
            tenants_cfg = self.config.get("tenants")
            if isinstance(tenants_cfg, dict):
                for tok, overlay in tenants_cfg.items():
                    quota = (overlay.get("quota")
                             if isinstance(overlay, dict) else None)
                    if isinstance(quota, dict) \
                            and "eval_s_per_window" in quota:
                        self.quotas.set_quota(
                            self._tenant_dense_id(str(tok)),
                            float(quota["eval_s_per_window"]))

        # Tenant-partitioned device-state views (state/manager.py
        # TenantPartitions): pow2 rung ladders per tenant over the
        # registry mirror's tenant column, so one tenant's registration
        # churn resizes/recompiles only its own partition view
        _mirror = self.mirror

        def _tenant_column():
            import numpy as np

            return np.where(_mirror.active, _mirror.tenant_id, NULL_ID)

        self.device_state.attach_partitions(
            _tenant_column,
            min_capacity=int(self.config.get(
                "state.partition_min_capacity", 64)),
            metrics=self.metrics)

        # domain services the dispatcher egresses into — registered as
        # children BEFORE it so the reverse-order stop keeps them alive
        # through the dispatcher's shutdown flush
        self.assets = AssetManagement("default", self.identity)
        self.commands = self.add_child(CommandProcessor(
            self.device_management,
            on_undelivered=self._on_undelivered_command,
            metrics=self.metrics,
        ))
        self.batch_ops = self.add_child(BatchOperationManager(
            self.device_management, self.commands,
            throttle_delay_ms=int(self.config.get(
                "batch.throttle_delay_ms", 0)),
        ))
        self.schedules = self.add_child(ScheduleManager(executors={
            "CommandInvocation": self._run_scheduled_invocation,
            "BatchCommandInvocation": self._run_scheduled_batch,
        }))
        # per-tenant engine lifecycle over the SHARED tensors (reference:
        # MultitenantMicroservice.java:242-260,358-380 — engine per tenant,
        # independent restart); engines share the instance identity map so
        # their dense tenant ids match the pipeline's tenant column
        self.engines = self.add_child(MultitenantEngineManager(
            self.tenants,
            engine_factory=self._make_tenant_engine,
            tenant_ids=self.identity,
        ))
        self.outbound = self.add_child(
            OutboundConnectorsManager(metrics=self.metrics,
                                      overload=self.overload))
        self.outbound.usage_ledger = self.usage_ledger
        # Streaming analytics & CEP (analytics/ subsystem): registered
        # Window/Session/Pattern queries compile once and run BOTH on
        # the live enriched batches (dispatcher egress offers them to
        # the runner's worker; sheds from SHEDDING as a non-priority
        # consumer) and retrospectively over the sealed event store
        # (REST-gated from DEGRADED like the other analytics surfaces).
        # Added before the dispatcher so the reverse-order stop keeps it
        # alive through the dispatcher's shutdown flush.
        self.analytics = None
        if bool(self.config.get("analytics.enabled", True)):
            from sitewhere_tpu.analytics.runner import QueryRunner

            self.analytics = self.add_child(QueryRunner(
                capacity=cap,
                resolve_mtype=self.identity.mtype.mint,
                event_store=self.event_store,
                outbound=self.outbound,
                overload=self.overload,
                metrics=self.metrics,
                tracer=self.tracer,
                max_queries=int(self.config.get(
                    "analytics.max_queries", 32)),
                max_matches=int(self.config.get(
                    "analytics.max_matches", 1024)),
                queue_depth=int(self.config.get(
                    "analytics.queue_depth", 64)),
                fanout_matches=bool(self.config.get(
                    "analytics.fanout_matches", True)),
            ))
            self.analytics.usage_ledger = self.usage_ledger
            self.analytics.quotas = self.quotas
        # Bring-your-own-rules (rules/ subsystem): per-tenant declarative
        # rule & enrichment programs compiled into per-structure batched
        # kernels.  Same egress-offer lifecycle as analytics — added
        # before the dispatcher so the reverse-order stop keeps the
        # engine draining through the dispatcher's shutdown flush.
        self.rule_engine = None
        if bool(self.config.get("rules.programs_enabled", True)):
            from sitewhere_tpu.rules.engine import RuleEngineRunner

            self.rule_engine = self.add_child(RuleEngineRunner(
                capacity=cap,
                n_mtype_slots=int(self.config.get(
                    "pipeline.mtype_slots", 8)),
                asset_capacity=int(self.config.get(
                    "rules.asset_capacity", 1024)),
                resolve_mtype=self.identity.mtype.mint,
                resolve_alert=self.identity.alert_type.mint,
                overload=self.overload,
                metrics=self.metrics,
                programs_per_tenant=int(self.config.get(
                    "rules.programs_per_tenant", 4)),
                max_programs=int(self.config.get(
                    "rules.max_programs", 262144)),
                queue_depth=int(self.config.get(
                    "rules.queue_depth", 64)),
            ))
            self.rule_engine.usage_ledger = self.usage_ledger
            self.rule_engine.quotas = self.quotas
        self.registration = self.add_child(RegistrationManager(
            self.device_management,
            default_device_type=self.config.get("registration.default_device_type"),
            allow_new_devices=bool(
                self.config.get("registration.allow_new_devices", True)
            ),
        ))

        # dispatch
        # Adaptive emission window (overlapped host pipeline): the
        # configured deadline is the ANCHOR; the controller shrinks the
        # window under idle traffic (chasing the <10ms p99 SLO) and grows
        # it under backlog (chasing full-width batches).  Disable with
        # pipeline.adaptive_deadline=false for a fixed window.
        controller = None
        if bool(self.config.get("pipeline.adaptive_deadline", True)):
            from sitewhere_tpu.ingest.batcher import AdaptiveBatchController

            controller = AdaptiveBatchController(
                deadline_ms=float(self.config["pipeline.deadline_ms"]),
                min_ms=self.config.get("pipeline.deadline_min_ms"),
                max_ms=self.config.get("pipeline.deadline_max_ms"),
                metrics=self.metrics,
            )
        self.batcher = Batcher(
            width=width,
            n_shards=n_shards,
            registry_capacity=cap,
            resolve_device=self.identity.device.lookup,
            resolve_mtype=self.identity.mtype.mint,
            resolve_alert=self.identity.alert_type.mint,
            invocations=self.identity.invocation,
            deadline_ms=float(self.config["pipeline.deadline_ms"]),
            # Emit plans in the packed wire form so the dispatcher
            # drives the ~11-buffer packed step — the default on EVERY
            # backend and on the mesh (_packed_step_enabled: the
            # dispatcher's many-output egress favors packed even on CPU;
            # on a mesh, per-call placement scales with buffer count).
            emit_packed=self._packed_step_enabled(),
            metrics=self.metrics,
            controller=controller,
        )
        # Decode worker pool (overlapped host pipeline, stage 1): wire
        # payloads decode on these workers while earlier windows are on
        # device; per-source lanes keep delivery in submission order.
        # ingest.decode_workers=0 disables (synchronous decode).
        from sitewhere_tpu.ingest.sources import DecodePool

        decode_workers = int(self.config.get("ingest.decode_workers", 2))
        self.decode_pool = (
            DecodePool(workers=decode_workers,
                       max_pending=int(self.config.get(
                           "ingest.decode_max_pending", 128)),
                       metrics=self.metrics)
            if decode_workers > 0 else None)
        self.dispatcher = self.add_child(PipelineDispatcher(
            batcher=self.batcher,
            registry_provider=self.mirror.publish_registry,
            state_manager=self.device_state,
            rules_provider=self.rules.publish,
            zones_provider=self.mirror.publish_zones,
            event_store=self.event_store,
            outbound=self.outbound,
            registration=self.registration,
            on_command_rows=self._on_command_rows,
            analytics=self.analytics,
            rules_engine=self.rule_engine,
            journal=self.ingest_journal,
            dead_letters=self.dead_letters,
            resolve_tenant=self._tenant_dense_id,
            on_host_request=self._on_host_request,
            inflight_depth=int(self.config.get("pipeline.inflight_depth", 0)),
            egress_offload=self.config.get("pipeline.egress_offload"),
            # Device-resident dispatch ring (pipeline/packed.py
            # build_packed_chain): unset → backend-adaptive (8 on TPU,
            # off elsewhere); 0/1 disables; ≥2 forces — the tier-1 CPU
            # smoke forces 2 so the chained path runs on every backend.
            ring_depth=(int(self.config["pipeline.ring_depth"])
                        if self.config.get("pipeline.ring_depth")
                        is not None else None),
            mesh=self.mesh,
            journal_reader=JournalReader(self.ingest_journal, "pipeline"),
            recovery_decoder=recovery_decoder,
            tracer=self.tracer,
            metrics=self.metrics,
            overload=self.overload,
            flightrec=self.flightrec,
            slo=self.slo,
            quarantine_after=int(self.config.get(
                "pipeline.quarantine_after", 3)),
            cost_analysis=self.config.get("telemetry.cost_analysis"),
            usage_ledger=self.usage_ledger,
        ))
        if self.rule_engine is not None:
            # fired tenant programs re-enter the pipeline as first-class
            # ALERT events through the dispatcher's derived-alert edge
            self.rule_engine.inject = self.dispatcher.inject_rule_alerts
        self.presence = self.add_child(PresenceManager(
            self.device_state,
            check_interval_s=float(self.config["presence.scan_interval_s"]),
            missing_after_s=int(self.config["presence.missing_after_s"]),
            on_state_changes=self._on_presence_changes,
        ))
        self.sources: List[LifecycleComponent] = []
        self._config_sources_built = False

        # cross-host fabric (rpc/ package; sitewhere-grpc-client analog):
        # the server publishes this instance's domain surface; a 2+ entry
        # peers list additionally turns on keyed forwarding so every
        # ingest row lands on the host that owns its device's shard
        # (SURVEY.md §2.4 — Kafka partition-leadership at the host plane)
        self.rpc_server = None
        self.forwarder = None
        peers: List[str] = list(self.config.get("rpc.peers") or [])
        if bool(self.config.get("rpc.server.enabled")) or peers:
            from sitewhere_tpu.rpc import RpcServer, bind_instance

            self.rpc_server = self.add_child(RpcServer(
                host=str(self.config.get("rpc.server.host", "127.0.0.1")),
                port=int(self.config.get("rpc.server.port", 0)),
                tokens=self.tokens, tracer=self.tracer,
                metrics=self.metrics))
            bind_instance(self.rpc_server, self)
            if self.overload is not None:
                # overload piggyback on every RPC response header: busy
                # fabrics learn this host's pressure at call rate,
                # faster than the fleet heartbeat period
                self.rpc_server.overload_provider = (
                    lambda: (int(self.overload.state),
                             self.overload.retry_after()))
        if len(peers) > 1:
            from sitewhere_tpu.rpc import HostForwarder, RpcDemux

            process_id = int(self.config.get("rpc.process_id", 0))
            if not 0 <= process_id < len(peers):
                raise ValueError(
                    f"rpc.process_id {process_id} outside peers list")
            if not jwt_secret:
                # without a shared secret every forwarded batch would be
                # rejected as unauthorized and dead-lettered — fail at
                # boot, not silently at runtime
                raise ValueError(
                    "multi-host (rpc.peers) requires a shared "
                    "security.jwt_secret so peers can verify each "
                    "other's service tokens")

            def _system_jwt() -> str:
                # service-to-service identity (reference SystemUserRunnable)
                return self.tokens.mint("system", ["ROLE_ADMIN"])

            self._peer_demuxes = {
                p: (None if p == process_id
                    else RpcDemux([ep], token_provider=_system_jwt))
                for p, ep in enumerate(peers)
            }
            self.forwarder = self.add_child(HostForwarder(
                self.dispatcher, process_id, self._peer_demuxes,
                dead_letters=self.dead_letters,
                deadline_ms=float(self.config.get(
                    "rpc.forward_deadline_ms", 25.0)),
                data_dir=self.data_dir,
                tracer=self.tracer,
                metrics=self.metrics,
                overload=self.overload,
                heartbeat_interval_s=float(self.config.get(
                    "rpc.heartbeat_interval_s", 0.5)),
                call_timeout_s=float(self.config.get(
                    "rpc.call_timeout_s", 10.0)),
                # hung-step watchdog flag on every beat: peers park
                # forwards toward a host whose device tier is wedged —
                # plus the mesh-shard attribution so a single sick
                # shard's wedge doesn't park the whole host
                device_unhealthy=lambda: self.dispatcher.device_unhealthy,
                device_unhealthy_shards=(
                    lambda: self.dispatcher.device_unhealthy_shards)))
        else:
            self._peer_demuxes = {}
        self._rpc_peers = list(peers)
        if self._peer_demuxes:
            # live endpoint reload (the Consul-watch analog): a peer that
            # moved hosts/ports picks up on config.reload() without a
            # restart.  Changing the NUMBER of peers changes device
            # ownership (rendezvous hash over P) and requires a restart —
            # reject it rather than silently split streams.
            self.config.on_change(self._on_peers_changed)

        # event search (service-event-search analog): the local store is
        # the built-in index; in a multi-host topology every peer's store
        # is a remote index and "federated" fans out + merges newest-first
        from sitewhere_tpu.outbound.search import (
            EventSearchProvider,
            FederatedSearchProvider,
            RemoteSearchProvider,
            SearchProvidersManager,
            TokenSearchAdapter,
        )

        self.search_providers = SearchProvidersManager(
            [EventSearchProvider("local", self.event_store)])
        if self._peer_demuxes:
            local_adapter = TokenSearchAdapter(
                "local", self.event_store, self.identity,
                self.device_management)
            legs = [local_adapter] + [
                RemoteSearchProvider(f"peer-{p}", demux)
                for p, demux in sorted(self._peer_demuxes.items())
                if demux is not None
            ]
            for leg in legs[1:]:
                self.search_providers.add_provider(leg)
            self.search_providers.add_provider(
                FederatedSearchProvider("federated", legs))

        # checkpoint/resume (SURVEY.md §5): restore the newest complete
        # snapshot BEFORE start so devices/assignments/users/tenants/rules,
        # DeviceState AND live analytics/CEP operator state survive a
        # restart; the journal replay in start() then re-derives anything
        # journaled after each component's snapshotted as-of offset.
        from sitewhere_tpu.runtime.checkpoint import (
            Checkpointer,
            StateProvider,
        )

        self._engine_snapshots: Dict[str, dict] = {}
        self._dedup_snapshot: Dict[str, list] = {}
        self.checkpointer = self.add_child(Checkpointer(
            self,
            interval_s=float(self.config.get("checkpoint.interval_s", 30.0)),
            prune_journal=bool(self.config.get(
                "journal.prune_after_checkpoint", False)),
        ))
        if self.analytics is not None:
            # live query/CEP state: open windows, rings, sessions,
            # pattern stages — carried with its exact applied offset
            self.checkpointer.register_provider(StateProvider(
                name="analytics",
                snapshot_fn=self.analytics.snapshot_state,
                restore_fn=self.analytics.restore_state,
                version=1))
        if self.rule_engine is not None:
            # tenant rule programs + attribute tables (docs are the
            # durable identity; operand tables and kernels rebuild on
            # the first post-restore publish)
            self.checkpointer.register_provider(StateProvider(
                name="rule-programs",
                snapshot_fn=self.rule_engine.snapshot_state,
                restore_fn=self.rule_engine.restore_state,
                version=1))
        # ingest dedup tables + forward-spool cursors (the spools
        # themselves are already durable journals; the cursor record is
        # observability for the recovery report)
        self.checkpointer.register_provider(StateProvider(
            name="runtime",
            snapshot_fn=self._snapshot_runtime_state,
            restore_fn=self._restore_runtime_state,
            version=1))
        # segment-store catalog manifest: rides the same CRC-framed,
        # generation-committed snapshot protocol; restore cross-checks
        # the directory-rebuilt catalog against the last committed
        # generation's view and exports the drift as a gauge
        from sitewhere_tpu.store.catalog import catalog_state_provider

        self.checkpointer.register_provider(
            catalog_state_provider(self.event_store))
        if self.usage_ledger is not None:
            # tenant usage totals + heavy-hitter/count-min sketches; the
            # sliding window deliberately restarts empty (shares describe
            # CURRENT load, not pre-restart load)
            self.checkpointer.register_provider(StateProvider(
                name="tenant-metering",
                snapshot_fn=self.usage_ledger.snapshot_payload,
                restore_fn=self.usage_ledger.restore_payload,
                version=1))
        self.restored = self.checkpointer.restore()

    # -- wiring helpers -----------------------------------------------------

    def _snapshot_runtime_state(self):
        """Checkpoint section for the small volatile runtime tables: the
        per-source ingest dedup LRUs (so a restart doesn't re-admit the
        duplicates the window had already caught) and the forward-spool
        committed cursors (informational — the spools are durable
        journals with their own offset files)."""
        import pickle

        dedup: Dict[str, list] = {}
        for src in self.sources:
            d = getattr(src, "deduplicator", None)
            if d is not None and hasattr(d, "export_keys"):
                dedup[src.name] = d.export_keys()
        spools: Dict[str, int] = {}
        if self.forwarder is not None:
            spools = {
                str(p): int(r.committed)
                for p, r in getattr(self.forwarder, "_spool_readers",
                                    {}).items()
            }
        return (pickle.dumps({"dedup": dedup, "spools": spools},
                             protocol=4), None)

    def _restore_runtime_state(self, header, payload) -> None:
        import pickle

        doc = pickle.loads(payload)
        # sources attach after __init__ — add_source hydrates from this
        self._dedup_snapshot = dict(doc.get("dedup") or {})

    def _on_peers_changed(self, config) -> None:
        from sitewhere_tpu.rpc.wire import parse_endpoint

        new_peers = list(config.get("rpc.peers") or [])
        # validate EVERY endpoint before touching any demux: a typo'd
        # port must not leave the fleet half-updated
        try:
            for ep in new_peers:
                parse_endpoint(str(ep))
        except ValueError as e:
            logger.error("rpc.peers reload rejected: %s", e)
            return
        old_peers = self._rpc_peers
        if len(new_peers) != len(old_peers):
            logger.error(
                "rpc.peers count changed %d -> %d: device ownership "
                "(rendezvous over P) would shift — restart required; "
                "keeping the old endpoints",
                len(old_peers), len(new_peers))
            return
        # A reorder of EXISTING endpoints rebinds process ids to
        # different hosts — the same ownership shift as a count change
        # (devices of process p would ship to a host that believes it is
        # process q).  A host MOVING keeps its index; an address already
        # bound to another index (including our own) may not reappear at
        # a changed one.
        for p, ep in enumerate(new_peers):
            if ep != old_peers[p] and ep in old_peers:
                logger.error(
                    "rpc.peers reorder detected (%s moved from index %d "
                    "to %d): process-id/host binding would shift — "
                    "restart required; keeping the old endpoints",
                    ep, old_peers.index(ep), p)
                return
        for p, demux in self._peer_demuxes.items():
            if demux is not None and demux.endpoints != [new_peers[p]]:
                logger.info("peer %d endpoint -> %s", p, new_peers[p])
                demux.set_endpoints([new_peers[p]])
        self._rpc_peers = new_peers

    def apply_membership_change(self, new_peers: List[str],
                                process_id: Optional[int] = None) -> dict:
        """Adopt a NEW peers list whose COUNT may differ — the explicit
        ops path for cluster grow/shrink (the config reload deliberately
        rejects count changes; see ``_on_peers_changed``).

        Sequence (reference: Kafka consumer rebalance + demux discovery
        add/remove, ``ApiDemux.java`` DiscoveryMonitor):

        1. build demuxes for the new endpoints (reusing live channels
           for endpoints that did not move);
        2. requeue every pending forwarded row under the new ownership
           (:meth:`HostForwarder.apply_membership` — a departed peer's
           spool drains to the rows' new owners);
        3. hand off locally-owned devices whose new owner is elsewhere
           (:func:`sitewhere_tpu.rpc.migration.migrate_out` — registry
           rows + newest-wins DeviceState over ``migration.import``).

        Returns the handoff summary.  Every host in the fleet must apply
        the SAME list (ownership is the rendezvous hash over it).
        """
        from sitewhere_tpu.rpc import RpcDemux
        from sitewhere_tpu.rpc.migration import migrate_out
        from sitewhere_tpu.rpc.wire import parse_endpoint
        from sitewhere_tpu.services.common import ValidationError

        for ep in new_peers:
            parse_endpoint(str(ep))
        if process_id is None:
            process_id = self._process_id()
        old_n = max(len(self._rpc_peers), 1)
        if not 0 <= process_id < len(new_peers):
            raise ValueError(
                f"process_id {process_id} outside new peers list")

        def _system_jwt() -> str:
            return self.tokens.mint("system", ["ROLE_ADMIN"])

        old_by_endpoint = {}
        for p, ep in enumerate(self._rpc_peers):
            demux = self._peer_demuxes.get(p)
            if demux is not None:
                old_by_endpoint[ep] = demux
        new_demuxes = {}
        for p, ep in enumerate(new_peers):
            if p == process_id:
                new_demuxes[p] = None
            elif ep in old_by_endpoint:
                new_demuxes[p] = old_by_endpoint.pop(ep)
            else:
                new_demuxes[p] = RpcDemux([ep], token_provider=_system_jwt)

        if self.forwarder is not None:
            self.forwarder.apply_membership(new_demuxes,
                                            process_id=process_id)
        elif len(new_peers) > 1:
            # A standalone instance has its protocol sources wired
            # straight to the dispatcher and (usually) no RpcServer for
            # peers to deliver to — conjuring a forwarder here would
            # leave every attached source bypassing it, splitting device
            # streams across hosts.  Multi-host membership starts at
            # boot (rpc.peers); this API then grows/shrinks it.
            raise ValidationError(
                "this instance booted standalone (no rpc.peers); "
                "restart it with rpc.peers + rpc.server.enabled to "
                "join a fleet")
        self._peer_demuxes = new_demuxes
        self._rpc_peers = list(new_peers)
        self.config.set("rpc.peers", list(new_peers))
        self.config.set("rpc.process_id", process_id)
        # closed-over demuxes for endpoints that left the fleet
        for demux in old_by_endpoint.values():
            try:
                demux.close()
            except Exception:
                logger.exception("old peer demux close failed")

        summary = migrate_out(self, old_n, len(new_peers), process_id,
                              new_demuxes)
        logger.info("membership change to %d peers: %s",
                    len(new_peers), summary)
        return summary

    def _process_id(self) -> int:
        return int(self.config.get("rpc.process_id", 0))

    def _packed_step_enabled(self) -> bool:
        """Config ``pipeline.packed_step`` (true/false) pins the step
        interface; the default is ON for the dispatcher on every
        backend.  The PURE step is backend-adaptive (CPU pays the
        repack; ``packed_step_default``), but the dispatcher's egress
        fetches many output buffers per step, which the packed [10, B]
        block collapses — measured on CPU: dispatcher path 253k → 327k
        events/s, p99 15 → 13.5 ms; on TPU it also removes the ~30 ms
        per-call dispatch tax."""
        cfg = self.config.get("pipeline.packed_step", "auto")
        if isinstance(cfg, bool):
            return cfg
        if str(cfg).lower() in ("true", "false"):
            return str(cfg).lower() == "true"
        from sitewhere_tpu.pipeline.packed import packed_env_override

        env = packed_env_override()
        return True if env is None else env

    def _overload_signals(self):
        """One sample of the pressure signals the overload controller
        watches — all of them gauges/counters the system already
        exports, read lock-free (a slightly stale read only delays a
        transition by one sample)."""
        from sitewhere_tpu.runtime.overload import OverloadSignals

        d = self.dispatcher
        pool = self.decode_pool
        decode_backlog = (pool.pending / pool.max_pending
                          if pool is not None and pool.max_pending else 0.0)
        # ingest→seal lag comes from the LIVE watermark (age of the
        # oldest unsealed event), not the last-value seal gauge — the
        # gauge pins historical spikes (a jit compile's 3s seal) for as
        # long as anything is busy, which would read as sustained
        # overload; the live measure self-decays as work seals.
        return OverloadSignals(
            seal_lag_s=d.oldest_unsealed_wait_s(),
            decode_backlog=decode_backlog,
            # ring-held plans are emitted-but-unstepped work the egress
            # window hasn't seen yet — in-flight pressure all the same
            egress_inflight=((len(d._inflight) + len(d._ring))
                             / max(1, d.egress_queue_depth)),
            batcher_backlog=self.batcher.pending / max(1, self.batcher.width),
            fsync_latency_s=float(self.ingest_journal.last_fsync_s),
        )

    def _slo_sample(self):
        """One SLO burn-rate sample: counter DELTAS since the previous
        sample (events processed, shed vs admitted) plus the rolling p99
        — the engine judges each delta against the BASELINE targets."""
        import time as _time

        now = _time.monotonic()
        last = self._slo_last
        snap = self.dispatcher.metrics_snapshot()
        processed = int(snap.get("processed", 0))
        shed = (int(self.overload.shed_total)
                if self.overload is not None else 0)
        admitted = (int(self.overload.admitted_total)
                    if self.overload is not None else processed)
        sample = None
        if last["at"] is not None:
            events = processed - last["processed"]
            sample = {
                "events": events,
                "elapsed_s": max(1e-9, now - last["at"]),
                # the rolling p99 is only evidence while traffic flows:
                # the latency reservoir is never time-pruned, so after a
                # burst it would keep reporting the burst's percentile
                # forever and an idle instance would read as burning
                "p99_ms": (snap.get("latency_p99_ms")
                           if events > 0 else None),
                "shed": shed - last["shed"],
                "admitted": admitted - last["admitted"],
                # queue SNAPSHOT (not a delta): the engine's wedge
                # witness for deployments whose admitted counter aliases
                # processed (overload disabled) — rows pending while
                # nothing completes judges as a stall, never as idle
                "backlog": int(snap.get("pending_rows", 0)),
            }
        self._slo_last = {"processed": processed, "shed": shed,
                          "admitted": admitted, "at": now}
        return sample

    def _flightrec_dump_async(self, reason: str, detail: str) -> None:
        """Anomaly dump OFF the calling thread: overload transitions and
        SLO alerts fire on the dispatcher loop, and a snapshot is a file
        write — during a disk-stressed incident (slow fsync is itself an
        overload signal) an inline dump would stall the dispatch loop at
        the exact moment it is overloaded.  The per-reason rate limit is
        checked inside anomaly(), so a storm spawns counted no-op
        threads, not files."""
        import threading as _threading

        _threading.Thread(
            target=lambda: self.flightrec.anomaly(reason, detail=detail),
            daemon=True, name="flightrec-dump").start()

    def _on_slo_alert(self, objective: str, burn: float) -> None:
        """A burn alert armed: stamp the tail sampler (traces around
        the breach are retained) and dump the flight recorder."""
        note = getattr(self.tracer, "note_anomaly", None)
        if note is not None:
            note()
        if self.flightrec is not None:
            self._flightrec_dump_async(f"slo-{objective}",
                                       f"burn {burn:.2f}x budget")

    def run_device_profile(self, iters: int = 16,
                           repeats: int = 3) -> dict:
        """On-demand device-stage calibration (the ``profile_step.py``
        fori-chain methodology at this instance's width/capacity):
        records ``device.stage_ms.*`` histogram samples and returns the
        stage medians.  Compiles one probe chain per stage — seconds of
        work; REST exposes it admin-only for exactly that reason."""
        from sitewhere_tpu.pipeline.telemetry import profile_device_stages

        # the LIVE table shapes: rule/zone eval cost is shape-driven, so
        # the probes must run at this deployment's actual capacities
        rules = self.rules.publish()
        zones = self.mirror.publish_zones()
        result = profile_device_stages(
            width=int(self.config["pipeline.width"]),
            capacity=int(self.config["pipeline.registry_capacity"]),
            rules_capacity=int(rules.threshold.shape[0]),
            zones_capacity=int(zones.nvert.shape[0]),
            iters=iters, repeats=repeats, metrics=self.metrics)
        full_ms = result.get("full_ms")
        if full_ms:
            # re-anchor the hung-step watchdog's soft/hard budgets to
            # the MEASURED per-step device time (floored inside
            # calibrate so a CPU test host never false-trips)
            self.dispatcher.watchdog.calibrate(float(full_ms))
        return result

    def start_profiler_capture(self) -> dict:
        """Start an on-demand ``jax.profiler`` trace into the data dir
        (the device-side flamegraph an operator opens in TensorBoard /
        XProf).  One capture at a time; returns the trace directory."""
        import time as _time

        import jax as _jax

        from sitewhere_tpu.services.common import ValidationError

        # the lock makes check-then-start atomic: two racing starts must
        # yield one capture and one honest "already running" error, not
        # a misdiagnosed "profiler unavailable" from the loser
        with self._profiler_lock:
            if getattr(self, "_profiler_dir", None):
                raise ValidationError(
                    "profiler capture already running: "
                    f"{self._profiler_dir}")
            trace_dir = os.path.join(
                self.data_dir, "profiles", f"capture-{int(_time.time())}")
            os.makedirs(trace_dir, exist_ok=True)
            try:
                _jax.profiler.start_trace(trace_dir)
            except Exception as e:
                raise ValidationError(f"jax profiler unavailable: {e}")
            self._profiler_dir = trace_dir
        logger.info("jax profiler capture started -> %s", trace_dir)
        return {"capturing": True, "trace_dir": trace_dir}

    def stop_profiler_capture(self) -> dict:
        import jax as _jax

        from sitewhere_tpu.services.common import ValidationError

        with self._profiler_lock:
            trace_dir = getattr(self, "_profiler_dir", None)
            if not trace_dir:
                raise ValidationError("no profiler capture running")
            try:
                _jax.profiler.stop_trace()
            except Exception as e:
                # keep _profiler_dir: a failed stop must stay retryable
                # — clearing it first would wedge BOTH endpoints (stop
                # says "nothing running", start "already started")
                raise ValidationError(f"profiler stop failed: {e}")
            self._profiler_dir = None
        logger.info("jax profiler capture stopped (%s)", trace_dir)
        return {"capturing": False, "trace_dir": trace_dir}

    def _tenant_dense_id(self, token: str) -> int:
        return self.identity.tenant.mint(token)

    def _make_tenant_engine(self, tenant, tenant_id: int,
                            config: Dict[str, object]) -> TenantEngine:
        """Engine factory: per-tenant service façades over the instance's
        shared identity map + registry mirror, with per-tenant config
        overlays from ``tenants.<token>`` in the instance config."""
        overlay = dict(config)
        per_tenant = self.config.get(f"tenants.{tenant.token}", None)
        if isinstance(per_tenant, dict):
            overlay.update(per_tenant)
        if tenant.token == "default":
            # the instance-level services ARE the default tenant's engine
            return TenantEngine(
                tenant, tenant_id, overlay,
                identity=self.identity, mirror=self.mirror,
                device_management=self.device_management,
                asset_management=self.assets,
            )
        engine = TenantEngine(
            tenant, tenant_id, overlay,
            identity=self.identity, mirror=self.mirror,
        )
        # checkpoint resume: hydrate the engine's host dicts (its rows in
        # the shared tensors were restored with the mirror snapshot).
        # `.get`, not `.pop` — the snapshot must survive for a later
        # rebuild-restart or a failed-then-retried engine start.
        snap = getattr(self, "_engine_snapshots", {}).get(tenant.token)
        if snap:
            from sitewhere_tpu.runtime.checkpoint import merge_store

            merge_store(engine.device_management,
                        snap.get("device_management", {}))
            merge_store(engine.asset_management, snap.get("assets", {}))
        return engine

    def _tenant_ids_of_devices(self, device_ids):
        import numpy as np

        reg = self.mirror.publish_registry()
        return np.asarray(reg.tenant_id)[device_ids]

    def _on_presence_changes(self, batch) -> None:
        import numpy as np

        self.dispatcher.inject_batch(batch, np.asarray(batch.valid))

    def _on_command_rows(self, cols, mask, trace=None) -> None:
        """Deliver pipeline COMMAND_INVOCATION events (reference:
        enriched-command-invocations → command-delivery, SURVEY.md §3.4).

        The tensor row carries only dense handles; the command token +
        parameters live in the journaled source payload (``payload_ref``).
        Rows without a resolvable command spec dead-letter.
        """
        from sitewhere_tpu.ingest.journal import CorruptJournal

        refs = cols["payload_ref"][mask]
        device_ids = cols["device_id"][mask]
        for ref, dev in zip(refs, device_ids):
            invocation = None
            try:
                if int(ref) != NULL_ID:
                    doc = json.loads(self.ingest_journal.read_one(int(ref)))
                    body = doc.get("request", doc)
                    command = body.get("commandToken")
                    if command:
                        assignment = body.get("assignmentToken")
                        if not assignment:
                            token = self.identity.device.token_of(int(dev))
                            active = (self.device_management
                                      .get_active_assignment(token)
                                      if token else None)
                            assignment = active.token if active else None
                        if assignment:
                            kwargs = {}
                            if body.get("invocationToken"):
                                kwargs["token"] = str(body["invocationToken"])
                            invocation = CommandInvocation(
                                command_token=str(command),
                                target_assignment=str(assignment),
                                parameter_values=dict(
                                    body.get("parameterValues", {})),
                                initiator=str(body.get("initiator", "EVENT")),
                                initiator_id=body.get("initiatorId"),
                                **kwargs,
                            )
            except (ValueError, KeyError, CorruptJournal) as e:
                logger.debug("unresolvable command payload ref %s: %s", ref, e)
            if invocation is not None:
                self.commands.invoke(invocation, trace=trace)
            else:
                self.dead_letters.append_json({
                    "kind": "undeliverable-invocation",
                    "device_id": int(dev),
                    "payload_ref": int(ref),
                })

    def _on_undelivered_command(self, invocation, reason) -> None:
        """Undelivered commands dead-letter (reference:
        undelivered-command-invocations topic)."""
        from sitewhere_tpu.runtime.resilience import dead_letter as _dl

        _dl(self.dead_letters, {
            "kind": "undelivered-command",
            "invocation": invocation.token,
            "command": invocation.command_token,
            "assignment": invocation.target_assignment,
            "parameterValues": invocation.parameter_values,
            "reason": str(reason),
        })

    def _run_scheduled_invocation(self, job) -> None:
        """Executor for CommandInvocation jobs (reference
        ``jobs/CommandInvocationJob.java``)."""
        self.commands.invoke(CommandInvocation(
            command_token=str(job.config["commandToken"]),
            target_assignment=str(job.config["assignmentToken"]),
            parameter_values=dict(job.config.get("parameterValues", {})),
            initiator="SCHEDULER",
            initiator_id=job.token,
        ))

    def _run_scheduled_batch(self, job) -> None:
        """Executor for BatchCommandInvocation jobs (reference
        ``jobs/BatchCommandInvocationJob.java``)."""
        self.batch_ops.create_batch_command_invocation(
            command_token=str(job.config["commandToken"]),
            parameter_values=dict(job.config.get("parameterValues", {})),
            devices=list(job.config.get("devices", [])) or None,
            group=job.config.get("group"),
        )

    def add_source(self, source: LifecycleComponent) -> LifecycleComponent:
        """Attach an ingest source wired into the dispatcher — or, in a
        multi-host topology, into the forwarder, which keeps locally-owned
        rows in-process and ships the rest to their owning host."""
        if self.forwarder is not None:
            source.on_event = (
                lambda req, payload=b"": self.forwarder.ingest_requests(
                    [req], payload))
            if hasattr(source, "on_events"):
                source.on_events = self.forwarder.ingest_requests
            if getattr(source, "raw_wire", False):
                # raw lane, multi-host form: owner-split the NDJSON
                # lines and ship remote rows to their owning host;
                # decode errors come back to the source for its
                # failure accounting
                source.on_wire_payload = (
                    lambda p, sid: self.forwarder.ingest_payload(
                        p, sid, raise_on_decode_error=True))
            source.on_registration = self.forwarder.ingest_registration
            # stream requests route to the device's owning host, which
            # handles them via its local _on_host_request
            self.forwarder.on_host_request = self._on_host_request
            source.on_host_request = self.forwarder.ingest_host_request
        else:
            source.on_event = self.dispatcher.ingest
            if hasattr(source, "on_events"):
                # batch forward: one columnar call per wire payload
                source.on_events = self.dispatcher.ingest_many
            if getattr(source, "raw_wire", False):
                # raw lane: C columnar decode + in-scanner token
                # resolution, no per-line json.loads; decode errors come
                # back to the source for its failure accounting
                source.on_wire_payload = (
                    lambda p, sid: self.dispatcher.ingest_wire_lines(
                        p, sid, raise_on_decode_error=True))
                # split halves for the decode pool: decode on a worker,
                # journal+batch in per-source order
                source.on_wire_decode = self.dispatcher.decode_wire_lines
                source.on_wire_decoded = self.dispatcher.ingest_wire_decoded
            source.on_registration = self.dispatcher.ingest_registration
        if self.decode_pool is not None and hasattr(source, "decode_pool"):
            # overlapped decode; the source itself keeps ack-gated
            # receivers (broker redelivery semantics) synchronous
            source.decode_pool = self.decode_pool
        # checkpoint resume: re-seed the source's dedup window so a
        # restart doesn't re-admit duplicates the window had caught
        dedup_keys = self._dedup_snapshot.get(source.name)
        if dedup_keys and getattr(source, "deduplicator", None) is not None \
                and hasattr(source.deduplicator, "import_keys"):
            source.deduplicator.import_keys(dedup_keys)
        source.on_failed_decode = self.dispatcher.ingest_failed_decode
        if getattr(source, "on_host_request", None) is None \
                and self.forwarder is None:
            source.on_host_request = self._on_host_request
        self.sources.append(self.add_child(source))
        return source

    def _on_host_request(self, req, payload: bytes = b"") -> None:
        """Route host-plane requests from sources (reference: device
        stream create/data/send-back requests flow through the event
        sources into ``DeviceStreamManager``,
        ``media/DeviceStreamManager.java``).  Stream requests are
        handled by the RECEIVING host (streams are assignment-scoped,
        management-plane); anything unroutable dead-letters."""
        from sitewhere_tpu.ingest.decoders import RequestKind
        from sitewhere_tpu.services.common import ServiceError

        try:
            if req.kind == RequestKind.STREAM_CREATE:
                self.stream_manager.handle_device_stream_request(
                    req.device_token, req.stream_id,
                    req.content_type or "application/octet-stream")
                return
            if req.kind == RequestKind.STREAM_DATA:
                self.stream_manager.handle_device_stream_data_request(
                    req.device_token, req.stream_id,
                    req.sequence_number, req.stream_data or b"")
                return
            if req.kind == RequestKind.STREAM_SEND:
                self.stream_manager.handle_send_device_stream_data_request(
                    req.device_token, req.stream_id, req.sequence_number)
                return
        except ServiceError as e:
            from sitewhere_tpu.ingest.decoders import encode_envelope

            # the raw request is recorded so the operator requeue path
            # can replay it (e.g. after the missing stream was created)
            self.dead_letters.append_json({
                "kind": "failed-stream-request",
                "request_kind": req.kind.name,
                "device_token": req.device_token,
                "stream_id": req.stream_id,
                "error": str(e),
                "payload": (payload or encode_envelope(req)).hex(),
            })
            return
        self.dead_letters.append_json({
            "kind": "unsupported-host-request",
            "request_kind": req.kind.name,
            "device_token": req.device_token,
        })

    # -- bootstrap (service-instance-management) ----------------------------

    @property
    def _marker_path(self) -> str:
        return os.path.join(self.data_dir, ".bootstrapped")

    @property
    def bootstrapped(self) -> bool:
        return os.path.exists(self._marker_path)

    def bootstrap(self) -> bool:
        """Ensure template users/tenants exist (idempotent, re-run on every
        start since the management stores are memory-resident until a
        checkpoint restores them) and run dataset initializers ONCE — the
        marker gates only the arbitrary-code initializers, the analog of
        the reference's bootstrapped marker around its Groovy scripts
        (``Microservice.java:516-518``).  Returns True if the dataset
        initializers ran."""
        for spec in self.template.users:
            spec = dict(spec)
            authorities = list(spec.pop("authorities", []))
            existing = {a.authority for a in self.users.list_granted_authorities()}
            for auth in authorities:
                if auth not in existing:
                    self.users.create_granted_authority(auth)
            if not any(u.username == spec["username"] for u in
                       self.users.list_users()):
                self.users.create_user(authorities=authorities, **spec)
        known = {t.token for t in self.tenants.list_tenants()}
        for spec in self.template.tenants:
            if spec["token"] not in known:
                self.tenants.create_tenant(**spec)
            self._tenant_dense_id(spec["token"])
        if self.bootstrapped:
            logger.info("instance %s already bootstrapped", self.instance_id)
            return False
        for initializer in self.template.dataset_initializers:
            initializer(self)
        with open(self._marker_path, "w") as f:
            json.dump({"template": self.template.template_id}, f)
        logger.info("bootstrapped instance %s from template %s",
                    self.instance_id, self.template.template_id)
        return True

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        self.bootstrap()
        # Warm the native wire decoder OFF the data path: its first-use
        # build (cc subprocess) must never stall a receiver thread's
        # decode into the <10ms p99 budget.  Decodes that arrive while
        # the build is in flight take the Python path silently — the
        # dispatcher surfaces that count as the ``native.build_fallbacks``
        # gauge, and kicking the build HERE is what keeps it near zero.
        import threading as _threading

        from sitewhere_tpu.native import load_swwire

        _threading.Thread(target=load_swwire, daemon=True,
                          name="native-warmup").start()
        # Config-declared sources (EventSourcesParser analog): built and
        # attached before the lifecycle start below brings them up.  A bad
        # declaration fails boot, like the reference's schema-validated
        # tenant XML.
        source_docs = self.config.get("sources")
        if source_docs and not self._config_sources_built:
            from sitewhere_tpu.ingest.factory import build_sources

            for src in build_sources(source_docs, scripts=self.scripts):
                self.add_source(src)
            self._config_sources_built = True
        # Capture the journal end BEFORE sources start so crash recovery
        # never double-ingests a fresh append racing the replay.
        recover_upto = self.ingest_journal.end_offset
        super().start()
        if bool(self.config.get("telemetry.device_profile_on_start",
                                False)):
            # boot-time device-stage calibration OFF the data path: the
            # probe chains compile on a background thread and land in
            # the device.stage_ms.* histograms when done
            def _calibrate():
                try:
                    self.run_device_profile()
                except Exception:
                    logger.exception("device-stage calibration failed")

            _threading.Thread(target=_calibrate, daemon=True,
                              name="device-profile").start()
        # Crash recovery: re-ingest journal records past each restored
        # component's as-of offset (at-least-once;
        # MicroserviceKafkaConsumer.java:116-139).  Records between the
        # replay floor and the committed offset rebuild volatile state
        # (open windows, device tensors newer than the snapshot) without
        # duplicating event-store persistence (store_dedup_floor).
        import time as _time

        t0 = _time.perf_counter()
        replayed = self.dispatcher.replay_journal(
            upto=recover_upto,
            from_offset=self.checkpointer.replay_floor)
        replay_s = _time.perf_counter() - t0
        # RTO as a measured number: how long the restore + replay halves
        # of recovery actually took, exported every boot
        self.metrics.gauge("recovery.replay_events").set(replayed)
        self.metrics.gauge("recovery.replay_s").set(replay_s)
        if replayed:
            logger.info("recovered %d journaled events in %.3fs on start "
                        "(floor %s)", replayed, replay_s,
                        self.checkpointer.replay_floor)
        if self.restored and self.flightrec is not None:
            # every restore leaves a flight-recorder snapshot: the batch
            # records of the replay plus the recovery numbers an operator
            # needs when asking "what did the restart cost us"
            self.flightrec.snapshot(
                "recovery",
                detail=(f"restored gen {self.checkpointer.restored_generation}"
                        f" in {self.checkpointer.restore_s:.3f}s; replayed "
                        f"{replayed} events in {replay_s:.3f}s from floor "
                        f"{self.checkpointer.replay_floor}"))

    def stop(self) -> None:
        # Stop the receivers, THEN drain the decode pool: a payload a
        # still-running receiver accepts after the flush would otherwise
        # deliver concurrently with (or after) the dispatcher's shutdown
        # flush below.  super().stop() skips the already-stopped sources.
        if self.decode_pool is not None:
            from sitewhere_tpu.runtime.lifecycle import LifecycleState

            for src in self.sources:
                if src.state == LifecycleState.STARTED:
                    try:
                        src.stop()
                    except Exception:  # keep stopping, like super().stop()
                        logger.exception("error stopping %s", src.name)
            self.decode_pool.flush()
        super().stop()  # dispatcher stop flushes + commits the offset
        # Final snapshot AFTER the flush so the checkpoint captures the
        # last committed state (components are stopped but data is live).
        # Ordering contract (audited, regression-tested in
        # tests/test_checkpoint.py): the dispatcher's stop() has drained
        # the ring and egress and committed the final journal offset, and
        # save() captures that offset BEFORE reading any component — the
        # snapshot's claimed offsets can never lead the sealed journal.
        self.checkpointer.save()

    def terminate(self) -> None:
        super().terminate()
        if self.decode_pool is not None:
            # release the pool's worker threads (tests build many
            # instances; daemons would pile up)
            self.decode_pool.stop(timeout_s=2.0)
            self.decode_pool = None
        if self._peer_demuxes:
            # the Config can outlive this Instance: a stale listener
            # would hold the whole graph and resurrect closed channels
            self.config.remove_listener(self._on_peers_changed)
        for demux in self._peer_demuxes.values():
            if demux is not None:
                demux.close()
        self.ingest_journal.close()
        self.dead_letters.close()

    # -- topology (admin surface) -------------------------------------------

    def topology(self) -> dict:
        """Live component tree + counters (reference
        ``TopologyStateAggregator`` → admin UI WebSocket feed)."""
        from sitewhere_tpu.runtime.metrics import global_registry

        topo = {
            "instance": self.instance_id,
            "bootstrapped": self.bootstrapped,
            "components": self.status_tree(),
            "pipeline": self.dispatcher.metrics_snapshot(),
            "devices": len(self.identity.device),
            "events_stored": self.event_store.total_events,
            "store": self.event_store.store_stats(),
            "tracing": self.tracer.stats(),
            # cross-cutting resilience counters (retries, breaker
            # transitions, supervisor restarts, dead-letter totals)
            "resilience": {
                k: v for k, v in
                global_registry().snapshot()["counters"].items()
                if k.startswith("resilience.")
            },
        }
        if self.overload is not None:
            topo["overload"] = self.overload.snapshot()
        if self.flightrec is not None:
            topo["flightrec"] = self.flightrec.stats()
        if self.slo is not None:
            topo["slo"] = self.slo.snapshot()
        if self.forwarder is not None:
            topo["forwarding"] = self.forwarder.metrics()
        return topo

    # -- dead-letter operations (the reprocess-topic analog) ----------------

    def list_dead_letters(self, limit: int = 100,
                          start: Optional[int] = None) -> List[dict]:
        """Dead-letter records with their offsets.

        Without ``start``: the newest ``limit`` records (the tail —
        offsets are dense, so this reads at most ``limit`` records
        regardless of journal size).  With ``start``: the first ``limit``
        records from that offset (oldest-first paging; pass the last
        returned offset + 1 as the next page's start).

        Reference: the dead-letter topics (failed-decode, unregistered,
        undelivered commands — ``KafkaTopicNaming.java:48-78``) are
        operator-inspectable with Kafka tooling; here they are one
        CRC-checked journal.  Records already requeued carry
        ``"requeued": true``.
        """
        limit = max(1, limit)
        if start is None:
            begin = self.dead_letters.end_offset - limit
            stop = None
        else:
            begin = start
            stop = start + limit
        requeued = self._requeued_dead_letters()
        out: List[dict] = []
        for offset, raw in self.dead_letters.scan(max(0, begin), stop):
            try:
                doc = json.loads(raw)
            except ValueError:
                doc = {"kind": "corrupt", "raw": raw.hex()}
            if doc.get("kind") == "requeue-marker":
                continue  # bookkeeping, not an operator-facing record
            doc["offset"] = offset
            if offset in requeued:
                doc["requeued"] = True
            out.append(doc)
        return out[-limit:]

    def _requeued_dead_letters(self) -> set:
        """Offsets already requeued, rebuilt from the retained journal
        tail's marker records (cached against the journal end offset)."""
        end = self.dead_letters.end_offset
        cache = getattr(self, "_requeue_cache", None)
        if cache is not None and cache[0] == end:
            return cache[1]
        done: set = set()
        # scan(0) starts at the first RETAINED segment (prune contract),
        # so this is bounded by the retention window
        for _, raw in self.dead_letters.scan(0):
            try:
                doc = json.loads(raw)
            except ValueError:
                continue
            if doc.get("kind") == "requeue-marker":
                done.add(int(doc.get("target", -1)))
        self._requeue_cache = (end, done)
        return done

    def _mark_requeued(self, offset: int) -> None:
        """Durable idempotency marker: requeuing the same offset twice
        must not re-deliver (markers ride the same journal, so they
        survive restarts and age out with the records they guard)."""
        self.dead_letters.append_json(
            {"kind": "requeue-marker", "target": int(offset)})

    def requeue_dead_letter(self, offset: int) -> dict:
        """Re-drive one dead-letter record through the pipeline (the
        reprocess-topic analog, ``KafkaTopicNaming.java:172-174``).

        - ``failed-decode``: re-decode the captured raw payload with the
          dispatcher's recovery decoder (the operator may have fixed the
          device type/scripts since) and re-ingest; a second decode
          failure dead-letters again.
        - ``unregistered``: re-read each referenced ingest-journal
          payload and re-ingest — after the operator registered the
          device manually, the rows now validate.
        - ``intake-shed``: re-ingest a payload that overload admission
          refused (the audit/replay half of the shedding contract) —
          admission applies again, so a requeue during a STILL-overloaded
          window is refused, not silently re-shed.
        - ``tenant-budget``: same replay path as ``intake-shed``, for
          sheds the tenant's CONFIGURED budget overlay caused.  Replay
          re-checks the tenant's CURRENT budget — re-ingest runs the
          composed admission again, so a tenant still over its budget
          is refused (with the budget named), and one whose budget was
          raised (or whose window drained) gets the rows back.
        - ``forward-shed``: re-route remote-owned rows the forwarder's
          shed-retention bound forced out — back through
          ``HostForwarder.ingest_payload`` so ownership recomputes and
          the owner's (possibly recovered) admission decides again.
        - ``undelivered-command``: re-invoke the command against its
          target assignment.
        Requeue granularity is the PAYLOAD (at-least-once): a multi-device
        payload whose other rows already processed re-ingests those rows
        too, exactly like the reference's reprocess topic redelivering a
        whole record.
        """
        from sitewhere_tpu.ingest.decoders import DecodeError, JsonLinesDecoder
        from sitewhere_tpu.services.common import EntityNotFound, ValidationError

        try:
            raw = self.dead_letters.read_one(int(offset))
        except KeyError:
            raise EntityNotFound(f"dead letter {offset} (pruned or invalid)")
        try:
            doc = json.loads(raw)
        except ValueError:
            raise ValidationError(f"dead letter {offset} is not requeueable "
                                  f"(corrupt record)")
        kind = doc.get("kind")
        if int(offset) in self._requeued_dead_letters():
            # idempotent retry: a second POST must not re-deliver
            return {"requeued": False, "kind": kind, "already": True,
                    "reason": "record was already requeued"}
        # same default the dispatcher's crash recovery uses
        decoder = self.dispatcher.recovery_decoder or JsonLinesDecoder()
        if kind == "forward-shed" and "payload" in doc:
            from sitewhere_tpu.runtime.overload import OverloadShed

            if self.forwarder is None:
                return {"requeued": False, "kind": kind,
                        "reason": "no forwarder on this host"}
            payload = bytes.fromhex(doc["payload"])
            try:
                self.forwarder.ingest_payload(payload, source_id="requeue")
            except OverloadShed as e:
                # owner still shedding: the record stays un-requeued so
                # the operator can retry after the fleet recovers
                return {"requeued": False, "kind": kind,
                        "reason": f"owner still shedding: {e}"}
            self._mark_requeued(offset)
            return {"requeued": True, "kind": kind,
                    "rows": payload.count(b"\n") + 1}
        if kind in ("failed-decode", "failed-stream-request",
                    "intake-shed", "tenant-budget") and "payload" in doc:
            payload = bytes.fromhex(doc["payload"])
            try:
                reqs = decoder(payload)
            except DecodeError as e:
                self.dispatcher.ingest_failed_decode(
                    payload, doc.get("source", "requeue"), e)
                return {"requeued": False, "kind": kind,
                        "reason": f"decode failed again: {e}"}
            if not reqs:
                return {"requeued": False, "kind": kind,
                        "reason": "decode failed again: no rows decoded"}
            from sitewhere_tpu.ingest.decoders import RequestKind

            from sitewhere_tpu.runtime.overload import OverloadShed

            events = [r for r in reqs if r.event_type is not None]
            if kind == "tenant-budget" and events:
                # budget replay carries the shedding tenant: re-stamp
                # rows that lost their metadata so the re-ingest below
                # re-checks THAT tenant's current composed budget, not
                # the default tenant's
                tenant = doc.get("tenant")
                if tenant:
                    for r in events:
                        if r.metadata is None or "tenant" not in r.metadata:
                            r.metadata = dict(r.metadata or {},
                                              tenant=tenant)
            if events:
                try:
                    self.dispatcher.ingest_many(events, payload,
                                                source_id="requeue")
                except OverloadShed as e:
                    # still overloaded / still over budget: the record
                    # stays un-requeued so the operator can retry after
                    # recovery (or after raising the tenant's budget)
                    reason = ("still over tenant budget"
                              if kind == "tenant-budget"
                              else "refused by admission")
                    return {"requeued": False, "kind": kind,
                            "reason": f"{reason}: {e}"}
            rows = len(events)
            for r in reqs:
                if r.event_type is not None:
                    continue
                if r.kind == RequestKind.REGISTRATION:
                    self.dispatcher.ingest_registration(r)
                else:
                    # host-plane (stream) request — re-route; a repeat
                    # failure dead-letters a fresh record
                    self._on_host_request(r, payload)
                    rows += 1
            self._mark_requeued(offset)
            return {"requeued": True, "kind": kind, "rows": rows}
        if kind == "unregistered" and doc.get("refs"):
            rows = 0
            missing: List[int] = []
            for ref in doc["refs"]:
                try:
                    payload = self.ingest_journal.read_one(int(ref))
                    reqs = [r for r in decoder(payload)
                            if r.event_type is not None]
                except Exception:
                    missing.append(int(ref))
                    continue
                if reqs:
                    self.dispatcher.ingest_many(reqs, payload)
                    rows += len(reqs)
            if rows > 0:
                self._mark_requeued(offset)
            return {"requeued": rows > 0, "kind": kind, "rows": rows,
                    **({"unreadable_refs": missing} if missing else {})}
        if kind == "device-poison" and doc.get("columns"):
            # poison rows isolated by the dispatcher's bisect
            # (_dead_letter_poison): the document carries the raw host
            # columns, so the rows re-enter the normal batch path
            # exactly as fresh ingest — requeue AFTER the producer-side
            # corruption is fixed (or to reproduce the quarantine)
            import numpy as np

            from sitewhere_tpu.ingest.batcher import _COL_FIELDS, _DTYPE
            from sitewhere_tpu.runtime.overload import OverloadShed

            columns = doc["columns"]
            if "device_id" not in columns:
                return {"requeued": False, "kind": kind,
                        "reason": "poison record lacks device_id column"}
            cols = {
                field: np.asarray(columns[field],
                                  dtype=_DTYPE.get(field, np.float32))
                for field in _COL_FIELDS if field in columns
            }
            try:
                rows = self.dispatcher.requeue_rows(cols)
            except OverloadShed as e:
                return {"requeued": False, "kind": kind,
                        "reason": f"refused by admission: {e}"}
            self._mark_requeued(offset)
            return {"requeued": True, "kind": kind, "rows": rows}
        if kind == "undelivered-command" and doc.get("command") \
                and doc.get("assignment"):
            ok = self.commands.invoke(CommandInvocation(
                command_token=doc["command"],
                target_assignment=doc["assignment"],
                parameter_values=doc.get("parameterValues", {}),
                initiator="REQUEUE",
            ))
            if ok:
                self._mark_requeued(offset)
            # a repeat failure has already dead-lettered a fresh record
            return {"requeued": bool(ok), "kind": kind,
                    **({} if ok else {"reason": "delivery failed again"})}
        return {"requeued": False, "kind": kind,
                "reason": "record kind is not requeueable"}

    def create_command_invocation(self, assignment_token: str,
                                  command_token: str,
                                  parameter_values: Optional[Dict[str, str]] = None,
                                  initiator: str = "REST",
                                  initiator_id: Optional[str] = None,
                                  ts_s: Optional[int] = None) -> dict:
        """Create a command-invocation EVENT for an assignment: journal
        the invocation body and let the pipeline's command-row egress
        deliver it (reference: REST creates an invocation event which
        flows enriched-command-invocations → command-delivery,
        SURVEY.md §3.4).  One delivery path — a direct ``commands.invoke``
        would double-deliver.  Raises EntityNotFound when the assignment
        is not on THIS host; the web layer federates that case over the
        fabric to the owner (``command.invoke``)."""
        import json as _json

        from sitewhere_tpu.ingest.decoders import DecodedRequest, RequestKind
        from sitewhere_tpu.services.common import mint_token, now_s

        assignment = self.device_management.get_device_assignment(
            assignment_token)
        device = self.device_management.get_device(assignment.device)
        inv_token = mint_token("inv")
        event_ts = int(ts_s if ts_s is not None else now_s())
        payload = _json.dumps({
            "deviceToken": device.token,
            "type": "commandinvocation",
            "request": {
                "commandToken": str(command_token),
                "assignmentToken": assignment_token,
                "parameterValues": dict(parameter_values or {}),
                "initiator": initiator,
                "initiatorId": initiator_id,
                "invocationToken": inv_token,
                # crash replay re-decodes this payload: without the
                # eventDate the recovered row would be stamped 1970 and
                # immediately TTL-pruned
                "eventDate": event_ts,
            },
        }).encode()
        self.dispatcher.ingest(DecodedRequest(
            kind=RequestKind.COMMAND_INVOCATION,
            device_token=device.token,
            ts_s=event_ts,
            # the invocation row carries the invocation handle so its
            # responses (correlated by the same token) query directly
            originating_event=inv_token,
        ), payload)
        self.dispatcher.flush()
        return {"queued": True, "token": inv_token,
                "deviceToken": device.token,
                "host": self.instance_id}

    def invoke_command(self, assignment_token: str, command_token: str,
                       parameter_values: Optional[Dict[str, str]] = None,
                       initiator: str = "REST",
                       initiator_id: Optional[str] = None,
                       ts_s: Optional[int] = None) -> dict:
        """Federated invocation: run locally when this host owns the
        assignment, otherwise route over the fabric to the owner (the
        reference's web-rest demuxing management calls to the owning
        service instance, SURVEY.md §3.3-3.4).  An unreachable peer makes
        the outcome AMBIGUOUS (it may have queued before dying) — that
        surfaces as a 5xx-class ServiceError, never a definitive 404 that
        would invite a double-delivering retry."""
        from sitewhere_tpu.services.common import EntityNotFound, ServiceError

        kwargs = dict(command_token=command_token,
                      parameter_values=parameter_values,
                      initiator=initiator, initiator_id=initiator_id,
                      ts_s=ts_s)
        try:
            return self.create_command_invocation(assignment_token, **kwargs)
        except EntityNotFound:
            from sitewhere_tpu.rpc.channel import RpcError

            ambiguous = False
            for _p, demux in sorted(self._peer_demuxes.items()):
                if demux is None:
                    continue
                try:
                    # short per-peer timeout: one hung peer must not
                    # stall the caller's thread for the 30s default
                    # times the fleet size
                    result, _ = demux.call("command.invoke", {
                        "assignmentToken": assignment_token,
                        "commandToken": command_token,
                        "parameterValues": dict(parameter_values or {}),
                        "initiator": initiator,
                        "initiatorId": initiator_id,
                        "ts": ts_s,
                    }, timeout_s=5.0)
                    return result
                except RpcError as e:
                    if e.error != "not_found":
                        raise
                except Exception:
                    ambiguous = True   # peer may have queued before dying
            if ambiguous:
                raise ServiceError(
                    f"assignment {assignment_token} not found locally and "
                    "a peer was unreachable — invocation state unknown; "
                    "retrying may double-deliver")
            raise

    def cluster_topology(self) -> dict:
        """Every host's topology, aggregated over the fabric (reference:
        ``TopologyStateAggregator.java:40-113`` consumes all
        microservices' state heartbeats into one live cluster view).  A
        peer that doesn't answer reports as unreachable rather than
        failing the whole view."""
        import threading

        view = {"local": self.topology(), "peers": {}}

        def poll(p, demux):
            try:
                body, _ = demux.call("instance.topology", timeout_s=2.0)
                view["peers"][str(p)] = body
            except Exception as e:   # noqa: BLE001 — degraded view, not error
                view["peers"][str(p)] = {"unreachable": str(e)}

        # concurrent polls: k dead peers cost ONE timeout, not k — the
        # endpoint exists to diagnose exactly that outage
        threads = [threading.Thread(target=poll, args=(p, d), daemon=True)
                   for p, d in sorted(self._peer_demuxes.items())
                   if d is not None]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=5.0)
        return view
