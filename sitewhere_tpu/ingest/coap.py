"""CoAP server receiver — RFC 7252 message codec over UDP.

Reference: ``service-event-sources/src/main/java/com/sitewhere/sources/
coap/CoapServerEventReceiver.java`` (+ ``CoapMessageDeliverer.java``): a
Californium CoAP server terminates constrained-device traffic; devices
POST JSON event payloads and the payload bytes flow into the source's
decoder exactly like any other receiver's.

This is a from-scratch RFC 7252 implementation (no CoAP library in the
image): 4-byte header (Ver|Type|TKL, Code, Message ID), token, delta-
encoded options with 13/14 extended forms, 0xFF payload marker.  The
server accepts POST/PUT (CON → piggybacked ACK 2.04, NON → no reply),
answers GET/DELETE with 4.05 Method Not Allowed, and RSTs malformed or
non-request messages per §4.2/§4.3.  The codec is symmetric so the
command-delivery CoAP destination and tests reuse it as a client.
"""

from __future__ import annotations

import dataclasses
import logging
import socket
import struct
from collections import OrderedDict
from typing import List, Optional, Tuple

from sitewhere_tpu.ingest.sources import Receiver
from sitewhere_tpu.runtime.overload import OverloadShed

logger = logging.getLogger("sitewhere_tpu.ingest.coap")

# Message types (§3)
CON, NON, ACK, RST = 0, 1, 2, 3

# Method / response codes as (class, detail) → the on-wire c.dd byte
GET, POST, PUT, DELETE = 0x01, 0x02, 0x03, 0x04
CHANGED_204 = (2 << 5) | 4       # 2.04 Changed
CREATED_201 = (2 << 5) | 1       # 2.01 Created
BAD_REQUEST_400 = (4 << 5) | 0   # 4.00
NOT_ALLOWED_405 = (4 << 5) | 5   # 4.05
UNAVAILABLE_503 = (5 << 5) | 3   # 5.03 Service Unavailable

OPT_URI_PATH = 11
OPT_CONTENT_FORMAT = 12
OPT_MAX_AGE = 14


def _uint_option(value: int) -> bytes:
    """Encode a CoAP uint option value (§3.2: minimal big-endian)."""
    value = max(0, int(value))
    out = b""
    while value:
        out = bytes([value & 0xFF]) + out
        value >>= 8
    return out


class CoapError(Exception):
    pass


@dataclasses.dataclass
class CoapMessage:
    """One parsed/encodable CoAP message (§3 framing)."""

    mtype: int                      # CON/NON/ACK/RST
    code: int                       # method or response code byte
    message_id: int
    token: bytes = b""
    options: List[Tuple[int, bytes]] = dataclasses.field(default_factory=list)
    payload: bytes = b""
    version: int = 1

    @property
    def uri_path(self) -> str:
        return "/" + "/".join(
            v.decode("utf-8", "replace")
            for n, v in self.options if n == OPT_URI_PATH
        )

    def option(self, number: int) -> Optional[bytes]:
        for n, v in self.options:
            if n == number:
                return v
        return None


def _ext(value: int) -> Tuple[int, bytes]:
    """Encode an option delta/length nibble + extension bytes (§3.1)."""
    if value < 13:
        return value, b""
    if value < 269:
        return 13, bytes([value - 13])
    return 14, struct.pack("!H", value - 269)


def encode_message(msg: CoapMessage) -> bytes:
    if not 0 <= len(msg.token) <= 8:
        raise CoapError("token length 0..8")
    out = bytearray()
    out.append((msg.version << 6) | (msg.mtype << 4) | len(msg.token))
    out.append(msg.code)
    out += struct.pack("!H", msg.message_id)
    out += msg.token
    prev = 0
    for number, value in sorted(msg.options, key=lambda o: o[0]):
        dn, dext = _ext(number - prev)
        ln, lext = _ext(len(value))
        out.append((dn << 4) | ln)
        out += dext + lext + value
        prev = number
    if msg.payload:
        out.append(0xFF)
        out += msg.payload
    return bytes(out)


def _read_ext(nibble: int, data: bytes, pos: int) -> Tuple[int, int]:
    if nibble < 13:
        return nibble, pos
    if nibble == 13:
        if pos >= len(data):
            raise CoapError("truncated option extension")
        return data[pos] + 13, pos + 1
    if nibble == 14:
        if pos + 2 > len(data):
            raise CoapError("truncated option extension")
        return struct.unpack_from("!H", data, pos)[0] + 269, pos + 2
    raise CoapError("reserved option nibble 15")


def parse_message(data: bytes) -> CoapMessage:
    if len(data) < 4:
        raise CoapError("short datagram")
    b0 = data[0]
    version = b0 >> 6
    if version != 1:
        raise CoapError(f"unsupported version {version}")
    mtype = (b0 >> 4) & 0x3
    tkl = b0 & 0xF
    if tkl > 8:
        raise CoapError("token length > 8")
    code = data[1]
    (message_id,) = struct.unpack_from("!H", data, 2)
    pos = 4
    if pos + tkl > len(data):
        raise CoapError("truncated token")
    token = data[pos:pos + tkl]
    pos += tkl
    options: List[Tuple[int, bytes]] = []
    number = 0
    payload = b""
    while pos < len(data):
        byte = data[pos]
        pos += 1
        if byte == 0xFF:
            payload = data[pos:]
            if not payload:
                raise CoapError("payload marker with empty payload")
            break
        delta, pos = _read_ext(byte >> 4, data, pos)
        length, pos = _read_ext(byte & 0xF, data, pos)
        if pos + length > len(data):
            raise CoapError("truncated option value")
        number += delta
        options.append((number, data[pos:pos + length]))
        pos += length
    return CoapMessage(mtype=mtype, code=code, message_id=message_id,
                       token=token, options=options, payload=payload,
                       version=version)


class CoapServerReceiver(Receiver):
    """RFC 7252 UDP server: device POSTs become source payloads.

    Piggybacked responses (§5.2.1): CON POST/PUT → ACK 2.04 with the
    request's message id + token; NON POST/PUT → processed silently;
    other methods → 4.05; malformed CON/NON → RST; stray ACK/RST from
    clients are ignored (§4.2).
    """

    # Retransmission dedup window (RFC 7252 §4.5): EXCHANGE_LIFETIME is
    # ~247s; a bounded LRU keyed on (endpoint, message id) covers it at
    # realistic rates while bounding memory.
    DEDUP_CAPACITY = 4096

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        super().__init__(name=f"coap-receiver:{port}")
        # the piggybacked ACK 2.04 is sent only after _emit returns: the
        # client's CON retransmission is the redelivery cue, so the
        # ingest decode pool must keep this source synchronous
        self.acks_on_emit = True
        self.host, self.port = host, port
        self._sock: Optional[socket.socket] = None
        self._alive = False
        self.bad_messages = 0
        self.duplicates = 0
        self.emit_errors = 0
        # (addr, message_id) → cached reply bytes (None for NON, §4.5:
        # the dup is silently ignored when there is nothing to retransmit)
        self._seen: "OrderedDict[tuple, Optional[bytes]]" = OrderedDict()

    def _bind(self) -> None:
        if self._sock is None:
            self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            self._sock.bind((self.host, self.port))
            self.port = self._sock.getsockname()[1]

    def start(self) -> None:
        self._bind()
        self._alive = True
        # Supervised (ROADMAP: remaining-receiver chaos coverage): an
        # unexpected socket death restarts the loop with backoff and
        # rebinds the SAME port (datagrams sent during the backoff sit
        # in the kernel buffer); repeated failures escalate terminally.
        self._spawn_supervised(self._run)
        super().start()

    def stop(self) -> None:
        self._alive = False
        if self._sock is not None:
            self._sock.close()
            self._sock = None
        self._stop_supervisor()
        super().stop()

    def _run(self) -> None:
        self._bind()   # restart after a crash that closed the socket
        while self._alive:
            sock = self._sock
            if sock is None:
                return   # stop() tore the socket down mid-iteration
            try:
                data, addr = sock.recvfrom(65536)
            except OSError:
                if not self._alive:
                    return   # clean shutdown closed the socket
                # release the port before the supervised restart rebinds
                # it (same contract as UdpReceiver._run)
                sock, self._sock = self._sock, None
                if sock is not None:
                    try:
                        sock.close()
                    except OSError:
                        pass
                raise        # unexpected socket death → supervisor restarts
            if not data:
                continue
            try:
                reply = self._handle(data, addr)
            except CoapError as e:
                self.bad_messages += 1
                reply = self._rst_for(data)
                logger.debug("bad CoAP datagram from %s: %s", addr, e)
            except Exception:
                # sink/emit crash: datagram-local, like a TCP
                # connection-local crash — NO reply goes out and the
                # (addr, mid) is NOT cached, so the client's CON
                # retransmission re-emits the payload (CoAP's
                # redelivery semantics); the server loop keeps serving
                self.emit_errors += 1
                logger.exception("CoAP handler failed")
                continue
            if reply is not None:
                try:
                    sock.sendto(reply, addr)
                except OSError:
                    if not self._alive:
                        return
                    raise

    def _handle(self, data: bytes, addr) -> Optional[bytes]:
        msg = parse_message(data)
        if msg.mtype in (ACK, RST):
            return None  # client-side message; nothing to do (§4.2)
        # Retransmission dedup (§4.5): a retried CON whose ACK was lost
        # must get the SAME response back without re-emitting the payload.
        key = (addr, msg.message_id)
        if key in self._seen:
            self.duplicates += 1
            self._seen.move_to_end(key)
            return self._seen[key]
        options: List[Tuple[int, bytes]] = []
        if msg.code in (POST, PUT):
            if msg.payload:
                try:
                    self._emit(msg.payload)
                    code = CHANGED_204
                except OverloadShed as e:
                    # CoAP-native backpressure (§5.9.3.4): 5.03 with
                    # Max-Age as the retry hint — the constrained
                    # client backs off instead of retransmitting hot
                    code = UNAVAILABLE_503
                    options.append((OPT_MAX_AGE, _uint_option(
                        max(1, int(round(e.retry_after_s))))))
            else:
                code = BAD_REQUEST_400
        elif msg.code in (GET, DELETE):
            code = NOT_ALLOWED_405
        else:
            # response code in a CON/NON request slot: reject
            raise CoapError(f"unexpected code {msg.code:#x}")
        reply = None
        if msg.mtype == CON:
            reply = encode_message(CoapMessage(
                mtype=ACK, code=code, message_id=msg.message_id,
                token=msg.token, options=options,
            ))
        self._seen[key] = reply
        while len(self._seen) > self.DEDUP_CAPACITY:
            self._seen.popitem(last=False)
        return reply

    @staticmethod
    def _rst_for(data: bytes) -> Optional[bytes]:
        """Best-effort RST echoing the (possibly torn) message id (§4.3)."""
        if len(data) < 4 or data[0] >> 6 != 1:
            return None
        (mid,) = struct.unpack_from("!H", data, 2)
        return encode_message(CoapMessage(mtype=RST, code=0, message_id=mid))


def encode_post(path: str, payload: bytes, message_id: int,
                token: bytes = b"", confirmable: bool = True,
                content_format: int = 50) -> bytes:
    """Client-side helper: a POST request datagram (50 = application/json)."""
    options: List[Tuple[int, bytes]] = [
        (OPT_URI_PATH, seg.encode()) for seg in path.strip("/").split("/")
        if seg
    ]
    if content_format is not None:
        options.append((
            OPT_CONTENT_FORMAT,
            bytes([content_format]) if content_format < 256
            else struct.pack("!H", content_format),
        ))
    return encode_message(CoapMessage(
        mtype=CON if confirmable else NON, code=POST,
        message_id=message_id, token=token, options=options,
        payload=payload,
    ))
