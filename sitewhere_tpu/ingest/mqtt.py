"""Minimal MQTT 3.1.1 client over stdlib sockets (QoS 0/1).

The reference wraps the fusesource mqtt-client in
``sitewhere-communication/.../mqtt/MqttLifecycleComponent.java`` and builds
event receivers (``sources/mqtt/MqttInboundEventReceiver.java:39``) and
command destinations (``destination/mqtt/MqttCommandDestination.java``) on
it.  No MQTT library is available in this image, so this module implements
the small protocol subset both sides need: CONNECT/CONNACK,
SUBSCRIBE/SUBACK, PUBLISH (+PUBACK for QoS 1), PINGREQ/PINGRESP,
DISCONNECT.  TLS wraps the socket via ``ssl.SSLContext`` when given
(reference supports TLS brokers).
"""

from __future__ import annotations

import socket
import ssl
import struct
import threading
import time
from typing import Callable, Optional, Tuple

# Packet types (<<4 in the fixed header).
CONNECT, CONNACK = 1, 2
PUBLISH, PUBACK = 3, 4
SUBSCRIBE, SUBACK = 8, 9
UNSUBSCRIBE, UNSUBACK = 10, 11
PINGREQ, PINGRESP = 12, 13
DISCONNECT = 14


class MqttError(Exception):
    pass


def _encode_remaining(n: int) -> bytes:
    out = bytearray()
    while True:
        byte = n % 128
        n //= 128
        out.append(byte | (0x80 if n else 0))
        if not n:
            return bytes(out)


def _utf8(s: str) -> bytes:
    b = s.encode("utf-8")
    return struct.pack(">H", len(b)) + b


def _read_exact(sock: socket.socket, n: int, interruptible: bool = False) -> bytes:
    """Read exactly n bytes.  With ``interruptible`` a timeout before the
    FIRST byte propagates (idle poll); a timeout mid-read keeps waiting so
    a slow sender can't desynchronize the packet stream."""
    buf = b""
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except socket.timeout:
            if interruptible and not buf:
                raise
            continue
        if not chunk:
            raise MqttError("connection closed")
        buf += chunk
    return buf


def read_packet(sock: socket.socket, interruptible: bool = False) -> Tuple[int, int, bytes]:
    """Read one packet: returns (type, flags, payload)."""
    head = _read_exact(sock, 1, interruptible=interruptible)[0]
    remaining, shift = 0, 0
    while True:
        byte = _read_exact(sock, 1)[0]
        remaining |= (byte & 0x7F) << shift
        if not byte & 0x80:
            break
        shift += 7
        if shift > 21:
            raise MqttError("bad remaining length")
    body = _read_exact(sock, remaining) if remaining else b""
    return head >> 4, head & 0x0F, body


def write_publish(
    sock: socket.socket, topic: str, payload: bytes, qos: int = 0,
    packet_id: int = 1, retain: bool = False,
) -> None:
    flags = (qos << 1) | (1 if retain else 0)
    var = _utf8(topic)
    if qos > 0:
        var += struct.pack(">H", packet_id)
    body = var + payload
    sock.sendall(bytes([PUBLISH << 4 | flags]) + _encode_remaining(len(body)) + body)


def parse_publish(flags: int, body: bytes) -> Tuple[str, bytes, int, int]:
    """Returns (topic, payload, qos, packet_id)."""
    (tlen,) = struct.unpack_from(">H", body, 0)
    topic = body[2 : 2 + tlen].decode("utf-8")
    pos = 2 + tlen
    qos = (flags >> 1) & 0x3
    packet_id = 0
    if qos:
        (packet_id,) = struct.unpack_from(">H", body, pos)
        pos += 2
    return topic, body[pos:], qos, packet_id


class MqttClient:
    """Blocking MQTT client; a background thread pumps inbound packets.

    ``on_message(topic, payload)`` runs on the pump thread — hand off to a
    worker pool for slow work (the reference uses a processing pool for the
    same reason, ``MqttInboundEventReceiver.java:194``).
    """

    def __init__(
        self,
        host: str,
        port: int = 1883,
        client_id: str = "sitewhere-tpu",
        keepalive: int = 60,
        username: Optional[str] = None,
        password: Optional[str] = None,
        tls: Optional[ssl.SSLContext] = None,
        connect_timeout: float = 10.0,
    ):
        self.host, self.port = host, port
        self.client_id = client_id
        self.keepalive = keepalive
        self.username, self.password = username, password
        self.tls = tls
        self.connect_timeout = connect_timeout
        self.on_message: Optional[Callable[[str, bytes], None]] = None
        self._sock: Optional[socket.socket] = None
        self._pump: Optional[threading.Thread] = None
        self._alive = False
        self._packet_id = 0
        self._suback = threading.Event()
        self._lock = threading.Lock()
        # QoS-1 publishes outstanding (pid → sent): the publisher half of
        # at-least-once — disconnect() drains these so closing the socket
        # can never race the broker out of handling a still-buffered
        # publish (an early close RSTs the connection and poisons the
        # broker's receive buffer).
        self._unacked: set = set()
        self._acked = threading.Condition(self._lock)
        self._last_send = time.monotonic()

    # -- connection ---------------------------------------------------------

    def connect(self) -> None:
        sock = socket.create_connection(
            (self.host, self.port), timeout=self.connect_timeout
        )
        if self.tls is not None:
            sock = self.tls.wrap_socket(sock, server_hostname=self.host)
        flags = 0x02  # clean session
        if self.username:
            flags |= 0x80
            if self.password:
                flags |= 0x40
        body = _utf8("MQTT") + bytes([4, flags]) + struct.pack(">H", self.keepalive)
        body += _utf8(self.client_id)
        if self.username:
            body += _utf8(self.username)
            if self.password:
                body += _utf8(self.password)
        sock.sendall(bytes([CONNECT << 4]) + _encode_remaining(len(body)) + body)
        ptype, _, ack = read_packet(sock)
        if ptype != CONNACK or len(ack) < 2 or ack[1] != 0:
            raise MqttError(f"CONNACK refused: {ack!r}")
        # Short poll timeout so keepalive pings fire even under steady
        # inbound traffic (MQTT keepalive counts CLIENT→server packets).
        sock.settimeout(max(0.5, min(self.keepalive / 4, 10.0)))
        self._sock = sock
        # clean session: a pid a dead prior session never got acked can
        # never be acked by THIS session — carrying it over would stall
        # every later drain_publishes for its full timeout
        self._unacked.clear()
        self._alive = True
        self._last_send = time.monotonic()
        self._pump = threading.Thread(target=self._pump_loop, daemon=True,
                                      name=f"mqtt-pump-{self.client_id}")
        self._pump.start()

    def disconnect(self) -> None:
        if self._sock is not None and self._alive:
            # publisher-side at-least-once: don't close under in-flight
            # QoS-1 publishes (see _unacked)
            self.drain_publishes(timeout=5.0)
        self._alive = False
        if self._sock is not None:
            try:
                self._sock.sendall(bytes([DISCONNECT << 4, 0]))
            except OSError:
                pass
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        if self._pump is not None:
            self._pump.join(timeout=2.0)
            self._pump = None

    # -- pub/sub ------------------------------------------------------------

    def _next_packet_id(self) -> int:
        self._packet_id = self._packet_id % 65535 + 1
        return self._packet_id

    def subscribe(self, topic: str, qos: int = 0, timeout: float = 10.0) -> None:
        if self._sock is None:
            raise MqttError("not connected")
        self._suback.clear()
        pid = self._next_packet_id()
        body = struct.pack(">H", pid) + _utf8(topic) + bytes([qos])
        with self._lock:
            self._sock.sendall(
                bytes([SUBSCRIBE << 4 | 0x02]) + _encode_remaining(len(body)) + body
            )
            self._last_send = time.monotonic()
        if not self._suback.wait(timeout):
            raise MqttError(f"no SUBACK for {topic!r}")

    def publish(self, topic: str, payload: bytes, qos: int = 0,
                retain: bool = False) -> None:
        if self._sock is None:
            raise MqttError("not connected")
        with self._lock:
            pid = self._next_packet_id()
            if qos:
                self._unacked.add(pid)
            try:
                write_publish(self._sock, topic, payload, qos, pid, retain)
            except BaseException:
                # never sent → never acked: leaking the pid would stall
                # every later drain_publishes/disconnect for its timeout
                self._unacked.discard(pid)
                raise
            self._last_send = time.monotonic()

    def drain_publishes(self, timeout: float = 5.0) -> bool:
        """Wait until every QoS-1 publish has been PUBACKed (or timeout);
        returns True when fully drained."""
        deadline = time.monotonic() + timeout
        with self._acked:
            while self._unacked:
                left = deadline - time.monotonic()
                if left <= 0 or not self._alive:
                    return not self._unacked
                self._acked.wait(left)
        return True

    # -- inbound pump -------------------------------------------------------

    def _maybe_ping(self) -> None:
        if self.keepalive <= 0 or self._sock is None:
            return
        now = time.monotonic()
        if now - self._last_send >= self.keepalive / 2:
            with self._lock:
                self._sock.sendall(bytes([PINGREQ << 4, 0]))
                self._last_send = now

    def _pump_loop(self) -> None:
        try:
            self._pump_packets()
        finally:
            # a dead pump can never see another PUBACK: wake any drain
            # waiter immediately instead of letting it sleep its timeout
            with self._acked:
                self._alive = False
                self._acked.notify_all()

    def _pump_packets(self) -> None:
        while self._alive and self._sock is not None:
            try:
                self._maybe_ping()
                ptype, flags, body = read_packet(self._sock, interruptible=True)
            except socket.timeout:
                continue  # idle poll window — loop for the keepalive check
            except (MqttError, OSError):
                break
            if ptype == PUBLISH:
                topic, payload, qos, pid = parse_publish(flags, body)
                if qos == 1:
                    with self._lock:
                        self._sock.sendall(
                            bytes([PUBACK << 4, 2]) + struct.pack(">H", pid)
                        )
                        self._last_send = time.monotonic()
                if self.on_message is not None:
                    try:
                        self.on_message(topic, payload)
                    except Exception:
                        # A broken callback must not kill inbound MQTT.
                        import logging

                        logging.getLogger("sitewhere_tpu.ingest").exception(
                            "mqtt on_message failed for topic %s", topic
                        )
            elif ptype == SUBACK:
                self._suback.set()
            elif ptype == PUBACK:
                if len(body) >= 2:  # short body: tolerate, don't kill pump
                    (pid,) = struct.unpack_from(">H", body, 0)
                    with self._acked:
                        self._unacked.discard(pid)
                        self._acked.notify_all()
            elif ptype == PINGRESP:
                pass
