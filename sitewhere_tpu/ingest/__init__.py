"""Host-side ingest: protocol frontends → decode → dedupe → batch → journal.

Replaces the reference's ``service-event-sources`` (receivers + decoders +
deduplicators, ``sources/InboundEventSource.java:35-309``) and the Kafka
durability layer (``MicroserviceKafkaProducer/Consumer``): events enter via
protocol frontends, are decoded to typed requests, deduplicated, appended to
a durable journal with offsets (the Kafka-topic analog), and assembled into
fixed-shape :class:`~sitewhere_tpu.schema.EventBatch` tensors routed by
owning shard for the SPMD pipeline step.
"""

from sitewhere_tpu.ingest.journal import Journal, JournalReader  # noqa: F401
from sitewhere_tpu.ingest.decoders import (  # noqa: F401
    DecodedRequest,
    RequestKind,
    JsonDecoder,
    JsonBatchDecoder,
    BinaryDecoder,
    CompositeDecoder,
    DecodeError,
    JsonLinesDecoder,
)
from sitewhere_tpu.ingest.dedup import AlternateIdDeduplicator  # noqa: F401
from sitewhere_tpu.ingest.coap import CoapServerReceiver  # noqa: F401
from sitewhere_tpu.ingest.amqp import AmqpReceiver  # noqa: F401
from sitewhere_tpu.ingest.stomp import StompReceiver  # noqa: F401
from sitewhere_tpu.ingest.columnar import decode_json_lines  # noqa: F401
from sitewhere_tpu.ingest.batcher import Batcher, BatchPlan  # noqa: F401
