"""Inbound event sources: protocol receivers + decode + dedupe + forward.

Reference: ``service-event-sources`` — an ``InboundEventSource`` composes a
list of ``IInboundEventReceiver`` s with one ``IDeviceEventDecoder`` and an
optional deduplicator (``sources/InboundEventSource.java:35-309``;
``onEncodedEventReceived:189-199`` → decode → dedupe → forward), and the
``EventSourcesManager`` forwards decoded events / registrations / failed
decodes to their Kafka topics (``EventSourcesManager.java:153-189``).

Here the forward targets are callables (wired to journals + batcher by the
runtime), and receivers are threads owning sockets:

- :class:`TcpReceiver` — raw TCP with pluggable framing (reference:
  ``socket/SocketInboundEventReceiver.java`` + interaction handlers).
- :class:`UdpReceiver` — one datagram = one raw payload.
- :class:`HttpReceiver` — HTTP POST endpoint (reference REST receivers).
- :class:`MqttReceiver` — broker subscription via the stdlib MQTT client
  (reference ``mqtt/MqttInboundEventReceiver.java``).
- :class:`PollingRestReceiver` — periodic HTTP GET poll (reference
  ``rest/PollingRestInboundEventReceiver.java``).
- :class:`WebSocketReceiver` — client pulling payloads from a remote WS
  endpoint with auto-reconnect (reference
  ``websocket/WebSocketEventReceiver.java``).
- :class:`sitewhere_tpu.ingest.coap.CoapServerReceiver` — RFC 7252 CoAP
  server (reference ``coap/CoapServerEventReceiver.java``).
- :class:`sitewhere_tpu.ingest.stomp.StompReceiver` — STOMP 1.2 broker
  subscription with per-message acks; ActiveMQ and RabbitMQ both speak
  STOMP natively, so this covers the reference's
  ``activemq/ActiveMQClientEventReceiver.java`` and
  ``rabbitmq/RabbitMqInboundEventReceiver.java`` without their client
  stacks.

Azure EventHub (proprietary AMQP dialect behind SAS auth) stays gated: its
role (durable broker buffering) is covered by the journal + the STOMP/MQTT
receivers, and the receiver SPI accepts new implementations.
"""

from __future__ import annotations

import collections
import errno
import http.server
import logging
import socket
import struct
import threading
import time
import urllib.request
from typing import Callable, Dict, List, Optional

logger = logging.getLogger("sitewhere_tpu.ingest")

from sitewhere_tpu.ingest.decoders import DecodedRequest, DecodeError, RequestKind
from sitewhere_tpu.runtime import faults
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent
from sitewhere_tpu.runtime.overload import OverloadShed
from sitewhere_tpu.runtime.resilience import Backoff, RetryPolicy, Supervisor

Decoder = Callable[[bytes], List[DecodedRequest]]
Forward = Callable[[DecodedRequest, bytes], None]
FailedDecode = Callable[[bytes, str, Exception], None]


class _DecodeJob:
    __slots__ = ("work", "deliver", "result", "error", "done", "delivering")

    def __init__(self, work, deliver):
        self.work = work
        self.deliver = deliver
        self.result = None
        self.error: Optional[BaseException] = None
        self.done = False
        self.delivering = False


class DecodePool:
    """Ordered parallel decode: the host-pipeline stage that lets window
    N+1's payload decode while window N is on device.

    Payloads submitted under the same ``key`` (the source id — the
    sharded sequence key) DECODE on any worker concurrently but DELIVER
    strictly in submission order, so per-device event order and the
    journal's offset↔row correspondence survive the parallelism.  The
    per-key lane is a FIFO of jobs; whichever worker completes the lane's
    head drains every completed head job in order (the ``delivering``
    flag makes that drain single-threaded per lane without a dedicated
    delivery thread).

    ``max_pending`` bounds buffered payloads across all lanes —
    ``submit`` blocks the receiver thread when saturated, which is the
    backpressure that keeps a fast socket from outrunning the pipeline.
    """

    def __init__(self, workers: int = 2, max_pending: int = 128,
                 name: str = "ingest-decode", metrics=None):
        import queue as _queue

        self.name = name
        self.max_pending = int(max_pending)   # overload signal denominator
        self._q: "_queue.Queue" = _queue.Queue()
        self._sem = threading.BoundedSemaphore(max_pending)
        self._lanes: Dict[object, "collections.deque"] = {}
        self._lock = threading.Lock()
        self._alive = True
        self.submitted = 0
        self.delivered = 0
        self.delivery_errors = 0
        if metrics is not None:
            self._m_depth = metrics.gauge("ingest.decode_pool_depth")
            self._m_jobs = metrics.counter("ingest.decode_pool_jobs")
        else:
            self._m_depth = self._m_jobs = None
        self._threads = [
            threading.Thread(target=self._worker, daemon=True,
                             name=f"{name}-{i}")
            for i in range(max(1, int(workers)))
        ]
        for t in self._threads:
            t.start()

    def submit(self, key, work: Callable[[], object],
               deliver: Callable[[object, Optional[BaseException]], None],
               ) -> None:
        """Queue ``work`` (CPU-only, side-effect free) for parallel
        execution; ``deliver(result, error)`` runs later, in per-``key``
        submission order, on a pool thread.  Blocks when the pool's
        pending budget is exhausted (backpressure)."""
        if self._alive:
            self._sem.acquire()
            # Re-check under the lock: a stop() between the check above
            # and the enqueue would strand the job behind the worker
            # sentinels (never executed, permit leaked) — the atomic
            # check-and-enqueue makes every job land either ahead of the
            # sentinels or on the synchronous fallback below.
            with self._lock:
                queued = self._alive
                if queued:
                    job = _DecodeJob(work, deliver)
                    self._lanes.setdefault(key, collections.deque()).append(job)
                    self.submitted += 1
                    self._q.put((key, job))
            if queued:
                if self._m_jobs is not None:
                    self._m_jobs.inc()
                    self._m_depth.set(self.pending)
                return
            self._sem.release()
        # stopped pool: degrade to synchronous (never drop a payload)
        try:
            result = work()
        except Exception as e:  # noqa: BLE001 — mirrors worker path
            deliver(None, e)
            return
        deliver(result, None)

    @property
    def pending(self) -> int:
        with self._lock:
            return sum(len(lane) for lane in self._lanes.values())

    def _worker(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            key, job = item
            try:
                job.result = job.work()
            except BaseException as e:  # noqa: BLE001 — routed to deliver
                job.error = e
            job.done = True
            self._drain(key)

    def _drain(self, key) -> None:
        """Deliver completed head jobs of one lane in order; only one
        thread delivers per lane at a time (the head job it popped is
        gone before any sibling can see the next head)."""
        while True:
            with self._lock:
                lane = self._lanes.get(key)
                if not lane or not lane[0].done or lane[0].delivering:
                    return
                job = lane[0]
                job.delivering = True
            try:
                job.deliver(job.result, job.error)
            except BaseException:  # noqa: BLE001 — a deliver that re-raises
                # a non-Exception (sys.exit in a decoder, a C-extension
                # signal) must not kill the unsupervised worker thread:
                # with every worker dead the queue backs up until the
                # pending semaphore wedges all receiver threads
                self.delivery_errors += 1
                logger.exception("decode pool %s: delivery failed",
                                 self.name)
            finally:
                with self._lock:
                    lane.popleft()
                    if not lane:
                        self._lanes.pop(key, None)
                    self.delivered += 1
                self._sem.release()
                if self._m_depth is not None:
                    self._m_depth.set(self.pending)

    def flush(self, timeout_s: float = 10.0) -> bool:
        """Block until every submitted payload has DELIVERED (tests and
        shutdown: nothing may reach the pipeline after stop returns)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.pending == 0:
                return True
            time.sleep(0.001)
        return self.pending == 0

    def stop(self, timeout_s: float = 10.0) -> None:
        self.flush(timeout_s)
        with self._lock:  # pairs with submit's check-and-enqueue
            self._alive = False
        for _ in self._threads:
            self._q.put(None)
        for t in self._threads:
            t.join(timeout=timeout_s)
        self._threads = []


class InboundEventSource(LifecycleComponent):
    """receivers × decoder × dedup → forward (see module docstring)."""

    def __init__(
        self,
        source_id: str,
        receivers: List["Receiver"],
        decoder: Decoder,
        deduplicator=None,
        on_event: Optional[Forward] = None,
        on_registration: Optional[Forward] = None,
        on_failed_decode: Optional[FailedDecode] = None,
        on_host_request: Optional[Forward] = None,
        on_events: Optional[Callable[[List[DecodedRequest], bytes], None]] = None,
        raw_wire: bool = False,
    ):
        super().__init__(name=f"event-source:{source_id}")
        self.source_id = source_id
        self.receivers = receivers
        self.decoder = decoder
        self.deduplicator = deduplicator
        self.on_event = on_event
        # Batch forward: when set, all of one payload's pipeline events go
        # through a single columnar call (PipelineDispatcher.ingest_many)
        # instead of per-request on_event — the 1M events/sec intake edge.
        self.on_events = on_events
        self.on_registration = on_registration
        self.on_failed_decode = on_failed_decode
        self.on_host_request = on_host_request
        # Raw wire lane (opt-in, config `"raw_wire": true`): NDJSON
        # payloads skip this source's scalar decoder entirely and go to
        # ``on_wire_payload`` (PipelineDispatcher.ingest_wire_lines, or
        # the forwarder's owner-splitting ingest_payload in multi-host
        # topologies) — one C columnar decode + in-scanner token
        # resolution per payload instead of json.loads per line.  The
        # wire lane handles registration/host-plane lines and dead-
        # letters failed payloads itself.  Differences a deployment opts
        # into: no source-level deduplication (``dedup`` config is
        # rejected with it) and per-request ``metadata.tenant`` routing
        # is not applied (wire rows land in the default tenant).
        self.raw_wire = raw_wire
        self.on_wire_payload: Optional[Callable[[bytes, str], int]] = None
        # Overlapped decode (host-pipeline stage 1): with a DecodePool
        # attached, the CPU-heavy decode of each payload runs on a pool
        # worker while earlier windows are on device; the ordered
        # delivery stage (journal + forward) keeps per-source submission
        # order.  ``on_wire_decode``/``on_wire_decoded`` are the split
        # halves of the wire lane (PipelineDispatcher.decode_wire_lines /
        # ingest_wire_decoded).  On the fill-direct path the decode half
        # returns a batcher Reservation (scanned in place on the pool
        # worker, PRIVATE until commit) riding the same ``(columns,
        # host_reqs)`` tuple — the delivery half commits it in
        # submission order, so the zero-copy scan parallelizes without
        # reordering rows.  The pool is ONLY used when no receiver
        # gates a broker ack on the emit call returning
        # (``acks_on_emit``): for those (MQTT broker intake, STOMP
        # client-individual) an async decode would acknowledge a payload
        # the journal has not seen yet — a durability regression — so
        # they keep the synchronous path and their redelivery semantics.
        self.decode_pool: Optional[DecodePool] = None
        self.on_wire_decode: Optional[Callable[[bytes], object]] = None
        self.on_wire_decoded: Optional[Callable[..., int]] = None
        self.decoded_count = 0
        self.failed_count = 0
        self.duplicate_count = 0
        self.shed_count = 0
        self.dropped_host_requests = 0
        for r in receivers:
            r.sink = self.on_encoded_payload
            self.add_child(r)

    def _pool_usable(self) -> bool:
        """May this source decode asynchronously?  Requires an attached
        pool, the split wire callables (for the wire lane), and NO
        ack-gated receiver (see ``decode_pool`` comment above)."""
        if self.decode_pool is None:
            return False
        if any(getattr(r, "acks_on_emit", False) for r in self.receivers):
            return False
        if self.raw_wire:
            return self.on_wire_decode is not None \
                and self.on_wire_decoded is not None
        return True

    def on_encoded_payload(self, payload: bytes) -> None:
        """Receiver callback (reference ``onEncodedEventReceived:189-199``).

        Never lets an exception escape into the transport thread: decode
        failures dead-letter; forward-target failures are logged and
        counted (a broken sink must not kill the receiver).

        With a decode pool attached (and no ack-gated receiver) the
        CPU-heavy decode stage runs on a pool worker — window N+1
        decodes while window N is on device — and the forward stage
        (journal + batch) runs later in per-source submission order.
        """
        if self._pool_usable():
            self.decode_pool.submit(
                self.source_id,
                lambda: self._decode_stage(payload),
                lambda result, exc: self._pool_deliver(payload, result, exc),
            )
            return
        try:
            result = self._decode_stage(payload)
        except Exception as e:  # noqa: BLE001 — _forward_stage routes it
            self._forward_stage(payload, None, e)
            return
        self._forward_stage(payload, result, None)

    def _decode_stage(self, payload: bytes):
        """CPU-only decode (pool-worker safe: no shared mutation)."""
        faults.fire("ingest.decode")
        if self.raw_wire and self.on_wire_payload is not None:
            if self.on_wire_decode is not None:
                return self.on_wire_decode(payload)
            return None  # unsplit wire sink decodes inside forward
        return self.decoder(payload)

    def _pool_deliver(self, payload: bytes, decoded,
                      exc: Optional[BaseException]) -> None:
        """Pooled delivery: ``_forward_stage``'s re-raise of non-decode
        failures has no receiver thread to land on here — the pool would
        log-and-drop it — so the payload dead-letters instead."""
        try:
            self._forward_stage(payload, decoded, exc)
        except OverloadShed:
            # already counted + dead-lettered at the admission edge; the
            # pooled sources (UDP/TCP/WS) have no ack channel to signal
            # backpressure on, so the shed ends here
            return
        except BaseException as e:  # noqa: BLE001 — last stop before the
            # pool; BaseException because _forward_stage re-raises
            # whatever the decode stage threw
            self.failed_count += 1
            if self.on_failed_decode is not None:
                self.on_failed_decode(payload, self.source_id, e)
            else:
                logger.exception(
                    "pooled forward failed for source %s", self.source_id)

    def _forward_stage(self, payload: bytes, decoded,
                       exc: Optional[BaseException]) -> None:
        """Ordered delivery: counters, dead-letters, journal + forward."""
        if self.raw_wire and self.on_wire_payload is not None:
            try:
                if exc is not None:
                    raise exc
                if decoded is None:
                    self.decoded_count += self.on_wire_payload(
                        payload, self.source_id)
                else:
                    columns, host_reqs = decoded
                    # source_id rides along so overload admission
                    # buckets + intake-shed audit records attribute to
                    # THIS source, not a shared "wire" bucket
                    self.decoded_count += self.on_wire_decoded(
                        payload, columns, host_reqs,
                        source_id=self.source_id)
            except OverloadShed:
                # admission refused the payload: counted here, then
                # re-raised so the RECEIVER signals protocol-native
                # backpressure (429 / 5.03 / withheld PUBACK / unacked)
                self.shed_count += 1
                raise
            except DecodeError as e:
                # same observable failure path as the scalar decoder:
                # the source's counter ticks and its on_failed_decode
                # dead-letters the payload (once)
                self.failed_count += 1
                if self.on_failed_decode is not None:
                    self.on_failed_decode(payload, self.source_id, e)
            except Exception:
                self.failed_count += 1
                logger.exception(
                    "raw wire forward failed for source %s", self.source_id)
            return
        if exc is not None:
            if isinstance(exc, DecodeError):
                self.failed_count += 1
                if self.on_failed_decode is not None:
                    self.on_failed_decode(payload, self.source_id, exc)
                return
            # non-decode crash (an injected fault, a decoder bug):
            # synchronous callers see it on the receiver thread exactly
            # as before the split — the receiver's supervisor/broker
            # redelivery owns it; pooled delivery catches it in
            # _pool_deliver and dead-letters the payload
            raise exc
        requests = decoded
        events: List[DecodedRequest] = []
        forwarded = 0
        last_shed: Optional[OverloadShed] = None
        for req in requests:
            if self.deduplicator is not None and self.deduplicator.is_duplicate(req):
                self.duplicate_count += 1
                continue
            self.decoded_count += 1
            try:
                if req.kind == RequestKind.REGISTRATION:
                    if self.on_registration is not None:
                        self.on_registration(req, payload)
                    forwarded += 1
                elif req.event_type is None:
                    # Host-plane requests (stream data, mappings): never
                    # into the tensor batcher.
                    if self.on_host_request is not None:
                        self.on_host_request(req, payload)
                        forwarded += 1
                    else:
                        self.dropped_host_requests += 1
                elif self.on_events is not None:
                    events.append(req)  # forwarded in one batch below
                elif self.on_event is not None:
                    self.on_event(req, payload)
                    forwarded += 1
            except OverloadShed as e:
                # this request was refused by admission (counted + dead-
                # lettered there); siblings keep forwarding
                self.shed_count += 1
                last_shed = e
            except Exception:
                self.failed_count += 1
                logger.exception(
                    "forward failed for %s from source %s",
                    req.kind.name, self.source_id,
                )
        if events:
            try:
                self.on_events(events, payload)
                forwarded += len(events)
            except OverloadShed as e:
                # ingest_many raises only when EVERY row was shed —
                # partial sheds are absorbed inside it
                self.shed_count += 1
                last_shed = e
            except Exception:
                self.failed_count += 1
                logger.exception(
                    "batch forward failed for source %s", self.source_id,
                )
        if last_shed is not None and forwarded == 0:
            # the whole payload was shed: the receiver owns the
            # protocol-native backpressure signal
            raise last_shed


class Receiver(LifecycleComponent):
    """Base receiver: owns a transport, pushes raw payloads to ``sink``.

    Loop-owning receivers run their loops under a
    :class:`~sitewhere_tpu.runtime.resilience.Supervisor`
    (:meth:`_spawn_supervised`): an unexpected exception restarts the
    loop with exponential backoff instead of silently killing the
    thread, and a receiver that fails ``max_restarts`` times in a row
    escalates — terminal log + metric + lifecycle ERROR state — rather
    than spinning forever.  ``restart_policy`` / ``max_restarts`` are
    plain attributes so deployments (and chaos tests) tune them without
    touching every subclass constructor.
    """

    def __init__(self, name: str):
        super().__init__(name=name)
        self.sink: Optional[Callable[[bytes], None]] = None
        self.received_count = 0
        self.sheds = 0
        self.restart_policy = RetryPolicy(initial_s=0.05, max_s=5.0)
        self.max_restarts = 8
        self.supervisor: Optional[Supervisor] = None
        # multi-loop receivers (EventHub partitions) supervise several
        # threads; `supervisor` stays the LAST spawned for back-compat
        self.supervisors: List[Supervisor] = []

    def _emit(self, payload: bytes) -> None:
        faults.fire("ingest.emit")
        self.received_count += 1
        if self.sink is None:
            return
        try:
            self.sink(payload)
        except OverloadShed:
            # admission refused the payload.  Ack-gated transports
            # (HTTP 202, CoAP ACK, QoS-1 PUBACK, STOMP/AMQP acks) see
            # the raise and answer with their native backpressure
            # signal; ack-less transports (UDP, TCP framing, WS, REST
            # poll) have nothing to signal on — the shed was counted +
            # dead-lettered at the admission edge, so it must NOT fall
            # into their supervisors as a crash.
            self.sheds += 1
            if getattr(self, "acks_on_emit", False):
                raise

    def _spawn_supervised(self, run: Callable[[], None],
                          name: Optional[str] = None) -> Supervisor:
        """Run ``run`` on a supervised thread; escalation marks this
        component failed (the operator-visible terminal state)."""
        self.supervisor = Supervisor(
            name or self.name, run, policy=self.restart_policy,
            max_restarts=self.max_restarts, min_uptime_s=5.0,
            on_escalate=self._on_escalate)
        self.supervisors.append(self.supervisor)
        self.supervisor.start()
        return self.supervisor

    def _on_escalate(self, exc: BaseException) -> None:
        logger.error("receiver %s failed permanently: %s", self.name, exc)
        self._fail(exc)

    def _stop_supervisor(self) -> None:
        for sup in self.supervisors:
            sup.stop()
        self.supervisors = []
        self.supervisor = None


def length_prefixed_frames(conn: socket.socket, emit: Callable[[bytes], None]) -> None:
    """Framing: u32-be length + body (the default interaction handler)."""
    buf = b""
    while True:
        data = conn.recv(65536)
        if not data:
            return
        buf += data
        while len(buf) >= 4:
            (ln,) = struct.unpack_from(">I", buf, 0)
            if ln > 16 << 20:
                raise ValueError(f"frame too large: {ln}")
            if len(buf) < 4 + ln:
                break
            emit(buf[4 : 4 + ln])
            buf = buf[4 + ln :]


def newline_frames(conn: socket.socket, emit: Callable[[bytes], None]) -> None:
    """Framing: newline-delimited payloads (e.g. JSON lines)."""
    buf = b""
    while True:
        data = conn.recv(65536)
        if not data:
            if buf.strip():
                emit(buf.strip())
            return
        buf += data
        while b"\n" in buf:
            line, buf = buf.split(b"\n", 1)
            if line.strip():
                emit(line.strip())


class WebSocketReceiver(Receiver):
    """Client pulling payloads from a remote WebSocket endpoint.

    Reference: ``websocket/WebSocketEventReceiver.java`` — a
    ``javax.websocket`` client session against a configured URL with
    optional headers; every received message's bytes feed the source's
    decoder.  Reconnects with capped exponential backoff when the remote
    closes or the connect fails (the reference restarts its session via
    the lifecycle).
    """

    def __init__(self, host: str, port: int, path: str = "/",
                 headers: Optional[dict] = None,
                 reconnect_delay_s: float = 0.5,
                 max_reconnect_delay_s: float = 30.0):
        super().__init__(name=f"ws-receiver:{host}:{port}{path}")
        self.host, self.port, self.path = host, port, path
        self.headers = dict(headers or {})
        self.reconnect_delay_s = reconnect_delay_s
        self.max_reconnect_delay_s = max_reconnect_delay_s
        self._alive = False
        self._stop_evt = threading.Event()
        self._client = None
        self.connects = 0
        # reconnect schedule on the shared primitive (was ad-hoc
        # delay-doubling state)
        self._backoff = Backoff(
            RetryPolicy(initial_s=reconnect_delay_s,
                        max_s=max_reconnect_delay_s),
            name="ingest.ws-reconnect")

    def start(self) -> None:
        self._alive = True
        self._stop_evt.clear()
        # Supervised: transport errors are handled by the reconnect loop
        # itself; the supervisor catches anything unexpected (a sink
        # exception, an injected fault) and restarts the whole loop.
        self._spawn_supervised(self._loop)
        super().start()

    def stop(self) -> None:
        self._alive = False
        self._stop_evt.set()
        client = self._client
        if client is not None:
            try:
                client.close()
            except OSError:
                pass
        self._stop_supervisor()
        super().stop()

    def _loop(self) -> None:
        from sitewhere_tpu.web.ws import ClientWebSocket

        while self._alive:
            try:
                self._client = ClientWebSocket(
                    self.host, self.port, self.path, headers=self.headers
                )
                self.connects += 1
                self._backoff.reset()  # connected: fresh schedule
                while self._alive:
                    msg = self._client.recv()
                    if msg is None:
                        break  # remote closed — reconnect
                    _, payload = msg
                    if payload:
                        self._emit(payload)
            except (OSError, ConnectionError) as e:
                logger.debug("ws receiver %s: %s", self.name, e)
            finally:
                client, self._client = self._client, None
                if client is not None:
                    try:
                        client.close()
                    except OSError:
                        pass
            if self._alive:
                self._stop_evt.wait(self._backoff.next_delay())


class _EmitCrash(Exception):
    """Marker: the sink/emit path crashed inside a framing loop (as
    opposed to a framing violation raised by the framing itself)."""


# accept() errors that do NOT mean the listener died: ride them out in
# place (the old ThreadingTCPServer's per-request error handling) —
# tearing down + restarting on an fd-exhaustion storm would burn the
# supervisor's restart budget and escalate a transient flood into
# permanent receiver death
_TRANSIENT_ACCEPT_ERRNOS = frozenset({
    errno.ECONNABORTED, errno.EMFILE, errno.ENFILE,
    errno.ENOBUFS, errno.ENOMEM,
})


class TcpReceiver(Receiver):
    """Threaded TCP server with pluggable framing.

    The accept loop runs under the shared receiver supervisor (ROADMAP:
    remaining-receiver chaos coverage): an unexpected accept failure
    restarts the loop with backoff — re-binding the SAME port, so
    clients just reconnect — and repeated failures escalate to the
    terminal lifecycle ERROR state.  A sink/emit crash inside one
    connection's framing loop closes ONLY that connection (counted in
    ``connection_errors``): the un-acked stream is the client's cue to
    reconnect and resend, TCP's redelivery semantics.  The accept loop
    is never the casualty of one connection's poison payload.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 framing: Callable = length_prefixed_frames):
        super().__init__(name=f"tcp-receiver:{port}")
        self.host, self.port = host, port
        self.framing = framing
        self._sock: Optional[socket.socket] = None
        self._alive = False
        self.connection_errors = 0
        # live connection handlers: stop() must close + join them so no
        # emit reaches an already-stopped pipeline after stop() returns
        # (the contract ThreadingTCPServer.server_close used to provide)
        self._conn_lock = threading.Lock()
        self._conns: Dict[socket.socket, threading.Thread] = {}

    def _bind(self) -> None:
        if self._sock is None:
            sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            sock.bind((self.host, self.port))
            sock.listen(64)
            self._sock = sock
            self.port = sock.getsockname()[1]

    def _close_listener(self) -> None:
        # shutdown BEFORE close: close() alone does not wake a thread
        # blocked in accept() on Linux — the loop would hang forever
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass

    def start(self) -> None:
        self._bind()
        self._alive = True
        self._spawn_supervised(self._run)
        super().start()

    def _handle(self, conn: socket.socket) -> None:
        # wrap emit so a sink crash is distinguishable from a framing
        # violation — a sink ValueError must be COUNTED, not mistaken
        # for a malformed frame
        def emit(payload: bytes) -> None:
            try:
                self._emit(payload)
            except Exception as e:
                raise _EmitCrash() from e

        try:
            with conn:
                self.framing(conn, emit)
        except _EmitCrash:
            # sink crash: this connection dies (its client resends on
            # reconnect), the accept loop and sibling connections do not
            self.connection_errors += 1
            logger.exception("tcp receiver %s: connection crashed",
                             self.name)
        except (ValueError, OSError):
            pass   # framing violation / peer reset — connection-local
        finally:
            with self._conn_lock:
                self._conns.pop(conn, None)

    def _run(self) -> None:
        self._bind()   # restart after a crash that closed the socket
        if not self._alive:
            # stop() raced the supervised restart: its _close_listener
            # saw _sock=None mid-_bind, so the fresh socket is ours to
            # release — otherwise the port stays bound forever
            self._close_listener()
            return
        while self._alive:
            sock = self._sock
            if sock is None:
                return   # stop() tore the listener down mid-iteration
            try:
                conn, _ = sock.accept()
            except OSError as e:
                if not self._alive:
                    return   # clean shutdown closed the socket
                if e.errno in _TRANSIENT_ACCEPT_ERRNOS:
                    # fd exhaustion / aborted handshake: keep listening
                    logger.warning("tcp receiver %s: transient accept "
                                   "error, retrying: %s", self.name, e)
                    time.sleep(0.05)
                    continue
                # release the port before the supervised restart rebinds
                # it (same contract as UdpReceiver._run)
                self._close_listener()
                raise        # unexpected socket death → supervisor restarts
            t = threading.Thread(
                target=self._handle, args=(conn,),
                name=f"{self.name}-conn", daemon=True)
            with self._conn_lock:
                # registration and stop() flip _alive under the same
                # lock: a handler either registers before stop()'s
                # snapshot (so it is closed + joined) or sees the stop
                # and never starts — nothing can emit into a stopped
                # pipeline either way
                if not self._alive:
                    try:
                        conn.close()
                    except OSError:
                        pass
                    return
                self._conns[conn] = t
            t.start()

    def stop(self) -> None:
        with self._conn_lock:
            self._alive = False
        self._close_listener()
        # tear down established connections and JOIN their handlers:
        # nothing may emit into the stopped pipeline after this returns
        with self._conn_lock:
            conns = list(self._conns.items())
        for conn, thread in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
            try:
                thread.join(timeout=2)
                if thread.is_alive():
                    # handler stuck in a slow emit: the no-emit-after-
                    # stop contract is broken — make it observable
                    logger.warning(
                        "tcp receiver %s: connection handler still "
                        "alive after stop() join timeout", self.name)
            except RuntimeError:
                pass   # raced the registration: thread not yet started
        self._stop_supervisor()
        super().stop()


class UdpReceiver(Receiver):
    """One datagram = one payload."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        super().__init__(name=f"udp-receiver:{port}")
        self.host, self.port = host, port
        self._sock: Optional[socket.socket] = None
        self._alive = False

    def _bind(self) -> None:
        if self._sock is None:
            self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            self._sock.bind((self.host, self.port))
            self.port = self._sock.getsockname()[1]

    def start(self) -> None:
        self._bind()
        self._alive = True
        # Supervised: a sink/emit exception restarts the loop with
        # backoff; the bound socket survives restarts, so datagrams sent
        # during the backoff window sit in the kernel buffer, not lost.
        self._spawn_supervised(self._run)
        super().start()

    def _run(self) -> None:
        self._bind()   # restart after a crash that closed the socket
        while self._alive:
            try:
                data, _ = self._sock.recvfrom(65536)
            except OSError:
                if not self._alive:
                    return   # clean shutdown closed the socket
                # release the port before the supervised restart rebinds
                # it — a leaked fd would turn every rebind into
                # EADDRINUSE and a transient recv error into terminal
                # receiver death
                sock, self._sock = self._sock, None
                if sock is not None:
                    try:
                        sock.close()
                    except OSError:
                        pass
                raise        # unexpected socket death → supervisor restarts
            if data:
                self._emit(data)

    def stop(self) -> None:
        self._alive = False
        if self._sock is not None:
            self._sock.close()
            self._sock = None
        self._stop_supervisor()
        super().stop()


class HttpReceiver(Receiver):
    """POST <path> with the payload as body → one event payload."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 path: str = "/events"):
        super().__init__(name=f"http-receiver:{port}")
        # the 202 response is an ack gated on _emit returning: the
        # decode pool must keep this source synchronous or the 202
        # would precede the journal append (at-least-once)
        self.acks_on_emit = True
        self.host, self.port, self.path = host, port, path
        self._server: Optional[http.server.ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        receiver = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_POST(self):
                if self.path != receiver.path:
                    self.send_error(404)
                    return
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length)
                try:
                    receiver._emit(body)
                except OverloadShed as e:
                    # HTTP-native backpressure: the client owns the
                    # retry (shed ≠ silent drop — the payload was also
                    # dead-lettered at the admission edge)
                    self.send_response(429)
                    self.send_header(
                        "Retry-After",
                        str(max(1, int(round(e.retry_after_s)))))
                    self.end_headers()
                    return
                self.send_response(202)
                self.end_headers()

            def log_message(self, *args):
                pass

        self._server = http.server.ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True, name=self.name
        )
        self._thread.start()
        super().start()

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        super().stop()


class MqttReceiver(Receiver):
    """Subscribe to a broker topic; every message is a payload."""

    def __init__(self, host: str, port: int = 1883, topic: str = "sitewhere/input",
                 qos: int = 0, client_id: str = "sw-tpu-ingest", **client_kw):
        super().__init__(name=f"mqtt-receiver:{topic}")
        from sitewhere_tpu.ingest.mqtt import MqttClient

        self.topic, self.qos = topic, qos
        self.client = MqttClient(host, port, client_id=client_id, **client_kw)

    def start(self) -> None:
        self.client.on_message = lambda topic, payload: self._emit(payload)
        self.client.connect()
        self.client.subscribe(self.topic, self.qos)
        super().start()

    def stop(self) -> None:
        self.client.disconnect()
        super().stop()


class PollingRestReceiver(Receiver):
    """Poll an HTTP endpoint on an interval; non-empty bodies are payloads.

    Reference: ``rest/PollingRestInboundEventReceiver.java`` (scripted
    response→payload mapping there; a ``transform`` callable here).
    """

    def __init__(self, url: str, interval_s: float = 10.0,
                 transform: Optional[Callable[[bytes], List[bytes]]] = None):
        super().__init__(name=f"poll-receiver:{url}")
        self.url = url
        self.interval_s = interval_s
        self.transform = transform or (lambda body: [body] if body else [])
        self._alive = False
        self._wake = threading.Event()

    def start(self) -> None:
        self._alive = True
        # Supervised: HTTP errors are expected (the poll just skips a
        # tick); a transform/sink exception restarts the loop with
        # backoff instead of killing the poller silently.
        self._spawn_supervised(self._run)
        super().start()

    def _run(self) -> None:
        while self._alive:
            try:
                with urllib.request.urlopen(self.url, timeout=10) as resp:
                    body = resp.read()
                for payload in self.transform(body):
                    self._emit(payload)
            except OSError:
                pass
            self._wake.wait(self.interval_s)
            self._wake.clear()

    def stop(self) -> None:
        self._alive = False
        self._wake.set()
        self._stop_supervisor()
        super().stop()
