"""Columnar wire decode: one NDJSON payload → column arrays, no per-event
dataclasses.

This is the true 1M events/sec/chip intake edge (round-2 verdict weak #2):
the scalar path builds one :class:`~sitewhere_tpu.ingest.decoders.
DecodedRequest` per event and the batcher loops per row per field; at high
rates that Python churn is the bottleneck, not the chip.  Here the whole
payload is parsed by ONE C-level ``json.loads`` and each batch column is
built by one comprehension + ``np.fromiter`` sweep — a few passes of
C-speed iteration per *field*, never Python work per (event × field).

Wire format: newline-delimited JSON, each line the same envelope the
scalar :class:`~sitewhere_tpu.ingest.decoders.JsonDecoder` accepts
(``{"deviceToken", "type", "request": {...}}``), matching the reference's
MQTT conformance senders (``MqttTests.java:107-168``) — so a fleet can
batch its existing messages into one payload without re-encoding.  A JSON
array of the same envelopes is accepted too.

Host-plane lines (registration etc.) are rare; they fall out as scalar
``DecodedRequest`` objects for the normal path.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from sitewhere_tpu.ids import NULL_ID
from sitewhere_tpu.ingest.decoders import (
    _LEVEL_ALIASES,
    _TYPE_ALIASES,
    DecodedRequest,
    DecodeError,
    RequestKind,
    _decode_one,
    _parse_ts,
    envelope_fields,
    parse_envelopes,
)
from sitewhere_tpu.schema import AlertLevel

_MISS = object()  # dict-get sentinel (kind 0 is falsy — `or` won't do)


class CopyTally:
    """Per-call accumulator of intermediate-buffer bytes a decode path
    materializes (anything that is neither the wire payload nor a final
    batch column: the C scanner's returned bytes objects, ``frombuffer``
    copies, ``astype`` outputs, the ``_split_epoch`` temporaries).  The
    dispatcher feeds the total into ``pipeline.bytes_copied.decode`` —
    the fill-direct path adds ZERO here, which is the measured (not
    asserted) half of the zero-copy story.  Boolean masks are excluded;
    the methodology only needs to be consistent across the A/B paths.
    """

    __slots__ = ("n",)

    def __init__(self) -> None:
        self.n = 0

    def add(self, nbytes: int) -> None:
        self.n += int(nbytes)


# _split_epoch materializes this many temp/output bytes per row (np.where
# f64 + int64 seconds + f64 diff + f64 scaled + f64 round + int64 nanos +
# two int32 casts = 8+8+8+8+8+8+4+4); counted as a constant so the hot
# path never introspects numpy internals.
_SPLIT_EPOCH_BYTES_PER_ROW = 56

# Request kinds that are pipeline events (EventType 0..5).
_EVENT_KINDS = frozenset(int(k) for k in RequestKind if k <= RequestKind.STATE_CHANGE)

# Exact-case lookup first (one dict get per line); common wire casings
# pre-seeded so the .lower() normalization never runs on the fast path.
_KIND_EXACT = dict(_TYPE_ALIASES)
_KIND_EXACT.update({
    "Measurement": RequestKind.MEASUREMENT,
    "Measurements": RequestKind.MEASUREMENT,
    "DeviceMeasurements": RequestKind.MEASUREMENT,
    "Location": RequestKind.LOCATION,
    "DeviceLocation": RequestKind.LOCATION,
    "Alert": RequestKind.ALERT,
    "DeviceAlert": RequestKind.ALERT,
    "RegisterDevice": RequestKind.REGISTRATION,
    "Registration": RequestKind.REGISTRATION,
    "Acknowledge": RequestKind.COMMAND_RESPONSE,
    "CommandResponse": RequestKind.COMMAND_RESPONSE,
    "CommandInvocation": RequestKind.COMMAND_INVOCATION,
    "StateChange": RequestKind.STATE_CHANGE,
    "StreamData": RequestKind.STREAM_DATA,
})


def space_of(resolve_device):
    """The HandleSpace behind a bound ``lookup`` resolver, else None.

    Only ``HandleSpace.lookup`` itself qualifies — a caller passing e.g.
    ``mint`` (or any other callable) keeps its semantics and the
    pre-resolved fast paths stay off.
    """
    from sitewhere_tpu.ids import HandleSpace

    owner = getattr(resolve_device, "__self__", None)
    if isinstance(owner, HandleSpace) \
            and getattr(resolve_device, "__func__", None) \
            is HandleSpace.lookup:
        return owner
    return None


def n_rows(columns: Dict[str, object]) -> int:
    """Event-row count of a decoded column dict, resolved or not."""
    return len(columns["device_id"] if "device_id" in columns
               else columns["device_token"])


def fill_direct_ready(payload, device_space) -> bool:
    """Cheap fill-direct eligibility gate, run BEFORE allocating a
    reservation — a deployment without the native toolchain (or a
    non-NDJSON payload) must not pay a per-payload buffer allocation
    just to abort it."""
    if not isinstance(payload, bytes) or payload[:1] == b"[":
        return False
    from sitewhere_tpu.native import load_swwire

    mod = load_swwire()
    if mod is None \
            or not hasattr(mod, "decode_measurement_lines_resolved_into"):
        return False
    return device_space.native_table() is not None


def decode_fill_direct(payload, device_space, reservation, resolve_mtype):
    """Fill-direct decode: C scan straight into a batcher reservation.

    The zero-copy resolved measurement path — the native scanner writes
    validated int32/float32 values DIRECTLY into ``reservation``'s
    packed column rows (device ids resolved through the TokenTable
    mirror, timestamps split to ``(ts_s, ts_ns)`` in C), and the only
    Python objects created are the handful of distinct measurement
    names.  Returns the row count on success; on ANY shape deviation the
    reservation is aborted (nothing was shared — no torn rows) and None
    is returned so the caller falls back to :func:`decode_json_lines`,
    which reproduces the current behavior bit-for-bit, errors included.
    """
    from sitewhere_tpu.native import load_swwire

    mod = load_swwire()
    if mod is None \
            or not hasattr(mod, "decode_measurement_lines_resolved_into") \
            or not isinstance(payload, bytes) or payload[:1] == b"[":
        reservation.abort()
        return None
    table = device_space.native_table()
    if table is None:
        reservation.abort()
        return None
    res = reservation
    out = mod.decode_measurement_lines_resolved_into(
        payload, table, res.device_id, res.name_idx, res.value,
        res.ts_s, res.ts_ns, res.update_state)
    if out is None:
        res.abort()
        return None
    n, uniq = out
    # Resolve the distinct names, then remap the scratch indices into
    # the mtype row in place — np.take with `out=` over DISTINCT
    # source/destination arrays, so no temporary is gathered.
    uniq_ids = np.asarray([resolve_mtype(u) for u in uniq], np.int32)
    row = res.mtype_id
    if len(uniq_ids) == 1:
        row[:n] = uniq_ids[0]
    else:
        np.take(uniq_ids, res.name_idx[:n], out=row[:n])
    res.n = n
    return n


def decode_json_lines(
    payload: bytes,
    device_space=None,
    copied: Optional[CopyTally] = None,
) -> Tuple[Dict[str, object], List[DecodedRequest]]:
    """Decode one NDJSON (or JSON-array) wire payload columnar-ly.

    Returns ``(columns, host_requests)`` where ``columns`` holds, for the
    event lines only:

    - ``device_token``: list[str] — resolve with ``lookup_many``
    - ``mtype`` / ``alert_type``: list[Optional[str]] — mint lazily
    - ``event_type``, ``ts_s``, ``ts_ns``, ``value``, ``lat``, ``lon``,
      ``elevation``, ``alert_level``, ``update_state``: numpy arrays

    and ``host_requests`` carries the rare host-plane lines (registration,
    stream data, …) as scalar requests for the normal path.  Raises
    :class:`DecodeError` if the payload as a whole cannot be parsed; a
    malformed individual line raises too (the whole payload dead-letters,
    matching the reference's per-payload failed-decode contract).

    With ``device_space`` (the HandleSpace the caller would resolve
    ``device_token`` against), homogeneous measurement payloads take the
    C scanner's RESOLVED form: ``columns`` then carries ``device_id``
    (int32, NULL_ID for unknown tokens — the step flags those rows
    unregistered and egress replays them from the journal) instead of
    ``device_token``, and ``mtype_uniq``/``mtype_idx`` instead of a
    per-row ``mtype`` list; :func:`resolve_columns` understands both
    shapes.  Token strings are never materialized for registered
    devices — the dominant per-line cost of the unresolved path.
    """
    if device_space is not None:
        resolved = _native_decode_resolved(payload, device_space, copied)
        if resolved is not None:
            return resolved
    native = _native_decode(payload, copied)
    if native is not None:
        return native
    try:
        return _decode_lines_inner(parse_envelopes(payload))
    except DecodeError:
        raise
    except (ValueError, TypeError, KeyError, OverflowError) as e:
        # Bad field values (non-numeric "value", unhashable "type", …)
        # must dead-letter like any other decode failure, never escape
        # into the receiver thread (scalar-path contract, decoders.py).
        raise DecodeError(f"bad wire batch: {e}") from e


def _native_decode_resolved(
    payload: bytes,
    device_space,
    copied: Optional[CopyTally] = None,
) -> Optional[Tuple[Dict[str, object], List[DecodedRequest]]]:
    """C fast path with device tokens resolved in C (TokenTable mirror).

    Same strictness contract as :func:`_native_decode`'s measurement
    scanner — any shape deviation returns None and the caller falls
    through to the unresolved native path, then pure Python.
    """
    from sitewhere_tpu.native import load_swwire

    mod = load_swwire()
    if mod is None or not hasattr(mod, "decode_measurement_lines_resolved") \
            or not isinstance(payload, bytes) or payload[:1] == b"[":
        return None
    table = device_space.native_table()
    if table is None:
        return None
    out = mod.decode_measurement_lines_resolved(payload, table)
    if out is None:
        return None
    ids_b, uniq_names, idx_b, values_b, ts_b, us_b = out
    # ids come back as a WRITABLE bytearray, so the batcher's in-place
    # NULL_ID rewrite for out-of-range rows needs no defensive copy
    device_id = np.frombuffer(ids_b, np.int32)
    n = len(device_id)
    if copied is not None:
        copied.add(len(ids_b) + len(idx_b) + len(values_b) + len(ts_b)
                   + len(us_b)                   # C scratch → PyBytes
                   + 4 * n + n                   # value/update astype
                   + _SPLIT_EPOCH_BYTES_PER_ROW * n)
    ts_s, ts_ns = _split_epoch(np.frombuffer(ts_b, np.float64))
    zeros = np.zeros(n, np.float32)
    return {
        "device_id": device_id,
        "event_type": np.zeros(n, np.int32),  # all MEASUREMENT
        "ts_s": ts_s, "ts_ns": ts_ns,
        "mtype_uniq": uniq_names,
        "mtype_idx": np.frombuffer(idx_b, np.int32),
        "value": np.frombuffer(values_b, np.float64).astype(np.float32),
        "lat": zeros, "lon": zeros, "elevation": zeros,
        "alert_code": np.full(n, NULL_ID, np.int32),
        "alert_level": np.zeros(n, np.int32),
        "update_state": np.frombuffer(us_b, np.uint8).astype(np.bool_),
    }, []


def _host_requests(host_lines) -> List[DecodedRequest]:
    """Registration/host-plane lines → scalar requests (shared by the
    event-family branches; a line ``json.loads`` rejects dead-letters
    the whole payload, exactly like the pure path)."""
    import json as _json

    host: List[DecodedRequest] = []
    for line in host_lines:
        try:
            doc = _json.loads(line)
        except ValueError as e:
            raise DecodeError(f"bad wire batch: {e}") from e
        host.append(_decode_one(*envelope_fields(doc)))
    return host


def _native_decode_events_into(
    mod, payload: bytes,
) -> Optional[Tuple[Dict[str, object], List[DecodedRequest]]]:
    """Fill-direct generic event-family decode: the C scanner writes the
    numeric columns straight into freshly allocated FINAL arrays (int32/
    float32/bool) — no intermediate bytes objects, no frombuffer/astype
    re-materialization.  None = fall through to the two-phase scanner
    (which reproduces errors like out-of-range timestamps exactly)."""
    cap = payload.count(b"\n") + 1
    kinds = np.empty(cap, np.int32)
    ts_s = np.empty(cap, np.int32)
    ts_ns = np.empty(cap, np.int32)
    value = np.empty(cap, np.float32)
    lat = np.empty(cap, np.float32)
    lon = np.empty(cap, np.float32)
    elev = np.empty(cap, np.float32)
    level = np.empty(cap, np.int32)
    us = np.empty(cap, np.bool_)
    out = mod.decode_event_lines_into(
        payload, kinds, ts_s, ts_ns, value, lat, lon, elev, level, us)
    if out is None:
        return None
    n, tokens, names, alert_types, host_lines = out
    if n == 0 and not host_lines:
        return None  # preserve the Python path's empty-payload error
    host = _host_requests(host_lines)
    if n == 0:
        return {"device_token": [], "mtype": [], "alert_type": []}, host
    return {
        "device_token": tokens,
        "event_type": kinds[:n],
        "ts_s": ts_s[:n], "ts_ns": ts_ns[:n],
        "mtype": names,
        "value": value[:n],
        "lat": lat[:n], "lon": lon[:n], "elevation": elev[:n],
        "alert_type": alert_types,
        "alert_level": level[:n],
        "update_state": us[:n],
    }, host


def _native_decode(
    payload: bytes,
    copied: Optional[CopyTally] = None,
) -> Optional[Tuple[Dict[str, object], List[DecodedRequest]]]:
    """The C fast path for NDJSON event payloads — measurements,
    locations and alerts in any mix, with registration lines split out
    for the (rare) host-plane path.

    Strictness contract (swwire.c): ANY deviation from the supported
    shapes returns None and the pure-Python decoder takes over — the
    native tier only accelerates, it never changes behavior.  A
    registration line the native scanner accepted but ``json.loads``
    rejects dead-letters the whole payload, exactly like the pure path.
    """
    from sitewhere_tpu.native import load_swwire

    mod = load_swwire()
    if mod is None or not isinstance(payload, bytes) \
            or payload[:1] == b"[":
        return None
    # Homogeneous measurement payloads (the dominant fleet shape) go
    # through the specialized single-purpose scanner (~2x the generic
    # one); it bails within the first divergent line, so trying it first
    # costs mixed payloads almost nothing.
    meas = mod.decode_measurement_lines(payload)
    if meas is not None:
        tokens, names, values_b, ts_b, us_b = meas
        n = len(tokens)
        if n == 0:
            return None  # preserve the Python path's empty-payload error
        if copied is not None:
            copied.add(len(values_b) + len(ts_b) + len(us_b)
                       + 4 * n + n + _SPLIT_EPOCH_BYTES_PER_ROW * n)
        ts_s, ts_ns = _split_epoch(np.frombuffer(ts_b, np.float64))
        zeros = np.zeros(n, np.float32)
        return {
            "device_token": tokens,
            "event_type": np.zeros(n, np.int32),  # all MEASUREMENT
            "ts_s": ts_s, "ts_ns": ts_ns,
            "mtype": names,
            "value": np.frombuffer(values_b, np.float64).astype(np.float32),
            "lat": zeros, "lon": zeros, "elevation": zeros,
            "alert_type": [None] * n,
            "alert_level": np.zeros(n, np.int32),
            "update_state": np.frombuffer(us_b, np.uint8).astype(np.bool_),
        }, []
    if hasattr(mod, "decode_event_lines_into") \
            and os.environ.get("SW_NATIVE_FILL", "1") != "0":
        # SW_NATIVE_FILL=0 must bypass BOTH fill-direct scanners (this
        # one and the resolved measurement path) so the documented A/B
        # escape hatch isolates every new code path, not just one
        filled = _native_decode_events_into(mod, payload)
        if filled is not None:
            return filled
    out = mod.decode_event_lines(payload)
    if out is None:
        return None
    (tokens, kinds_b, names, alert_types, values_b, ts_b, lat_b, lon_b,
     elev_b, lvl_b, us_b, host_lines) = out
    n = len(tokens)
    if n == 0 and not host_lines:
        return None  # preserve the Python path's empty-payload error
    host = _host_requests(host_lines)
    if n == 0:
        return {"device_token": [], "mtype": [], "alert_type": []}, host
    if copied is not None:
        copied.add(len(kinds_b) + len(values_b) + len(ts_b) + len(lat_b)
                   + len(lon_b) + len(elev_b) + len(lvl_b) + len(us_b)
                   + 4 * n * 6 + n + _SPLIT_EPOCH_BYTES_PER_ROW * n)
    ts_s, ts_ns = _split_epoch(np.frombuffer(ts_b, np.float64))
    columns: Dict[str, object] = {
        "device_token": tokens,
        "event_type": np.frombuffer(kinds_b, np.uint8).astype(np.int32),
        "ts_s": ts_s.astype(np.int32),
        "ts_ns": ts_ns.astype(np.int32),
        "mtype": names,
        "value": np.frombuffer(values_b, np.float64).astype(np.float32),
        "lat": np.frombuffer(lat_b, np.float64).astype(np.float32),
        "lon": np.frombuffer(lon_b, np.float64).astype(np.float32),
        "elevation": np.frombuffer(elev_b, np.float64).astype(np.float32),
        "alert_type": alert_types,
        "alert_level": np.frombuffer(lvl_b, np.int32).copy(),
        "update_state": np.frombuffer(us_b, np.uint8).astype(np.bool_),
    }
    return columns, host


def _decode_lines_inner(
    docs: List[dict],
) -> Tuple[Dict[str, object], List[DecodedRequest]]:
    # Fast extraction: C-driven comprehensions with exception fallback to
    # the generic per-line loop (hardwareId alias, host-plane lines,
    # malformed-line diagnostics).  Every hot sweep below is one
    # comprehension / np call per FIELD, not Python work per (row×field).
    try:
        tokens = [d["deviceToken"] for d in docs]
        kind_names = [d["type"] for d in docs]
        reqs = [d["request"] for d in docs]
        kinds = [_KIND_EXACT.get(k, _MISS) for k in kind_names]
    except (TypeError, KeyError):
        return _decode_generic(docs)
    if _MISS in kinds:
        kinds = [
            (k if k is not _MISS
             else _TYPE_ALIASES.get(str(raw).strip().lower()))
            for k, raw in zip(kinds, kind_names)
        ]
    if None in kinds or any(int(k) not in _EVENT_KINDS for k in kinds) \
            or not all(type(r) is dict for r in reqs) \
            or not all(type(t) is str and t for t in tokens):
        return _decode_generic(docs)

    n = len(docs)
    ts_s, ts_ns = _ts_columns(reqs)
    event_type = np.fromiter(map(int, kinds), np.int32, n)
    update_state = np.fromiter(
        (r.get("updateState", True) for r in reqs), np.bool_, n)

    first = kinds[0]
    if first == RequestKind.MEASUREMENT and kinds.count(first) == n:
        # homogeneous measurement payload — the dominant fleet shape
        try:
            values = np.fromiter((r["value"] for r in reqs), np.float32, n)
        except KeyError:
            raise DecodeError("measurement needs name+value") from None
        mtypes = [r.get("name") or r.get("measurementId") for r in reqs]
        if None in mtypes:
            raise DecodeError("measurement needs name+value")
        zeros = np.zeros(n, np.float32)
        columns: Dict[str, object] = {
            "device_token": tokens,
            "event_type": event_type,
            "ts_s": ts_s, "ts_ns": ts_ns,
            "mtype": mtypes, "value": values,
            "lat": zeros, "lon": zeros, "elevation": zeros,
            "alert_type": [None] * n,
            "alert_level": np.zeros(n, np.int32),
            "update_state": update_state,
        }
        return columns, []
    if first == RequestKind.LOCATION and kinds.count(first) == n:
        try:
            lats = np.fromiter((r["latitude"] for r in reqs), np.float32, n)
            lons = np.fromiter((r["longitude"] for r in reqs), np.float32, n)
        except KeyError as e:
            raise DecodeError(f"location missing {e}") from None
        elevs = np.fromiter(
            (r.get("elevation", 0.0) for r in reqs), np.float32, n)
        columns = {
            "device_token": tokens,
            "event_type": event_type,
            "ts_s": ts_s, "ts_ns": ts_ns,
            "mtype": [None] * n, "value": np.zeros(n, np.float32),
            "lat": lats, "lon": lons, "elevation": elevs,
            "alert_type": [None] * n,
            "alert_level": np.zeros(n, np.int32),
            "update_state": update_state,
        }
        return columns, []

    # mixed-kind payload: per-row extraction (rare on the wire)
    return _decode_mixed(tokens, kinds, reqs, ts_s, ts_ns, event_type,
                         update_state)


def _ts_columns(reqs: List[dict]) -> Tuple[np.ndarray, np.ndarray]:
    """Vectorized eventDate/timestamp → (ts_s, ts_ns); per-row fallback
    for ISO strings (same aliases as the scalar ``_decode_one``)."""
    n = len(reqs)
    try:
        raw = np.fromiter(
            (r.get("eventDate") or r.get("timestamp") or 0 for r in reqs),
            np.float64, n)
    except (TypeError, ValueError):
        pairs = [_parse_ts(r.get("eventDate", r.get("timestamp")))
                 for r in reqs]
        return (np.fromiter((p[0] for p in pairs), np.int32, n),
                np.fromiter((p[1] for p in pairs), np.int32, n))
    return _split_epoch(raw)


def _split_epoch(raw: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Shared float64-epoch → (ts_s, ts_ns) split (millis heuristic) —
    ONE implementation so the native and Python paths can't drift."""
    if not np.isfinite(raw).all():
        # json.loads parses "1e999" (and the Infinity/NaN literals) to
        # non-finite floats; the scalar path's int(inf) is a decode
        # error, so the columnar path must dead-letter too instead of
        # silently storing an int64-min timestamp (fuzz-found)
        raise DecodeError("non-finite eventDate/timestamp")
    raw = np.where(raw > 1e11, raw / 1e3, raw)  # epoch millis
    if ((raw >= float(1 << 31)) | (raw <= -float(1 << 31) - 1.0)).any():
        # int32 epoch-seconds schema: reject instead of silently
        # truncating — the bound mirrors the scalar path's
        # truncate-toward-zero int(value) + [-2^31, 2^31) check exactly,
        # so int32-min itself stays accepted on both paths
        raise DecodeError("eventDate out of range")
    ts_s = raw.astype(np.int64)
    ts_ns = np.round((raw - ts_s) * 1e9).astype(np.int64)
    return ts_s.astype(np.int32), ts_ns.astype(np.int32)


def _decode_mixed(tokens, kinds, reqs, ts_s, ts_ns, event_type,
                  update_state) -> Tuple[Dict[str, object], List[DecodedRequest]]:
    n = len(tokens)
    mtypes: List[Optional[str]] = []
    values = np.zeros(n, np.float32)
    alert_types: List[Optional[str]] = []
    alert_levels = np.zeros(n, np.int32)
    lats = np.zeros(n, np.float32)
    lons = np.zeros(n, np.float32)
    elevs = np.zeros(n, np.float32)
    origins: List[Optional[str]] = []  # invocation-token correlation
    for i, (kind, r) in enumerate(zip(kinds, reqs)):
        # touches only the fields the kind carries; no object construction
        if kind == RequestKind.MEASUREMENT:
            # `or` (not get-with-default): an empty name falls through to
            # the alias — same rule as the fast path and the C decoder
            name = r.get("name") or r.get("measurementId")
            if not name or "value" not in r:
                raise DecodeError("measurement needs name+value")
            mtypes.append(str(name))
            values[i] = float(r["value"])
            alert_types.append(None)
            origins.append(None)
        elif kind == RequestKind.LOCATION:
            try:
                lats[i] = float(r["latitude"])
                lons[i] = float(r["longitude"])
            except KeyError as e:
                raise DecodeError(f"location missing {e}") from e
            elevs[i] = float(r.get("elevation", 0.0))
            mtypes.append(None)
            alert_types.append(None)
            origins.append(None)
        elif kind == RequestKind.ALERT:
            # same semantics as the scalar decoder: missing type defaults
            # to "alert", an unknown string level is a decode error —
            # replay of a journaled payload must never diverge from what
            # the hot path accepted
            alert_types.append(str(r.get("type", r.get("alertType", "alert"))))
            level = r.get("level", "info")
            if isinstance(level, str):
                lv = _LEVEL_ALIASES.get(level.lower())
                if lv is None:
                    raise DecodeError(f"bad alert level {level!r}")
                level = lv
            alert_levels[i] = int(level)
            mtypes.append(None)
            origins.append(None)
            if "latitude" in r and "longitude" in r:
                lats[i] = float(r["latitude"])
                lons[i] = float(r["longitude"])
        else:
            # COMMAND_INVOCATION / COMMAND_RESPONSE / STATE_CHANGE rows:
            # only the correlation token beyond type + timestamp (the
            # scalar path resolves the same fields — never diverge)
            mtypes.append(None)
            alert_types.append(None)
            if kind == RequestKind.COMMAND_RESPONSE:
                origins.append(r.get("originatingEventId"))
            elif kind == RequestKind.COMMAND_INVOCATION:
                origins.append(r.get("invocationToken"))
            else:
                origins.append(None)

    columns: Dict[str, object] = {
        "device_token": tokens,
        "event_type": event_type,
        "ts_s": ts_s, "ts_ns": ts_ns,
        "mtype": mtypes, "value": values,
        "lat": lats, "lon": lons, "elevation": elevs,
        "alert_type": alert_types,
        "alert_level": alert_levels,
        "update_state": update_state,
    }
    if any(o is not None for o in origins):
        columns["origin"] = origins
    return columns, []


def _decode_generic(docs) -> Tuple[Dict[str, object], List[DecodedRequest]]:
    """Slow path: hardwareId alias, host-plane lines, full diagnostics."""
    events: List[tuple] = []
    host: List[DecodedRequest] = []
    for doc in docs:
        token, kind_name, req = envelope_fields(doc)
        kind = _TYPE_ALIASES.get(kind_name.strip().lower())
        if kind is None:
            raise DecodeError(f"unknown request type {kind_name!r}")
        if int(kind) in _EVENT_KINDS:
            events.append((token, kind, req))
        else:
            host.append(_decode_one(token, kind_name, req))

    if not events:
        return {"device_token": [], "mtype": [], "alert_type": []}, host
    tokens = [t for t, _, _ in events]
    kinds = [k for _, k, _ in events]
    reqs = [r for _, _, r in events]
    n = len(events)
    ts_s, ts_ns = _ts_columns(reqs)
    event_type = np.fromiter(map(int, kinds), np.int32, n)
    update_state = np.fromiter(
        (r.get("updateState", True) for r in reqs), np.bool_, n)
    columns, _ = _decode_mixed(tokens, kinds, reqs, ts_s, ts_ns,
                               event_type, update_state)
    return columns, host


def resolve_columns(
    columns: Dict[str, object],
    resolve_device,
    resolve_mtype,
    resolve_alert,
    invocations=None,
) -> Dict[str, np.ndarray]:
    """Map token/name columns to dense handles → batcher-ready arrays.

    Hot-path shape: device tokens resolve through the HandleSpace's bulk
    lookup when available (one C-level listcomp instead of a Python
    callable per token), and name columns memoize per payload (a fleet
    payload typically carries a handful of measurement names).  Columns
    the C resolved scanner already mapped (``device_id``, ``alert_code``,
    ``mtype_uniq``/``mtype_idx``) pass through; only the unique names are
    minted here — the HandleSpace stays the one authority for handles.
    """
    n = n_rows(columns)
    out: Dict[str, np.ndarray] = {
        k: columns[k]
        for k in ("event_type", "ts_s", "ts_ns", "value", "lat", "lon",
                  "elevation", "alert_level", "update_state")
    }
    if "device_id" in columns:
        out["device_id"] = np.asarray(columns["device_id"], np.int32)
    else:
        tokens = columns["device_token"]
        owner = space_of(resolve_device)
        if owner is not None:
            out["device_id"] = np.asarray(
                owner.lookup_many(tokens), np.int32)
        else:
            out["device_id"] = np.fromiter(
                (resolve_device(t) for t in tokens), np.int32, n)

    def memoized(names, resolve) -> np.ndarray:
        mapping = {
            m: (NULL_ID if m is None else resolve(m)) for m in set(names)
        }
        return np.asarray([mapping[m] for m in names], np.int32)

    if "mtype_uniq" in columns:
        uniq_ids = np.asarray(
            [resolve_mtype(u) for u in columns["mtype_uniq"]], np.int32)
        out["mtype_id"] = (uniq_ids[columns["mtype_idx"]] if len(uniq_ids)
                           else np.full(n, NULL_ID, np.int32))
    else:
        out["mtype_id"] = memoized(columns["mtype"], resolve_mtype)
    if "alert_code" in columns:
        out["alert_code"] = np.asarray(columns["alert_code"], np.int32)
    else:
        out["alert_code"] = memoized(columns["alert_type"], resolve_alert)
    origins = columns.get("origin")
    if origins is not None and invocations is not None:
        from sitewhere_tpu.schema import EventType

        et = np.asarray(columns["event_type"])
        cid = np.full(n, NULL_ID, np.int32)
        for i, tok in enumerate(origins):
            if tok:
                # invocations MINT their token (host- or replay-created);
                # responses only LOOK UP — an unknown/garbage token stays
                # uncorrelated instead of permanently allocating a handle
                cid[i] = (invocations.mint(tok)
                          if et[i] == int(EventType.COMMAND_INVOCATION)
                          else invocations.lookup(tok))
        out["command_id"] = cid
    return out
