"""AMQP 0-9-1 client: native RabbitMQ ingest without broker plugins.

Reference: ``service-event-sources/.../rabbitmq/RabbitMqInboundEventReceiver.java``
consumes a RabbitMQ queue through the Java AMQP client.  The STOMP
receiver (:mod:`sitewhere_tpu.ingest.stomp`) reaches RabbitMQ only when
its STOMP plugin is enabled; this module speaks the broker's NATIVE
protocol — a from-scratch consume-side AMQP 0-9-1 client
(https://www.rabbitmq.com/resources/specs/amqp0-9-1.pdf):

- protocol handshake (``AMQP\\x00\\x00\\x09\\x01``), PLAIN
  authentication, tune negotiation (frame-max + heartbeat), vhost open;
- one channel: ``basic.qos`` prefetch, ``queue.declare`` (idempotent),
  ``basic.consume`` with explicit acks;
- every delivery (method + content header + body frames, multi-frame
  bodies reassembled) feeds the sink and is ``basic.ack``ed ONLY after
  the sink accepts — a crash between delivery and journal append
  redelivers (the broker plays the Kafka-offset role the reference
  relies on, ``MicroserviceKafkaConsumer.java:94``);
- negotiated heartbeats with a dead-connection cutoff and
  capped-exponential reconnect, like the other socket receivers.

Consume-side only by design: command egress uses the MQTT/CoAP/HTTP
destinations; publishing to AMQP would go through an outbound connector.
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

from sitewhere_tpu.ingest.sources import Receiver, logger
from sitewhere_tpu.runtime.overload import OverloadShed

PROTOCOL_HEADER = b"AMQP\x00\x00\x09\x01"

FRAME_METHOD = 1
FRAME_HEADER = 2
FRAME_BODY = 3
FRAME_HEARTBEAT = 8
FRAME_END = 0xCE

# (class, method) ids used by the consume path
CONNECTION_START = (10, 10)
CONNECTION_START_OK = (10, 11)
CONNECTION_TUNE = (10, 30)
CONNECTION_TUNE_OK = (10, 31)
CONNECTION_OPEN = (10, 40)
CONNECTION_OPEN_OK = (10, 41)
CONNECTION_CLOSE = (10, 50)
CONNECTION_CLOSE_OK = (10, 51)
CHANNEL_OPEN = (20, 10)
CHANNEL_OPEN_OK = (20, 11)
CHANNEL_CLOSE = (20, 40)
CHANNEL_CLOSE_OK = (20, 41)
QUEUE_DECLARE = (50, 10)
QUEUE_DECLARE_OK = (50, 11)
BASIC_QOS = (60, 10)
BASIC_QOS_OK = (60, 11)
BASIC_CONSUME = (60, 20)
BASIC_CONSUME_OK = (60, 21)
BASIC_DELIVER = (60, 60)
BASIC_ACK = (60, 80)
BASIC_NACK = (60, 120)


class AmqpError(Exception):
    """Protocol violation or broker-initiated close."""


# -- wire primitives --------------------------------------------------------

def shortstr(s: str) -> bytes:
    b = s.encode("utf-8")
    if len(b) > 255:
        raise AmqpError(f"shortstr too long ({len(b)})")
    return bytes([len(b)]) + b


def longstr(b: bytes) -> bytes:
    return struct.pack(">I", len(b)) + b


def field_table(d: Dict[str, object]) -> bytes:
    """Encode a field table (the subset the handshake needs: longstr,
    bool, signed 32-bit int, nested table)."""
    out = bytearray()
    for k, v in d.items():
        out += shortstr(k)
        if isinstance(v, bool):
            out += b"t" + (b"\x01" if v else b"\x00")
        elif isinstance(v, int):
            out += b"I" + struct.pack(">i", v)
        elif isinstance(v, dict):
            out += b"F" + field_table(v)
        else:
            raw = v if isinstance(v, bytes) else str(v).encode("utf-8")
            out += b"S" + longstr(raw)
    return longstr(bytes(out))


def frame(ftype: int, channel: int, payload: bytes) -> bytes:
    return (struct.pack(">BHI", ftype, channel, len(payload))
            + payload + bytes([FRAME_END]))


def method_frame(channel: int, cm: Tuple[int, int], args: bytes = b"") -> bytes:
    return frame(FRAME_METHOD, channel,
                 struct.pack(">HH", cm[0], cm[1]) + args)


def parse_shortstr(buf: bytes, off: int) -> Tuple[str, int]:
    n = buf[off]
    return buf[off + 1: off + 1 + n].decode("utf-8"), off + 1 + n


class FrameReader:
    """Incremental AMQP frame parser → (type, channel, payload) tuples."""

    def __init__(self, max_frame: int = 16 << 20):
        self._buf = bytearray()
        self.max_frame = max_frame

    def feed(self, data: bytes) -> List[Tuple[int, int, bytes]]:
        self._buf += data
        frames: List[Tuple[int, int, bytes]] = []
        while len(self._buf) >= 7:
            ftype, channel, size = struct.unpack_from(">BHI", self._buf, 0)
            if size > self.max_frame:
                raise AmqpError(f"frame too large: {size}")
            if len(self._buf) < 7 + size + 1:
                break
            end = self._buf[7 + size]
            if end != FRAME_END:
                raise AmqpError(f"bad frame end 0x{end:02x}")
            frames.append((ftype, channel,
                           bytes(self._buf[7: 7 + size])))
            del self._buf[: 7 + size + 1]
        return frames


class AmqpReceiver(Receiver):
    """Consume one AMQP queue; every delivery body is an encoded event
    payload, acked only after the sink accepts it."""

    CHANNEL = 1

    def __init__(self, host: str, port: int = 5672, vhost: str = "/",
                 queue: str = "sitewhere.input",
                 username: str = "guest", password: str = "guest",
                 prefetch: int = 64, declare: bool = True,
                 durable: bool = True, heartbeat_s: int = 10,
                 reconnect_delay_s: float = 0.5,
                 max_reconnect_delay_s: float = 30.0):
        super().__init__(name=f"amqp-receiver:{host}:{port}/{queue}")
        # basic.ack is sent only AFTER the sink accepts the delivery:
        # the ingest decode pool must keep this source synchronous or
        # the ack would precede the journal append (at-least-once)
        self.acks_on_emit = True
        self.host, self.port = host, port
        self.vhost = vhost
        self.queue = queue
        self.username, self.password = username, password
        self.prefetch = prefetch
        self.declare = declare
        self.durable = durable
        self.heartbeat_s = heartbeat_s
        self.reconnect_delay_s = reconnect_delay_s
        self.max_reconnect_delay_s = max_reconnect_delay_s
        self._alive = False
        self._stop_evt = threading.Event()
        self._sock: Optional[socket.socket] = None
        self.connects = 0
        self.acked = 0
        self.nacked = 0
        self.emit_errors = 0
        # consecutive sink failures → escalating pre-nack delay, so a
        # persistently failing sink (nack → broker requeues near the
        # head → instant redelivery to this sole consumer) degrades to a
        # slow retry loop, not a CPU-burning redeliver/nack spin
        self._nack_streak = 0
        # same pacing for overload sheds (tracked separately: a shed is
        # backpressure, not a fault — no error counters, no logs)
        self._shed_streak = 0
        # Frames parsed past the one a handshake step awaited (the broker
        # may coalesce e.g. consume-ok + the first deliver into one TCP
        # segment); _consume drains these before its first recv.
        self._pending: Deque[Tuple[int, int, bytes]] = deque()

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self._alive = True
        self._stop_evt.clear()
        # Supervised (ROADMAP: remaining-receiver chaos coverage):
        # transport errors are handled by the reconnect loop itself;
        # the supervisor catches anything unexpected — a frame-codec
        # bug (struct.error/IndexError from a malformed frame), an
        # injected fault escaping the per-delivery guard — and restarts
        # the whole loop with backoff instead of silently killing the
        # consumer thread, escalating terminally after max_restarts.
        self._spawn_supervised(self._loop)
        super().start()

    def stop(self) -> None:
        self._alive = False
        self._stop_evt.set()
        sock = self._sock
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        self._stop_supervisor()
        super().stop()

    # -- session -------------------------------------------------------------

    def _expect(self, sock: socket.socket, reader: FrameReader,
                cm: Tuple[int, int]) -> bytes:
        """Read frames until the wanted method arrives on any channel;
        heartbeats are tolerated, anything else is a protocol error.

        Frames the broker coalesced into the same TCP segment AFTER the
        awaited method (e.g. a basic.deliver right behind consume-ok)
        stay on ``self._pending`` for the consume loop — returning
        mid-batch must not drop them, or they would sit unacked at the
        broker forever while eating prefetch window."""
        while True:
            while self._pending:
                ftype, channel, payload = self._pending.popleft()
                if ftype == FRAME_HEARTBEAT:
                    continue
                if ftype != FRAME_METHOD or len(payload) < 4:
                    raise AmqpError(f"unexpected frame type {ftype}")
                got = struct.unpack_from(">HH", payload, 0)
                if got == CONNECTION_CLOSE:
                    code, off = struct.unpack_from(">H", payload, 4)[0], 6
                    text, off = parse_shortstr(payload, off)
                    raise AmqpError(f"broker closed: {code} {text}")
                if got != cm:
                    raise AmqpError(f"expected {cm}, got {got}")
                return payload[4:]
            data = sock.recv(65536)
            if not data:
                raise AmqpError("broker closed during handshake")
            self._pending.extend(reader.feed(data))

    def _connect(self) -> Tuple[socket.socket, FrameReader, float]:
        sock = socket.create_connection((self.host, self.port), timeout=10)
        try:
            return self._handshake(sock)
        except BaseException:
            try:
                sock.close()
            except OSError:
                pass
            raise

    def _handshake(self, sock) -> Tuple[socket.socket, FrameReader, float]:
        sock.settimeout(10)
        reader = FrameReader()
        self._pending.clear()  # nothing carried over from a dead session
        sock.sendall(PROTOCOL_HEADER)
        self._expect(sock, reader, CONNECTION_START)
        response = b"\x00" + self.username.encode() + b"\x00" + \
            self.password.encode()
        sock.sendall(method_frame(0, CONNECTION_START_OK,
                     field_table({"product": "sitewhere-tpu",
                                  "platform": "python"})
                     + shortstr("PLAIN") + longstr(response)
                     + shortstr("en_US")))
        tune = self._expect(sock, reader, CONNECTION_TUNE)
        channel_max, frame_max, hb = struct.unpack_from(">HIH", tune, 0)
        # negotiate DOWN: 0 from either side means "no limit"/"disabled"
        frame_max = min(frame_max or 1 << 20, 1 << 20)
        heartbeat = (min(hb, self.heartbeat_s) if hb and self.heartbeat_s
                     else (hb or self.heartbeat_s))
        sock.sendall(method_frame(0, CONNECTION_TUNE_OK, struct.pack(
            ">HIH", min(channel_max or 2047, 2047), frame_max, heartbeat)))
        sock.sendall(method_frame(0, CONNECTION_OPEN,
                                  shortstr(self.vhost) + shortstr("")
                                  + b"\x00"))
        self._expect(sock, reader, CONNECTION_OPEN_OK)

        ch = self.CHANNEL
        sock.sendall(method_frame(ch, CHANNEL_OPEN, shortstr("")))
        self._expect(sock, reader, CHANNEL_OPEN_OK)
        sock.sendall(method_frame(ch, BASIC_QOS, struct.pack(
            ">IHB", 0, self.prefetch, 0)))
        self._expect(sock, reader, BASIC_QOS_OK)
        if self.declare:
            flags = 0x02 if self.durable else 0  # durable bit
            sock.sendall(method_frame(ch, QUEUE_DECLARE, struct.pack(
                ">H", 0) + shortstr(self.queue) + bytes([flags])
                + field_table({})))
            self._expect(sock, reader, QUEUE_DECLARE_OK)
        # no-local=0 no-ack=0 exclusive=0 no-wait=0 → explicit acks
        sock.sendall(method_frame(ch, BASIC_CONSUME, struct.pack(
            ">H", 0) + shortstr(self.queue) + shortstr("") + b"\x00"
            + field_table({})))
        self._expect(sock, reader, BASIC_CONSUME_OK)
        return sock, reader, float(heartbeat)

    # -- consume loop --------------------------------------------------------

    def _loop(self) -> None:
        delay = self.reconnect_delay_s
        while self._alive:
            try:
                sock, reader, heartbeat = self._connect()
            except (OSError, AmqpError) as e:
                if not self._alive:
                    return
                logger.warning("%s: connect failed (%s); retry in %.1fs",
                               self.name, e, delay)
                if self._stop_evt.wait(delay):
                    return
                delay = min(delay * 2, self.max_reconnect_delay_s)
                continue
            self._sock = sock
            self.connects += 1
            delay = self.reconnect_delay_s
            try:
                self._consume(sock, reader, heartbeat)
            except (OSError, AmqpError) as e:
                if self._alive:
                    logger.warning("%s: session lost (%s); reconnecting",
                                   self.name, e)
            finally:
                self._sock = None
                try:
                    sock.close()
                except OSError:
                    pass

    def _consume(self, sock, reader: FrameReader, heartbeat: float) -> None:
        # in-flight delivery assembly state
        delivery_tag: Optional[int] = None
        body_size = 0
        body = bytearray()
        last_rx = time.monotonic()
        last_tx = time.monotonic()
        sock.settimeout(max(0.2, heartbeat / 4 if heartbeat else 5.0))
        while self._alive:
            now = time.monotonic()
            if heartbeat:
                if now - last_rx > 2 * heartbeat:
                    raise AmqpError("heartbeat timeout")
                if now - last_tx >= heartbeat:
                    sock.sendall(frame(FRAME_HEARTBEAT, 0, b""))
                    last_tx = now
            if self._pending:
                # deliveries the handshake's _expect already parsed
                frames = list(self._pending)
                self._pending.clear()
            else:
                try:
                    data = sock.recv(65536)
                except socket.timeout:
                    continue
                if not data:
                    raise AmqpError("connection closed by broker")
                last_rx = time.monotonic()
                frames = reader.feed(data)
            for ftype, channel, payload in frames:
                if ftype == FRAME_HEARTBEAT:
                    continue
                if ftype == FRAME_METHOD:
                    cm = struct.unpack_from(">HH", payload, 0)
                    if cm == BASIC_DELIVER:
                        off = 4
                        _tag, off = parse_shortstr(payload, off)
                        delivery_tag = struct.unpack_from(
                            ">Q", payload, off)[0]
                        body = bytearray()
                        body_size = -1  # header frame pending
                    elif cm == CONNECTION_CLOSE:
                        sock.sendall(method_frame(0, CONNECTION_CLOSE_OK))
                        raise AmqpError("broker closed connection")
                    elif cm == CHANNEL_CLOSE:
                        sock.sendall(method_frame(
                            channel, CHANNEL_CLOSE_OK))
                        raise AmqpError("broker closed channel")
                    # consume-path replies (qos-ok etc. mid-stream): ignore
                elif ftype == FRAME_HEADER and delivery_tag is not None:
                    body_size = struct.unpack_from(">Q", payload, 4)[0]
                    if body_size == 0:
                        last_tx = self._finish(sock, delivery_tag, bytes(body),
                                               last_tx)
                        delivery_tag = None
                elif ftype == FRAME_BODY and delivery_tag is not None:
                    body += payload
                    if body_size >= 0 and len(body) >= body_size:
                        last_tx = self._finish(sock, delivery_tag, bytes(body),
                                               last_tx)
                        delivery_tag = None

    def _finish(self, sock, delivery_tag: int, payload: bytes,
                last_tx: float) -> float:
        """Sink the payload; ack ONLY on acceptance (redelivery covers a
        crash; a poison payload dead-letters in the sink and is acked so
        it does not loop forever).

        A sink that RAISES (transient failure: journal full, downstream
        stall) gets ``basic.nack`` with requeue — leaving the delivery
        unacked would strand it until connection close and, after
        ``prefetch`` such failures, stall the consumer forever on an
        otherwise-healthy session.  Consecutive failures back off
        (50 ms doubling to 1 s) before the nack, because the broker
        redelivers a requeued message to this sole consumer immediately.

        An admission SHED is different from a failure but takes the
        same wire action, separately paced and counted: an escalating
        pause, then ``basic.nack`` with requeue.  Leaving the delivery
        unacked instead would eat the prefetch window on a
        heartbeat-healthy session that never recycles — after
        ``prefetch`` sheds the broker stops delivering and the consumer
        is wedged FOREVER, even after overload clears (the exact stall
        documented above).  The pre-nack pause is the backpressure; the
        requeued message redelivers (at-least-once) and lands once
        admission reopens."""
        try:
            self._emit(payload)
        except OverloadShed as e:
            self._shed_streak += 1
            delay = min(max(0.05, e.retry_after_s / 16)
                        * (2 ** min(self._shed_streak - 1, 6)), 1.0)
            self._stop_evt.wait(delay)
            sock.sendall(method_frame(
                self.CHANNEL, BASIC_NACK,
                struct.pack(">QB", delivery_tag, 0x02)))
            return time.monotonic()
        except Exception:
            self.emit_errors += 1
            self.nacked += 1
            self._nack_streak += 1
            logger.exception("%s: sink rejected payload; nack + requeue "
                             "(streak %d)", self.name, self._nack_streak)
            delay = min(0.05 * (2 ** min(self._nack_streak - 1, 10)), 1.0)
            self._stop_evt.wait(delay)
            # bits: 0x01 multiple, 0x02 requeue → requeue only
            sock.sendall(method_frame(
                self.CHANNEL, BASIC_NACK,
                struct.pack(">QB", delivery_tag, 0x02)))
            return time.monotonic()
        self._nack_streak = 0
        self._shed_streak = 0
        sock.sendall(method_frame(
            self.CHANNEL, BASIC_ACK,
            struct.pack(">QB", delivery_tag, 0)))
        self.acked += 1
        return time.monotonic()
