"""Config-driven event-source construction (EventSourcesParser analog).

Reference: tenant configuration XML declares each event source — its
receivers, decoder, and deduplicator — and
``spring/EventSourcesParser.java:27-50`` materializes them into the
running engine.  Here the same declaration lives in the instance config
tree::

    "sources": [
        {"id": "wire", "decoder": "json",
         "receivers": [{"type": "tcp", "port": 7011,
                        "framing": "newline"}]},
        {"id": "mq", "decoder": "jsonlines", "dedup": {"window": 65536},
         "receivers": [{"type": "stomp", "host": "broker", "port": 61613,
                        "destination": "/queue/telemetry"}]},
    ]

and :func:`build_sources` materializes :class:`InboundEventSource`
instances the caller attaches via ``Instance.add_source`` (which wires
the dispatcher/forwarder sinks).  Receiver types map to the transports
in :mod:`sitewhere_tpu.ingest.sources` (+ CoAP and STOMP); decoder names
to :mod:`sitewhere_tpu.ingest.decoders`.  Unknown types raise
``ValidationError`` at build time — a config typo must fail boot, not
silently drop a source (the reference's schema-validated XML gives the
same guarantee).
"""

from __future__ import annotations

from typing import Dict, List

from sitewhere_tpu.services.common import ValidationError

_FRAMINGS = ("length", "newline")


def _build_receiver(doc: Dict):
    from sitewhere_tpu.ingest import coap, sources, stomp

    if not isinstance(doc, dict):
        raise ValidationError(f"receiver entry must be an object: {doc!r}")
    kind = str(doc.get("type", "")).lower()
    args = {k: v for k, v in doc.items() if k != "type"}
    try:
        if kind == "tcp":
            framing = str(args.pop("framing", "length")).lower()
            if framing not in _FRAMINGS:
                raise ValidationError(
                    f"tcp framing must be one of {_FRAMINGS}: {framing!r}")
            return sources.TcpReceiver(
                host=str(args.pop("host", "127.0.0.1")),
                port=int(args.pop("port", 0)),
                framing=(sources.newline_frames if framing == "newline"
                         else sources.length_prefixed_frames),
                **args)
        if kind == "udp":
            return sources.UdpReceiver(
                host=str(args.pop("host", "127.0.0.1")),
                port=int(args.pop("port", 0)), **args)
        if kind == "http":
            return sources.HttpReceiver(
                host=str(args.pop("host", "127.0.0.1")),
                port=int(args.pop("port", 0)),
                path=str(args.pop("path", "/events")), **args)
        if kind == "mqtt":
            return sources.MqttReceiver(
                host=str(args.pop("host")),
                port=int(args.pop("port", 1883)),
                topic=str(args.pop("topic", "sitewhere/input")), **args)
        if kind in ("mqtt-broker", "hosted-mqtt"):
            # hosts an in-process broker: devices connect directly, no
            # external middleware (ActiveMQBrokerEventReceiver analog)
            from sitewhere_tpu.ingest import mqtt_broker

            return mqtt_broker.MqttBrokerReceiver(
                host=str(args.pop("host", "127.0.0.1")),
                # the conventional MQTT port: devices must be able to
                # find the hosted broker without reading logs (an
                # ephemeral port would move every restart)
                port=int(args.pop("port", 1883)),
                topic_filter=str(args.pop(
                    "topic_filter", "sitewhere/input/#")), **args)
        if kind == "stomp":
            return stomp.StompReceiver(
                host=str(args.pop("host")),
                port=int(args.pop("port", 61613)),
                destination=str(args.pop(
                    "destination", "/queue/sitewhere.input")), **args)
        if kind in ("amqp", "rabbitmq"):
            from sitewhere_tpu.ingest import amqp

            return amqp.AmqpReceiver(
                host=str(args.pop("host")),
                port=int(args.pop("port", 5672)),
                queue=str(args.pop("queue", "sitewhere.input")), **args)
        if kind in ("eventhub", "amqp10"):
            from sitewhere_tpu.ingest import amqp10

            return amqp10.EventHubReceiver(
                host=str(args.pop("host")),
                port=int(args.pop("port", 5672)),
                event_hub=str(args.pop("event_hub", "sitewhere")),
                **args)
        if kind == "coap":
            return coap.CoapServerReceiver(
                host=str(args.pop("host", "127.0.0.1")),
                port=int(args.pop("port", 0)), **args)
        if kind in ("ws", "websocket"):
            return sources.WebSocketReceiver(
                host=str(args.pop("host")),
                port=int(args.pop("port")),
                path=str(args.pop("path", "/")), **args)
        if kind in ("poll", "polling-rest"):
            return sources.PollingRestReceiver(
                url=str(args.pop("url")),
                interval_s=float(args.pop("interval_s", 10.0)), **args)
    except ValidationError:
        raise
    except (KeyError, TypeError, ValueError) as e:
        raise ValidationError(f"bad {kind!r} receiver config: {e}") from e
    raise ValidationError(f"unknown receiver type {doc.get('type')!r}")


def _build_decoder(name: str, scripts=None):
    from sitewhere_tpu.ingest import decoders

    key = str(name).lower()
    table = {
        "json": decoders.JsonDecoder,
        "jsonlines": decoders.JsonLinesDecoder,
        "batch": decoders.JsonBatchDecoder,
        "binary": decoders.BinaryDecoder,
    }
    if key in table:
        return table[key]()
    if scripts is not None:
        try:
            meta = scripts.describe(str(name))
        except Exception:
            raise ValidationError(f"unknown decoder {name!r}")
        if meta.get("kind") != "decoder":
            # must fail BOOT: at runtime the kind mismatch would raise
            # past the sources' DecodeError handling into the transport
            # thread, silently losing every payload
            raise ValidationError(
                f"script {name!r} is a {meta.get('kind')}, not a decoder")
        # runtime-uploaded decoder script (ScriptSynchronizer analog):
        # resolves the ACTIVE version on every call, so uploads swap
        # behavior live
        return scripts.as_decoder(str(name))
    raise ValidationError(f"unknown decoder {name!r}")


def build_sources(docs: List[Dict], scripts=None) -> List:
    """Materialize ``InboundEventSource`` objects from config documents."""
    from sitewhere_tpu.ingest.dedup import AlternateIdDeduplicator
    from sitewhere_tpu.ingest.sources import InboundEventSource

    out = []
    for doc in docs or []:
        if not isinstance(doc, dict):
            raise ValidationError(f"source entry must be an object: {doc!r}")
        source_id = str(doc.get("id") or f"source-{len(out)}")
        receivers = [_build_receiver(r) for r in doc.get("receivers", [])]
        if not receivers:
            raise ValidationError(f"source {source_id!r} has no receivers")
        decoder = _build_decoder(doc.get("decoder", "json"), scripts)
        dedup_doc = doc.get("dedup")
        dedup = None
        if dedup_doc is not None:
            if not isinstance(dedup_doc, dict):
                raise ValidationError(
                    f"dedup must be an object: {dedup_doc!r}")
            unknown = set(dedup_doc) - {"window"}
            if unknown:
                raise ValidationError(
                    f"unknown dedup option(s): {sorted(unknown)}")
            dedup = AlternateIdDeduplicator(
                window=int(dedup_doc.get("window", 1 << 20)))
        raw_wire = bool(doc.get("raw_wire", False))
        if raw_wire and dedup is not None:
            # must fail BOOT: the raw lane never consults the
            # deduplicator, so accepting both would silently disable a
            # configured dedup window
            raise ValidationError(
                f"source {source_id!r}: raw_wire bypasses the decoder "
                "and dedup — remove the dedup block or raw_wire")
        if raw_wire and str(doc.get("decoder", "json")).lower() not in (
                "json", "jsonlines", "batch"):
            # same principle for the decoder: the raw lane feeds payloads
            # to the NDJSON columnar decode (which also accepts single
            # envelopes and JSON arrays — the json/jsonlines/batch wire
            # shapes), so a binary or script decoder here would be
            # silently disabled and every payload would dead-letter
            raise ValidationError(
                f"source {source_id!r}: raw_wire handles JSON wire "
                f"shapes only — decoder {doc.get('decoder')!r} would "
                "never run")
        out.append(InboundEventSource(
            source_id, receivers, decoder, deduplicator=dedup,
            raw_wire=raw_wire))
    return out
