"""Deadline-driven batcher: decoded requests → routed fixed-shape batches.

This is the seam between the variable-rate host world and the static-shape
SPMD pipeline (SURVEY.md §7 hard part #1).  The reference's analog is the
Kafka producer partitioner + consumer poll batching
(``EventSourcesManager.java:166``, ``MicroserviceKafkaConsumer.java:123-128``):
events keyed by device token land in per-partition record batches.  Here:

- intake is COLUMNAR: rows live in per-shard queues of numpy column
  chunks, written once at intake (vectorized ``add_arrays`` gathers one
  slice per field per shard; the scalar ``add`` paths append into a
  growable staging chunk) and copied exactly once more at emission, by
  slice, into the fixed-shape batch — no per-row per-field Python loops
  anywhere on the hot path;
- each event row is routed to the mesh shard that owns its device registry
  block (:func:`~sitewhere_tpu.parallel.mesh.shard_for_device`), preserving
  the shard-local-gather invariant of the sharded pipeline step;
- a batch is emitted when any shard segment fills (``width // n_shards``
  rows) or when the oldest pending event exceeds the deadline — bounding
  added latency the way the Mongo buffer bounds flush delay
  (``DeviceEventBuffer.java:40-46``, ≤250 ms there; default 5 ms here for
  the <10 ms p99 budget);
- rows that don't fit carry over to the next batch (no drops);
- unknown devices round-robin across shards and dead-letter on-device.
"""

from __future__ import annotations

import collections
import dataclasses
import time
from typing import Callable, Deque, Dict, List, Optional, Sequence

import numpy as np

from sitewhere_tpu.analysis.markers import hot_path
from sitewhere_tpu.ids import NULL_ID
from sitewhere_tpu.ingest.decoders import DecodedRequest
from sitewhere_tpu.parallel.mesh import shard_for_device
from sitewhere_tpu.schema import EventBatch

_FIELDS = (
    ("valid", np.bool_, False),
    ("device_id", np.int32, NULL_ID),
    ("tenant_id", np.int32, NULL_ID),
    ("event_type", np.int32, 0),
    ("ts_s", np.int32, 0),
    ("ts_ns", np.int32, 0),
    ("mtype_id", np.int32, NULL_ID),
    ("value", np.float32, 0.0),
    ("lat", np.float32, 0.0),
    ("lon", np.float32, 0.0),
    ("elevation", np.float32, 0.0),
    ("alert_code", np.int32, NULL_ID),
    ("alert_level", np.int32, 0),
    ("command_id", np.int32, NULL_ID),
    ("payload_ref", np.int32, NULL_ID),
    ("update_state", np.bool_, True),
)

# Data columns (everything but the emission-owned `valid` flag).
_COL_FIELDS = tuple(name for name, _, _ in _FIELDS[1:])
_DTYPE = {name: dt for name, dt, _ in _FIELDS}
_FILL = {name: fill for name, _, fill in _FIELDS}
# 0-d fill templates: `np.broadcast_to(_FILL_0D[f], n)` is a zero-copy
# 0-stride view of any length — intake never allocates a full column for
# an omitted field again (emission copies by slice regardless).  The
# views are read-only; nothing on the intake/emit path writes into a
# queued chunk's columns, only the freshly-allocated batch buffers.
_FILL_0D = {name: np.full((), fill, dt) for name, dt, fill in _FIELDS}
# Bytes one emitted row occupies across every batch column (the unit of
# the pipeline.bytes_copied.batch accounting).
_ROW_BYTES = sum(np.dtype(dt).itemsize for _, dt, _ in _FIELDS)

# Packed wire layout (pipeline/packed.py BATCH_I/BATCH_F), cached on
# first use — reservations allocate their columns AS rows of a packed
# buffer pair so a full-width reserved segment is H2D-ready as-is.
_PACKED_LAYOUT = None


def _packed_layout():
    global _PACKED_LAYOUT
    if _PACKED_LAYOUT is None:
        from sitewhere_tpu.pipeline.packed import BATCH_F, BATCH_I

        _PACKED_LAYOUT = (BATCH_I, BATCH_F,
                          {f: i for i, f in enumerate(BATCH_I)},
                          {f: i for i, f in enumerate(BATCH_F)})
    return _PACKED_LAYOUT


@dataclasses.dataclass
class _Chunk:
    """A columnar run of pending rows on one shard.

    ``start`` = rows already emitted; ``length`` = rows written.  A chunk
    whose backing arrays are longer than ``length`` is a *staging* chunk —
    the scalar add paths append into it in place (amortizing allocation);
    vectorized chunks arrive full (``length == capacity``).  A chunk
    carrying a ``reserved`` back-reference was filled in place by the
    fill-direct wire scanner (:meth:`Batcher.reserve`); when such a chunk
    is the sole content of a full-width packed emission, ``_emit`` adopts
    its buffers as the batch outright instead of copying.
    """

    cols: Dict[str, np.ndarray]
    length: int
    arrival: float
    start: int = 0
    reserved: Optional["Reservation"] = None
    # Row offset of this chunk inside its reservation's buffers (sharded
    # commits enqueue per-shard VIEWS of one buffer; adoption needs each
    # view to sit exactly at its shard's segment).
    res_off: int = 0

    @property
    def capacity(self) -> int:
        return len(self.cols["device_id"])


class Reservation:
    """A writable, packed-layout column segment for the fill-direct scan.

    :meth:`Batcher.reserve` hands the native wire scanner
    (``decode_measurement_lines_resolved_into``) direct int32/float32
    views into a fresh packed buffer pair — the same ``[C, B]`` rows the
    emitted batch ships H2D — so the hot path is recv → C scan+validate →
    in-place columnar write → H2D stage with zero intermediate copies.

    Contract:

    - the buffers are PRIVATE to this reservation until :meth:`commit`
      enqueues them under the dispatcher's intake lock, so concurrent
      decode workers can fill reservations in parallel and commit in
      delivery order — and a mid-payload bail simply never commits,
      leaving no torn rows by construction (:meth:`abort` just drops it);
    - the scanner writes ``device_id``, ``mtype_id`` (via the
      ``name_idx`` scratch + one remap), ``value``, ``ts_s``, ``ts_ns``
      and ``update_state``; every other column is a 0-stride fill
      template (PR 3's layout) or a per-payload constant
      (:meth:`set_const`) — nothing is materialized per row;
    - a full-width reservation that is the sole pending content when the
      batch emits is ADOPTED: its buffers become the packed plan and the
      batch-assembly copy disappears entirely.  Adopted ``host_cols``
      expose ``valid``/``update_state`` as int32 rows (not bool) — no
      egress consumer reads them, only the device does.
    """

    __slots__ = ("_batcher", "ibuf", "fbuf", "name_idx", "cap", "n",
                 "tenant_id", "payload_ref", "_open")

    def __init__(self, batcher: "Batcher", cap: int):
        _, _, bi, bf = _packed_layout()
        self._batcher = batcher
        self.cap = cap
        self.n = 0
        self.tenant_id = 0
        self.payload_ref = NULL_ID
        self._open = True
        self.ibuf = np.empty((len(bi), cap), np.int32)
        self.fbuf = np.empty((len(bf), cap), np.float32)
        self.name_idx = np.empty(cap, np.int32)
        if cap == batcher.width:
            # adoption candidate: pre-fill the columns the scanner never
            # writes (off the intake lock — commit stays O(1))
            for f in ("event_type", "alert_code", "alert_level",
                      "command_id"):
                self.ibuf[bi[f]].fill(_FILL[f])
            for f in ("lat", "lon", "elevation"):
                self.fbuf[bf[f]].fill(_FILL[f])

    # -- scanner-facing views (full-capacity, contiguous) -------------------

    def _irow(self, f: str) -> np.ndarray:
        return self.ibuf[_packed_layout()[2][f]]

    @property
    def device_id(self) -> np.ndarray:
        return self._irow("device_id")

    @property
    def mtype_id(self) -> np.ndarray:
        return self._irow("mtype_id")

    @property
    def ts_s(self) -> np.ndarray:
        return self._irow("ts_s")

    @property
    def ts_ns(self) -> np.ndarray:
        return self._irow("ts_ns")

    @property
    def update_state(self) -> np.ndarray:
        return self._irow("update_state")

    @property
    def value(self) -> np.ndarray:
        return self.fbuf[_packed_layout()[3]["value"]]

    def set_const(self, *, tenant_id: int, payload_ref: int) -> None:
        """Per-payload constants, applied as 0-stride broadcasts at
        commit (and materialized into their rows only on adoption)."""
        self.tenant_id = int(tenant_id)
        self.payload_ref = int(payload_ref)

    def abort(self) -> None:
        """Discard: nothing was shared, so nothing needs undoing."""
        self._open = False

    def commit(self) -> List[BatchPlan]:
        """Enqueue the ``self.n`` scanned rows (call under the intake
        lock, i.e. via the dispatcher's ``_take``).  Returns every plan
        that became ready, like :meth:`Batcher.add_arrays`."""
        b = self._batcher
        if not self._open:
            raise RuntimeError("reservation already committed/aborted")
        self._open = False
        n = self.n
        if n <= 0:
            return []
        # in-place NULL_ID rewrite (same contract as add_arrays): the C
        # table can hold ids at/past the registry capacity, and unknown
        # tokens are already NULL_ID.  The buffers are ours — no
        # defensive copy needed.
        d = self.device_id[:n]
        bad = (d < 0) | (d >= b.capacity)
        if b.n_shards > 1:
            # Sharded commit: the scanner wrote RESOLVED ids, so shard
            # routing is knowable here.  Segment-ordered payloads (each
            # shard's rows a contiguous run, runs in shard order) enqueue
            # zero-copy views of this buffer; anything else takes the
            # add_arrays gather lane (copies counted, unknown ids
            # round-robined there).
            return self._commit_sharded(b, n, bad)
        if bad.any():
            d[bad] = NULL_ID
        cols: Dict[str, np.ndarray] = {
            f: self._irow(f)[:n]
            for f in ("device_id", "mtype_id", "ts_s", "ts_ns",
                      "update_state")
        }
        cols["value"] = self.value[:n]
        cols["tenant_id"] = np.broadcast_to(
            np.int32(self.tenant_id), n)
        cols["payload_ref"] = np.broadcast_to(
            np.int32(self.payload_ref), n)
        for f in _COL_FIELDS:
            if f not in cols:
                cols[f] = np.broadcast_to(_FILL_0D[f], n)
        now = b.clock()
        b._pending[0].append(
            _Chunk(cols=cols, length=n, arrival=now, reserved=self))
        b._counts[0] += n
        if b._oldest is None:
            b._oldest = now
        plans: List[BatchPlan] = []
        while max(b._counts) >= b.seg:
            plans.append(b._emit())
        return plans

    def _commit_sharded(self, b: "Batcher", n: int,
                        bad: np.ndarray) -> List[BatchPlan]:
        """Sharded enqueue of the scanned rows.  The zero-copy lane
        requires every id in range and the shard sequence monotonically
        non-decreasing — then shard ``s``'s rows are one contiguous run
        and the chunk is a VIEW (``res_off`` records its buffer
        position, so a full-width segment-aligned reservation can be
        adopted outright by ``_emit``)."""
        d = self.device_id[:n]
        segmented = not bad.any()
        if segmented:
            shard = d // b.rows_per_shard
            if n > 1:
                segmented = bool((shard[:-1] <= shard[1:]).all())
        if not segmented:
            # Gather fallback: same routing/copy contract as columnar
            # intake (bad ids rewritten + round-robined there).  The
            # buffers are ours and never touched again — views are safe
            # to hand over.
            return b.add_arrays(
                _copy=False,
                device_id=d,
                mtype_id=self.mtype_id[:n],
                ts_s=self.ts_s[:n],
                ts_ns=self.ts_ns[:n],
                update_state=self.update_state[:n],
                value=self.value[:n],
                tenant_id=np.broadcast_to(np.int32(self.tenant_id), n),
                payload_ref=np.broadcast_to(np.int32(self.payload_ref), n),
            )
        now = b.clock()
        bounds = np.searchsorted(shard, np.arange(b.n_shards + 1))
        for s in range(b.n_shards):
            lo, hi = int(bounds[s]), int(bounds[s + 1])
            if lo == hi:
                continue
            cols: Dict[str, np.ndarray] = {
                f: self._irow(f)[lo:hi]
                for f in ("device_id", "mtype_id", "ts_s", "ts_ns",
                          "update_state")
            }
            cols["value"] = self.value[lo:hi]
            cols["tenant_id"] = np.broadcast_to(
                np.int32(self.tenant_id), hi - lo)
            cols["payload_ref"] = np.broadcast_to(
                np.int32(self.payload_ref), hi - lo)
            for f in _COL_FIELDS:
                if f not in cols:
                    cols[f] = np.broadcast_to(_FILL_0D[f], hi - lo)
            b._pending[s].append(_Chunk(
                cols=cols, length=hi - lo, arrival=now, reserved=self,
                res_off=lo))
            b._counts[s] += hi - lo
        if b._oldest is None:
            b._oldest = now
        plans: List[BatchPlan] = []
        while max(b._counts) >= b.seg:
            plans.append(b._emit())
        return plans

    def finalize_adopted(self, n: int) -> Dict[str, np.ndarray]:
        """Emission-time completion of an adopted full-width buffer:
        write validity, the per-payload constants and the padding fills
        into their rows, and return the host-column views."""
        BATCH_I, BATCH_F, bi, bf = _packed_layout()
        ibuf, fbuf = self.ibuf, self.fbuf
        valid = ibuf[bi["valid"]]
        valid[:n] = 1
        valid[n:] = 0
        ibuf[bi["tenant_id"]][:n] = self.tenant_id
        ibuf[bi["payload_ref"]][:n] = self.payload_ref
        if n < self.cap:
            ibuf[bi["tenant_id"]][n:] = _FILL["tenant_id"]
            ibuf[bi["payload_ref"]][n:] = _FILL["payload_ref"]
            for f in ("device_id", "mtype_id", "ts_s", "ts_ns",
                      "update_state"):
                ibuf[bi[f]][n:] = _FILL[f]
            fbuf[bf["value"]][n:] = _FILL["value"]
        host_cols = {f: ibuf[i] for i, f in enumerate(BATCH_I)}
        host_cols.update({f: fbuf[i] for i, f in enumerate(BATCH_F)})
        return host_cols


class BatchPlan:
    """A ready-to-dispatch batch plus its host-side bookkeeping.

    ``host_cols`` keeps the numpy columns the device batch was built from
    so egress never has to fetch the input batch back off the device —
    only step *outputs* cross the host boundary after dispatch.

    The device :class:`EventBatch` is materialized LAZILY: ``_emit``
    runs under the dispatcher's intake lock, and building the unpacked
    batch there meant 16 host→device transfers while every source
    thread's intake was blocked (swlint lock-discipline LK004).  The
    emitter now hands over only the numpy ``host_cols``; the first
    ``plan.batch`` access — the dispatcher stages plans before taking
    any lock — pays the transfers off-lock.
    """

    __slots__ = ("_batch", "n_events", "width", "created_at", "max_wait_s",
                 "host_cols", "packed_i", "packed_f", "staged", "seq",
                 "reason", "dispatch_s")

    def __init__(
        self,
        batch: Optional[EventBatch] = None,
        n_events: int = 0,
        width: int = 1,
        created_at: float = 0.0,
        max_wait_s: float = 0.0,  # how long the oldest row waited
        host_cols: Optional[Dict[str, np.ndarray]] = None,
        # Packed wire form ([12, B] int32 / [4, B] float32,
        # pipeline/packed.py) when the batcher was built with
        # ``emit_packed`` — then ``batch`` is None and the dispatcher
        # feeds the packed step directly (2 transfers instead of 16).
        packed_i: Optional[np.ndarray] = None,
        packed_f: Optional[np.ndarray] = None,
        # Device-resident (bi, bf) pair staged ahead of the step by the
        # dispatcher (pipeline/packed.py stage_packed_batch): the H2D
        # copy of plan N+1 overlaps plan N's device step.  None =
        # unstaged (sync transfer at step-call time, the CPU fallback).
        staged: Optional[tuple] = None,
        # Emission bookkeeping for the device-resident dispatch ring:
        # ``seq`` is the batcher's monotonic emission number (commit/
        # egress attribution of a chained step), ``reason`` the emit
        # trigger ("fill" | "deadline" | "flush").  Only full-width fill
        # emissions ride the ring; deadline/flush partials are latency-
        # sensitive and take the single-step path.
        seq: int = -1,
        reason: str = "fill",
        # Host dispatch time this plan paid (single-step: the jitted
        # call; ring slot: its 1/K share of the chain dispatch) —
        # flight-recorder stage attribution, stamped by the dispatcher.
        dispatch_s: float = 0.0,
    ):
        self._batch = batch
        self.n_events = n_events
        self.width = width
        self.created_at = created_at
        self.max_wait_s = max_wait_s
        self.host_cols = host_cols if host_cols is not None else {}
        self.packed_i = packed_i
        self.packed_f = packed_f
        self.staged = staged
        self.seq = seq
        self.reason = reason
        self.dispatch_s = dispatch_s

    def materialize_batch(self) -> Optional[EventBatch]:
        """Build (and cache) the device EventBatch from ``host_cols`` —
        call OFF the intake/step locks; packed plans return None (they
        ship ``packed_i``/``packed_f`` instead)."""
        if self._batch is None and self.packed_i is None and self.host_cols:
            import jax.numpy as jnp

            self._batch = EventBatch(
                **{k: jnp.asarray(v) for k, v in self.host_cols.items()})
        return self._batch

    @property
    def batch(self) -> Optional[EventBatch]:
        return self.materialize_batch()

    @property
    def fill(self) -> float:
        return self.n_events / self.width


class AdaptiveBatchController:
    """Load-adaptive emission window (the deadline the batcher emits on).

    The batch WIDTH is compiled into the jitted step and cannot change
    per plan — the adaptive knob is the *time window* a partial batch may
    coalesce before the deadline forces it out.  The stream-processing
    literature identifies exactly this trade (arxiv 1807.07724 §5,
    2307.14287 §4): small windows chase the latency SLO, large windows
    chase throughput, and a static setting is wrong at one end or the
    other.  Decisions are made per EMIT (never per row) from signals the
    batcher already has:

    - a deadline emit at low fill with nothing left pending → the stream
      is idle; SHRINK the window toward ``min_s`` (less added latency);
    - a segment-fill emit, or a full batch still pending after an emit →
      the stream is backlogged; GROW the window toward ``max_s`` (fuller
      batches, fewer partial-width dispatches).

    Deterministic: no internal clock — driven entirely by the batcher's
    emits, so a fake-clock test replays decisions exactly.  Decisions are
    exported through the metrics registry (``ingest.adaptive_window_s``
    gauge, ``ingest.adaptive_grow`` / ``ingest.adaptive_shrink``
    counters).
    """

    def __init__(
        self,
        deadline_ms: float = 5.0,
        min_ms: Optional[float] = None,
        max_ms: Optional[float] = None,
        low_fill: float = 0.25,
        grow: float = 1.5,
        shrink: float = 0.75,
        metrics=None,
    ):
        if grow <= 1.0 or not 0.0 < shrink < 1.0:
            raise ValueError("need grow > 1 and 0 < shrink < 1")
        self.window_s = deadline_ms / 1e3
        self.min_s = (min_ms if min_ms is not None else deadline_ms / 4) / 1e3
        self.max_s = (max_ms if max_ms is not None else deadline_ms * 8) / 1e3
        if not self.min_s <= self.window_s <= self.max_s:
            raise ValueError(
                f"deadline {self.window_s}s outside [{self.min_s}, {self.max_s}]")
        self.low_fill = low_fill
        self.grow = grow
        self.shrink = shrink
        self.grows = 0
        self.shrinks = 0
        if metrics is not None:
            self._m_window = metrics.gauge("ingest.adaptive_window_s")
            self._m_window.set(self.window_s)
            self._m_grow = metrics.counter("ingest.adaptive_grow")
            self._m_shrink = metrics.counter("ingest.adaptive_shrink")
        else:
            self._m_window = self._m_grow = self._m_shrink = None

    @property
    def deadline_s(self) -> float:
        return self.window_s

    def on_emit(self, n_events: int, width: int, pending: int,
                reason: str) -> None:
        """Observe one emission (``reason``: "fill" | "deadline" |
        "flush") and adjust the window.  Flush emits are shutdown/drain
        artifacts and never adapt."""
        if reason == "flush":
            return
        if reason == "fill" or pending >= width:
            new = min(self.window_s * self.grow, self.max_s)
            if new != self.window_s:
                self.window_s = new
                self.grows += 1
                if self._m_grow is not None:
                    self._m_grow.inc()
                    self._m_window.set(new)
        elif reason == "deadline" and pending == 0 \
                and n_events <= self.low_fill * width:
            new = max(self.window_s * self.shrink, self.min_s)
            if new != self.window_s:
                self.window_s = new
                self.shrinks += 1
                if self._m_shrink is not None:
                    self._m_shrink.inc()
                    self._m_window.set(new)


class Batcher:
    """Assembles routed, fixed-shape event batches (see module docstring).

    ``resolve_device(token) -> int`` / ``resolve_mtype(name) -> int`` /
    ``resolve_alert(name) -> int`` map edge strings to dense handles — in
    the full stack these are the management stores' lookup methods (the
    near-cache analog of ``CachedDeviceManagementApiChannel.java``).
    """

    def __init__(
        self,
        width: int,
        n_shards: int,
        registry_capacity: int,
        resolve_device: Callable[[str], int],
        resolve_mtype: Callable[[str], int],
        resolve_alert: Callable[[str], int],
        invocations=None,  # HandleSpace-like (mint/lookup) for
                           # invocation-token correlation
        deadline_ms: float = 5.0,
        clock: Callable[[], float] = time.monotonic,
        emit_packed: bool = False,
        metrics=None,
        controller: Optional[AdaptiveBatchController] = None,
    ):
        if width % n_shards != 0:
            raise ValueError(f"width={width} not divisible by n_shards={n_shards}")
        # Validate the routing invariant up front (same check as
        # shard_for_device, surfaced at construction).
        shard_for_device(0, registry_capacity, n_shards)
        self.width = width
        self.n_shards = n_shards
        self.seg = width // n_shards
        self.capacity = registry_capacity
        self.rows_per_shard = registry_capacity // n_shards
        self.resolve_device = resolve_device
        self.resolve_mtype = resolve_mtype
        self.resolve_alert = resolve_alert
        self.invocations = invocations
        self._deadline_s = deadline_ms / 1e3
        # Optional adaptive window: when set, the controller owns the
        # deadline (shrinks under idle, grows under backlog) and the
        # static value above is only the fallback after detach.
        self.controller = controller
        self.clock = clock
        self.emit_packed = emit_packed
        self._pending: List[Deque[_Chunk]] = [
            collections.deque() for _ in range(n_shards)
        ]
        self._counts = [0] * n_shards
        self._oldest: Optional[float] = None
        self._rr = 0  # round-robin shard for unknown devices
        self.emitted_batches = 0
        self.emitted_events = 0
        # Bytes memcpy'd during batch assembly (intake copies + emission
        # slice copies; adopted reserved buffers contribute zero) — the
        # measured half of the zero-copy ingest story.
        self.copied_bytes = 0
        # registry fold-in (per EMIT, never per row): batch fill/wait are
        # the assemble-stage watermark the lag attribution story needs
        self.metrics = metrics
        if metrics is not None:
            self._m_batches = metrics.counter("ingest.batches_emitted")
            self._m_rows = metrics.counter("ingest.rows_emitted")
            self._m_fill = metrics.gauge("ingest.batch_fill")
            self._m_wait = metrics.histogram("ingest.batch_wait_s")
            self._m_copied = metrics.counter("pipeline.bytes_copied.batch")
        else:
            self._m_copied = None

    @property
    def deadline_s(self) -> float:
        if self.controller is not None:
            return self.controller.deadline_s
        return self._deadline_s

    @deadline_s.setter
    def deadline_s(self, value: float) -> None:
        self._deadline_s = float(value)
        if self.controller is not None:
            # write-through: the attribute was a plain knob before the
            # controller existed, so an explicit set re-anchors the
            # adaptive window (still clamped to its [min_s, max_s])
            # instead of being silently shadowed by it
            c = self.controller
            c.window_s = min(max(float(value), c.min_s), c.max_s)
            if c._m_window is not None:
                c._m_window.set(c.window_s)

    # -- intake: scalar paths ------------------------------------------------

    def add(self, req: DecodedRequest, tenant_id: int, payload_ref: int) -> Optional[BatchPlan]:
        """Queue one decoded event; returns a plan if a segment filled."""
        et = req.event_type
        if et is None:
            raise ValueError(
                f"{req.kind.name} is a host-plane request, not a pipeline event"
            )
        return self._enqueue_row(
            device_id=self.resolve_device(req.device_token),
            tenant_id=tenant_id,
            event_type=int(et),
            ts_s=req.ts_s,
            ts_ns=req.ts_ns,
            mtype_id=self.resolve_mtype(req.mtype) if req.mtype else NULL_ID,
            value=req.value,
            lat=req.lat,
            lon=req.lon,
            elevation=req.elevation,
            alert_code=(self.resolve_alert(req.alert_type)
                        if req.alert_type else NULL_ID),
            alert_level=int(req.alert_level),
            # responses/invocations correlate through the invocation
            # token (reference: originatingEventId links a response to
            # its invocation event)
            command_id=self._invocation_id(req),
            payload_ref=payload_ref,
            update_state=bool(req.update_state),
        )

    def add_dense(
        self,
        *,
        device_id: int,
        tenant_id: int,
        event_type: int,
        ts_s: int,
        ts_ns: int = 0,
        mtype_id: int = NULL_ID,
        value: float = 0.0,
        lat: float = 0.0,
        lon: float = 0.0,
        elevation: float = 0.0,
        alert_code: int = NULL_ID,
        alert_level: int = 0,
        command_id: int = NULL_ID,
        payload_ref: int = NULL_ID,
        update_state: bool = False,
    ) -> Optional[BatchPlan]:
        """Queue one already-resolved row — the re-injection path for
        derived alerts and presence STATE_CHANGEs (reprocess-topic analog),
        which carry dense handles instead of edge strings.  Defaults to
        ``update_state=False``: system-generated events must not touch
        last-known state or presence."""
        return self._enqueue_row(
            device_id=int(device_id),
            tenant_id=int(tenant_id),
            event_type=int(event_type),
            ts_s=int(ts_s),
            ts_ns=int(ts_ns),
            mtype_id=int(mtype_id),
            value=float(value),
            lat=float(lat),
            lon=float(lon),
            elevation=float(elevation),
            alert_code=int(alert_code),
            alert_level=int(alert_level),
            command_id=int(command_id),
            payload_ref=int(payload_ref),
            update_state=bool(update_state),
        )

    def _enqueue_row(self, **values) -> Optional[BatchPlan]:
        """Shared routing/append/deadline/emit tail of the scalar paths."""
        device_id = values["device_id"]
        if 0 <= device_id < self.capacity:
            shard = device_id // self.rows_per_shard
        else:
            values["device_id"] = NULL_ID
            shard = self._rr = (self._rr + 1) % self.n_shards
        now = self.clock()
        q = self._pending[shard]
        tail = q[-1] if q else None
        if tail is None or tail.length >= tail.capacity:
            tail = _Chunk(
                cols={f: np.empty(self.seg, _DTYPE[f]) for f in _COL_FIELDS},
                length=0,
                arrival=now,
            )
            q.append(tail)
        i = tail.length
        for f in _COL_FIELDS:
            tail.cols[f][i] = values[f]
        tail.length = i + 1
        self._counts[shard] += 1
        if self._oldest is None:
            self._oldest = now
        if self._counts[shard] >= self.seg:
            return self._emit()
        return None

    # -- intake: vectorized paths -------------------------------------------

    def add_arrays(self, _copy: bool = True, **columns) -> List[BatchPlan]:
        """Columnar intake: queue N pre-resolved rows from 1-D arrays.

        ``device_id`` is required; any other batch column
        (:data:`_COL_FIELDS`) may be supplied as an array of the same
        length or omitted to take its fill value.  Returns every plan that
        became ready (possibly several when N spans multiple segments).
        This is the 1M events/sec/chip intake edge: one gather per field
        per shard, no Python per-row work.

        ``_copy=False`` is for internal callers that hand over freshly
        built arrays they will never touch again; external callers keep
        the default so refilling their buffers cannot corrupt queued rows.
        """
        device_id = np.asarray(columns["device_id"], np.int32)
        n = len(device_id)
        if n == 0:
            return []
        cols: Dict[str, np.ndarray] = {}
        filled: set = set()
        for f in _COL_FIELDS:
            v = columns.get(f)
            if f == "device_id":
                cols[f] = device_id
            elif v is None:
                # Zero-alloc fill: a 0-stride read-only broadcast of the
                # per-field template, never a fresh np.full per call —
                # emission copies by slice regardless, and nothing writes
                # into queued chunk columns.
                cols[f] = np.broadcast_to(_FILL_0D[f], n)
                filled.add(f)
            else:
                if not (type(v) is np.ndarray and v.dtype == _DTYPE[f]
                        and v.ndim == 1):
                    # already-typed 1-D inputs skip the asarray sweep
                    v = np.asarray(v, _DTYPE[f])
                cols[f] = v
                if len(v) != n:
                    raise ValueError(
                        f"column {f!r} length {len(v)} != {n}")
        unknown_keys = set(columns) - set(_COL_FIELDS)
        if unknown_keys:
            raise ValueError(f"unknown columns {sorted(unknown_keys)}")

        in_range = (device_id >= 0) & (device_id < self.capacity)
        if self.n_shards == 1:
            shard = None  # everything lands on shard 0
            if not in_range.all():
                cols["device_id"] = np.where(in_range, device_id, NULL_ID)
        else:
            shard = device_id // self.rows_per_shard
            bad = ~in_range
            if bad.any():
                k = int(bad.sum())
                shard[bad] = (self._rr + np.arange(k)) % self.n_shards
                self._rr = (self._rr + k) % self.n_shards
                cols["device_id"] = np.where(bad, NULL_ID, device_id)

        now = self.clock()
        if self.n_shards == 1:
            # Copy caller-backed columns: np.asarray above is zero-copy for
            # matching dtypes, and rows can sit queued past this call (up
            # to the deadline) — a caller refilling its buffers must not
            # corrupt queued events.  (The multi-shard path copies via its
            # boolean-mask gather already.)
            if _copy:
                # Fill broadcasts are immutable templates — copying them
                # would just re-materialize the np.full this path dropped.
                copied = {
                    f for f, c in cols.items()
                    if f not in filled
                    and (c is columns.get(f) or c.base is not None)
                }
                self._count_copied(sum(cols[f].nbytes for f in copied))
                cols = {
                    f: (np.array(c, copy=True) if f in copied else c)
                    for f, c in cols.items()
                }
            self._pending[0].append(_Chunk(cols=cols, length=n, arrival=now))
            self._counts[0] += n
        else:
            for s in range(self.n_shards):
                m = shard == s
                c = int(m.sum())
                if c == 0:
                    continue
                self._pending[s].append(_Chunk(
                    cols={f: cols[f][m] for f in _COL_FIELDS},
                    length=c,
                    arrival=now,
                ))
                self._count_copied(c * (_ROW_BYTES - 1))  # mask gathers
                self._counts[s] += c
        if self._oldest is None:
            self._oldest = now

        plans: List[BatchPlan] = []
        while max(self._counts) >= self.seg:
            plans.append(self._emit())
        return plans

    def reserve(self, cap: int) -> Optional["Reservation"]:
        """Hand out a :class:`Reservation` of up to ``cap`` rows for the
        fill-direct wire scanner, or None when ineligible (a payload
        wider than one batch cannot land in one emission).  Sharded
        batchers reserve too: the scanner writes RESOLVED device ids, so
        ``commit`` routes by shard after the scan — a segment-ordered
        full-width payload is adopted zero-copy exactly like the
        single-shard case, and anything else falls back to the gather
        lane.  The buffers are private until ``commit`` — reserve is
        safe from any thread."""
        if not 0 < cap <= self.width:
            return None
        return Reservation(self, cap)

    def _count_copied(self, nbytes: int) -> None:
        if nbytes:
            self.copied_bytes += nbytes
            if self._m_copied is not None:
                self._m_copied.inc(nbytes)

    def _invocation_id(self, req: DecodedRequest) -> int:
        """Invocation rows MINT their token (host- or replay-created);
        responses only LOOK UP, so a device sending garbage
        originatingEventId values cannot permanently allocate handles —
        the unknown token just stays uncorrelated (NULL_ID)."""
        inv = self.invocations
        if inv is None or not req.originating_event:
            return NULL_ID
        from sitewhere_tpu.ingest.decoders import RequestKind

        if req.kind == RequestKind.COMMAND_INVOCATION:
            return inv.mint(req.originating_event)
        return inv.lookup(req.originating_event)

    def add_requests(
        self,
        reqs: Sequence[DecodedRequest],
        tenant_ids: Sequence[int],
        payload_refs: Sequence[int],
    ) -> List[BatchPlan]:
        """Batch intake of decoded requests: one token-resolution pass
        builds the column arrays, then :meth:`add_arrays`."""
        n = len(reqs)
        if n == 0:
            return []
        out = {f: np.empty(n, _DTYPE[f]) for f in _COL_FIELDS}
        rd, rm, ra = self.resolve_device, self.resolve_mtype, self.resolve_alert
        for i, req in enumerate(reqs):
            et = req.event_type
            if et is None:
                raise ValueError(
                    f"{req.kind.name} is a host-plane request, not a pipeline event"
                )
            out["device_id"][i] = rd(req.device_token)
            out["event_type"][i] = int(et)
            out["ts_s"][i] = req.ts_s
            out["ts_ns"][i] = req.ts_ns
            out["mtype_id"][i] = rm(req.mtype) if req.mtype else NULL_ID
            out["value"][i] = req.value
            out["lat"][i] = req.lat
            out["lon"][i] = req.lon
            out["elevation"][i] = req.elevation
            out["alert_code"][i] = ra(req.alert_type) if req.alert_type else NULL_ID
            out["alert_level"][i] = int(req.alert_level)
            out["update_state"][i] = bool(req.update_state)
            # invocation-token correlation, same contract as add()
            out["command_id"][i] = self._invocation_id(req)
        out["tenant_id"][:] = np.asarray(tenant_ids, np.int32)
        out["payload_ref"][:] = np.asarray(payload_refs, np.int32)
        return self.add_arrays(_copy=False, **out)  # freshly built here

    # -- deadline/flush ------------------------------------------------------

    def poll(self) -> Optional[BatchPlan]:
        """Emit on deadline: call periodically from the dispatch loop."""
        if self._oldest is None:
            return None
        if self.clock() - self._oldest >= self.deadline_s:
            return self._emit(reason="deadline")
        return None

    def flush(self) -> Optional[BatchPlan]:
        """Emit whatever is pending (shutdown/drain)."""
        if self._oldest is None:
            return None
        return self._emit(reason="flush")

    @property
    def pending(self) -> int:
        return sum(self._counts)

    # -- emission -----------------------------------------------------------

    def _emit_tail(self, n: int, reason: str):
        """Shared emission bookkeeping: wait accounting, counters,
        adaptive-controller feedback.  Returns ``(now, wait)``."""
        now = self.clock()
        wait = now - self._oldest if self._oldest is not None else 0.0
        # Carried-over rows keep their chunk arrival time for the deadline
        # (plain min-scan: no per-emit list on the hot path).
        oldest = None
        for q in self._pending:
            if q and (oldest is None or q[0].arrival < oldest):
                oldest = q[0].arrival
        self._oldest = oldest
        self.emitted_batches += 1
        self.emitted_events += n
        if self.metrics is not None:
            self._m_batches.inc()
            self._m_rows.inc(n)
            self._m_fill.set(n / self.width)
            self._m_wait.observe(wait)
        if self.controller is not None:
            self.controller.on_emit(n, self.width, self.pending, reason)
        return now, wait

    def _adoptable_sharded(self) -> bool:
        """True when every shard's sole pending chunk is the matching
        segment of ONE full-width reservation — ``_commit_sharded`` left
        segment-aligned views, so the reserved buffers already ARE the
        batch and ``_emit_adopted`` can ship them without a copy."""
        res = None
        for s in range(self.n_shards):
            q = self._pending[s]
            if len(q) != 1:
                return False
            ch = q[0]
            if ch.reserved is None or ch.start != 0 \
                    or ch.length != self.seg \
                    or ch.res_off != s * self.seg:
                return False
            if res is None:
                res = ch.reserved
            elif ch.reserved is not res:
                return False
        return res is not None and res.cap == self.width

    @hot_path
    def _emit_adopted(self, reason: str) -> BatchPlan:
        """Zero-copy emission: the pending chunk(s) are a full-width
        reserved segment — its packed buffers BECOME the batch.  Only
        validity, the per-payload constants and any padding are written;
        no row data moves.  (Sharded: one view-chunk per shard, all of
        the same reservation, popped together.)"""
        res = None
        n = 0
        for s in range(self.n_shards):
            ch = self._pending[s].popleft()
            res = ch.reserved
            n += ch.length
            self._counts[s] -= ch.length
        host_cols = res.finalize_adopted(n)
        now, wait = self._emit_tail(n, reason)
        return BatchPlan(
            batch=None, n_events=n, width=self.width, created_at=now,
            max_wait_s=wait, host_cols=host_cols,
            packed_i=res.ibuf, packed_f=res.fbuf,
            seq=self.emitted_batches - 1, reason=reason,
        )

    def _assemble_buffers(self):
        """Fallback batch-assembly buffers — the copying lane's
        allocations, off the adopted path.  Full-width fill emissions
        (single-shard AND segment-ordered sharded reservations) adopt
        the reservation's packed buffers and never come here; this
        allocates only for the mixed/deadline/flush leftovers whose rows
        genuinely have to be gathered out of multiple chunks.

        Packed mode builds the host columns directly as rows of the
        packed wire buffers — ``_emit``'s fill loop writes through the
        ``out`` views, so emission costs no extra pass.  Bool columns
        keep their own arrays (host_cols consumers expect bool dtype)
        and land in their int rows at the end."""
        if not self.emit_packed:
            return None, None, {
                name: np.full(self.width, fill, dtype=dt)
                for name, dt, fill in _FIELDS
            }
        from sitewhere_tpu.pipeline.packed import BATCH_F, BATCH_I

        ibuf = np.empty((len(BATCH_I), self.width), np.int32)
        fbuf = np.empty((len(BATCH_F), self.width), np.float32)
        out = {}
        for i, f in enumerate(BATCH_I):
            if f in ("valid", "update_state"):
                out[f] = np.full(self.width, _FILL[f], np.bool_)
            else:
                ibuf[i].fill(_FILL[f])
                out[f] = ibuf[i]
        for i, f in enumerate(BATCH_F):
            fbuf[i].fill(_FILL[f])
            out[f] = fbuf[i]
        out["valid"][:] = False
        return ibuf, fbuf, out

    @hot_path
    def _emit(self, reason: str = "fill") -> BatchPlan:
        if self.emit_packed:
            q = self._pending[0]
            if self.n_shards == 1:
                if len(q) == 1 and q[0].reserved is not None \
                        and q[0].start == 0 \
                        and q[0].reserved.cap == self.width:
                    return self._emit_adopted(reason)
            elif q and q[0].reserved is not None \
                    and self._adoptable_sharded():
                return self._emit_adopted(reason)
        ibuf, fbuf, out = self._assemble_buffers()
        n = 0
        for s in range(self.n_shards):
            base = s * self.seg
            filled = 0
            q = self._pending[s]
            while filled < self.seg and q:
                ch = q[0]
                take = min(ch.length - ch.start, self.seg - filled)
                lo, hi = base + filled, base + filled + take
                for f in _COL_FIELDS:
                    out[f][lo:hi] = ch.cols[f][ch.start:ch.start + take]
                out["valid"][lo:hi] = True
                ch.start += take
                filled += take
                if ch.start >= ch.length:
                    # Fully drained (staging chunks included — dropping
                    # them keeps a later append from resurrecting
                    # already-emitted rows).
                    q.popleft()
            self._counts[s] -= filled
            n += filled
        self._count_copied(n * _ROW_BYTES)

        now, wait = self._emit_tail(n, reason)
        if self.emit_packed:
            from sitewhere_tpu.pipeline.packed import BATCH_I

            ibuf[BATCH_I.index("valid")] = out["valid"]
            ibuf[BATCH_I.index("update_state")] = out["update_state"]
            self._count_copied(2 * 4 * self.width)  # bool→int32 rows
            return BatchPlan(
                batch=None, n_events=n, width=self.width, created_at=now,
                max_wait_s=wait, host_cols=out, packed_i=ibuf, packed_f=fbuf,
                seq=self.emitted_batches - 1, reason=reason,
            )
        # No device work here: _emit runs under the dispatcher's intake
        # lock, so the EventBatch H2D materializes lazily at first
        # plan.batch access (the dispatcher stages plans off-lock).
        return BatchPlan(
            batch=None, n_events=n, width=self.width, created_at=now,
            max_wait_s=wait, host_cols=out,
            seq=self.emitted_batches - 1, reason=reason,
        )
