"""Deadline-driven batcher: decoded requests → routed fixed-shape batches.

This is the seam between the variable-rate host world and the static-shape
SPMD pipeline (SURVEY.md §7 hard part #1).  The reference's analog is the
Kafka producer partitioner + consumer poll batching
(``EventSourcesManager.java:166``, ``MicroserviceKafkaConsumer.java:123-128``):
events keyed by device token land in per-partition record batches.  Here:

- each event row is routed to the mesh shard that owns its device registry
  block (:func:`~sitewhere_tpu.parallel.mesh.shard_for_device`), preserving
  the shard-local-gather invariant of the sharded pipeline step;
- a batch is emitted when any shard segment fills (``width // n_shards``
  rows) or when the oldest pending event exceeds the deadline — bounding
  added latency the way the Mongo buffer bounds flush delay
  (``DeviceEventBuffer.java:40-46``, ≤250 ms there; default 5 ms here for
  the <10 ms p99 budget);
- rows that don't fit carry over to the next batch (no drops);
- unknown devices round-robin across shards and dead-letter on-device.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, List, Optional

import numpy as np

from sitewhere_tpu.ids import NULL_ID
from sitewhere_tpu.ingest.decoders import DecodedRequest, RequestKind
from sitewhere_tpu.parallel.mesh import shard_for_device
from sitewhere_tpu.schema import EventBatch

_FIELDS = (
    ("valid", np.bool_, False),
    ("device_id", np.int32, NULL_ID),
    ("tenant_id", np.int32, NULL_ID),
    ("event_type", np.int32, 0),
    ("ts_s", np.int32, 0),
    ("ts_ns", np.int32, 0),
    ("mtype_id", np.int32, NULL_ID),
    ("value", np.float32, 0.0),
    ("lat", np.float32, 0.0),
    ("lon", np.float32, 0.0),
    ("elevation", np.float32, 0.0),
    ("alert_code", np.int32, NULL_ID),
    ("alert_level", np.int32, 0),
    ("command_id", np.int32, NULL_ID),
    ("payload_ref", np.int32, NULL_ID),
    ("update_state", np.bool_, True),
)


@dataclasses.dataclass
class _Row:
    device_id: int
    tenant_id: int
    event_type: int
    ts_s: int
    ts_ns: int
    mtype_id: int
    value: float
    lat: float
    lon: float
    elevation: float
    alert_code: int
    alert_level: int
    command_id: int
    payload_ref: int
    update_state: bool = True
    arrival: float = 0.0  # host clock at intake (deadline tracking only)


_COL_FIELDS = tuple(f for f in _Row.__dataclass_fields__ if f != "arrival")


@dataclasses.dataclass
class BatchPlan:
    """A ready-to-dispatch batch plus its host-side bookkeeping."""

    batch: EventBatch
    n_events: int
    width: int
    created_at: float
    max_wait_s: float  # how long the oldest row waited before emit

    @property
    def fill(self) -> float:
        return self.n_events / self.width


class Batcher:
    """Assembles routed, fixed-shape event batches (see module docstring).

    ``resolve_device(token) -> int`` / ``resolve_mtype(name) -> int`` /
    ``resolve_alert(name) -> int`` map edge strings to dense handles — in
    the full stack these are the management stores' lookup methods (the
    near-cache analog of ``CachedDeviceManagementApiChannel.java``).
    """

    def __init__(
        self,
        width: int,
        n_shards: int,
        registry_capacity: int,
        resolve_device: Callable[[str], int],
        resolve_mtype: Callable[[str], int],
        resolve_alert: Callable[[str], int],
        deadline_ms: float = 5.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        if width % n_shards != 0:
            raise ValueError(f"width={width} not divisible by n_shards={n_shards}")
        self.width = width
        self.n_shards = n_shards
        self.seg = width // n_shards
        self.capacity = registry_capacity
        self.resolve_device = resolve_device
        self.resolve_mtype = resolve_mtype
        self.resolve_alert = resolve_alert
        self.deadline_s = deadline_ms / 1e3
        self.clock = clock
        self._pending: List[List[_Row]] = [[] for _ in range(n_shards)]
        self._oldest: Optional[float] = None
        self._rr = 0  # round-robin shard for unknown devices
        self.emitted_batches = 0
        self.emitted_events = 0

    # -- intake -------------------------------------------------------------

    def add(self, req: DecodedRequest, tenant_id: int, payload_ref: int) -> Optional[BatchPlan]:
        """Queue one decoded event; returns a plan if a segment filled."""
        et = req.event_type
        if et is None:
            raise ValueError(
                f"{req.kind.name} is a host-plane request, not a pipeline event"
            )
        device_id = self.resolve_device(req.device_token)
        mtype_id = self.resolve_mtype(req.mtype) if req.mtype else NULL_ID
        alert_code = self.resolve_alert(req.alert_type) if req.alert_type else NULL_ID
        return self._enqueue(
            _Row(
                device_id=device_id,
                tenant_id=tenant_id,
                event_type=int(et),
                ts_s=req.ts_s,
                ts_ns=req.ts_ns,
                mtype_id=mtype_id,
                value=req.value,
                lat=req.lat,
                lon=req.lon,
                elevation=req.elevation,
                alert_code=alert_code,
                alert_level=int(req.alert_level),
                command_id=NULL_ID,
                payload_ref=payload_ref,
                update_state=bool(req.update_state),
            )
        )

    def add_dense(
        self,
        *,
        device_id: int,
        tenant_id: int,
        event_type: int,
        ts_s: int,
        ts_ns: int = 0,
        mtype_id: int = NULL_ID,
        value: float = 0.0,
        lat: float = 0.0,
        lon: float = 0.0,
        elevation: float = 0.0,
        alert_code: int = NULL_ID,
        alert_level: int = 0,
        command_id: int = NULL_ID,
        payload_ref: int = NULL_ID,
        update_state: bool = False,
    ) -> Optional[BatchPlan]:
        """Queue one already-resolved row — the re-injection path for
        derived alerts and presence STATE_CHANGEs (reprocess-topic analog),
        which carry dense handles instead of edge strings.  Defaults to
        ``update_state=False``: system-generated events must not touch
        last-known state or presence."""
        return self._enqueue(
            _Row(
                device_id=int(device_id),
                tenant_id=int(tenant_id),
                event_type=int(event_type),
                ts_s=int(ts_s),
                ts_ns=int(ts_ns),
                mtype_id=int(mtype_id),
                value=float(value),
                lat=float(lat),
                lon=float(lon),
                elevation=float(elevation),
                alert_code=int(alert_code),
                alert_level=int(alert_level),
                command_id=int(command_id),
                payload_ref=int(payload_ref),
                update_state=bool(update_state),
            )
        )

    def _enqueue(self, row: _Row) -> Optional[BatchPlan]:
        """Shared routing/append/deadline/emit tail of the add paths."""
        if 0 <= row.device_id < self.capacity:
            shard = shard_for_device(row.device_id, self.capacity, self.n_shards)
        else:
            row.device_id = NULL_ID
            shard = self._rr = (self._rr + 1) % self.n_shards
        row.arrival = self.clock()
        self._pending[shard].append(row)
        if self._oldest is None:
            self._oldest = row.arrival
        if len(self._pending[shard]) >= self.seg:
            return self._emit()
        return None

    def poll(self) -> Optional[BatchPlan]:
        """Emit on deadline: call periodically from the dispatch loop."""
        if self._oldest is None:
            return None
        if self.clock() - self._oldest >= self.deadline_s:
            return self._emit()
        return None

    def flush(self) -> Optional[BatchPlan]:
        """Emit whatever is pending (shutdown/drain)."""
        if self._oldest is None:
            return None
        return self._emit()

    @property
    def pending(self) -> int:
        return sum(len(p) for p in self._pending)

    # -- emission -----------------------------------------------------------

    def _emit(self) -> BatchPlan:
        import jax.numpy as jnp

        cols = {
            name: np.full(self.width, fill, dtype=dt) for name, dt, fill in _FIELDS
        }
        n = 0
        for shard in range(self.n_shards):
            base = shard * self.seg
            take = self._pending[shard][: self.seg]
            self._pending[shard] = self._pending[shard][self.seg :]
            for i, row in enumerate(take):
                pos = base + i
                cols["valid"][pos] = True
                for f in _COL_FIELDS:
                    cols[f][pos] = getattr(row, f)
            n += len(take)

        now = self.clock()
        wait = now - self._oldest if self._oldest is not None else 0.0
        # Carried-over rows keep their true arrival time for the deadline.
        remaining = [r.arrival for p in self._pending for r in p[:1]]
        self._oldest = min(remaining) if remaining else None
        self.emitted_batches += 1
        self.emitted_events += n
        batch = EventBatch(**{k: jnp.asarray(v) for k, v in cols.items()})
        return BatchPlan(
            batch=batch, n_events=n, width=self.width, created_at=now,
            max_wait_s=wait,
        )
