"""AMQP 1.0 receiver: Azure Event Hubs ingest without SDK dependencies.

Reference: ``service-event-sources/src/main/java/com/sitewhere/sources/
azure/EventHubInboundEventReceiver.java`` consumes an Event Hub through
the Azure ``EventProcessorHost`` SDK (per-partition receivers, consumer
groups, offset checkpoints).  Event Hubs speak AMQP 1.0 on the wire
(ISO/IEC 19464 / OASIS amqp-core-v1.0), a DIFFERENT protocol from the
0-9-1 RabbitMQ client in :mod:`sitewhere_tpu.ingest.amqp` — this module
is a from-scratch consume-side AMQP 1.0 client covering the subset an
Event Hub partition receiver needs:

- the type system: fixed/variable-width primitives, composite lists,
  maps, symbols, described types (encoder + decoder, round-trip tested);
- SASL PLAIN / ANONYMOUS negotiation (frame type 1), then the AMQP
  protocol header and ``open``/``begin``/``attach`` bring-up;
- a receiver link per partition (``{hub}/ConsumerGroups/{group}/
  Partitions/{n}``) with explicit ``flow`` link-credit (topped up at
  half-window, the prefetch analog), multi-frame transfer reassembly
  (``more`` flag), and ``disposition(accepted)`` settlement AFTER the
  sink accepts — crash-before-ack redelivers, the at-least-once contract
  the reference gets from EventProcessorHost checkpointing;
- offset checkpoints: each message's ``x-opt-offset`` annotation is
  persisted per partition (JSON sidecar, atomic rename) and resume
  attaches with the Event-Hub selector filter
  (``amqp.annotation.x-opt-offset > '<last>'``) so a reconnect does not
  replay the whole partition;
- idle-timeout keepalive (empty frames) honoring the peer's ``open``
  value, capped-exponential reconnect per partition.

Consume-side only, like the 0-9-1 client: command egress goes through
the MQTT/CoAP/HTTP destinations and outbound connectors.
"""

from __future__ import annotations

import json
import os
import socket
import struct
import threading
import time
import uuid as _uuid
from typing import Dict, List, Optional, Tuple

from sitewhere_tpu.ingest.sources import Receiver, logger
from sitewhere_tpu.runtime.overload import OverloadShed

AMQP_HEADER = b"AMQP\x00\x01\x00\x00"
SASL_HEADER = b"AMQP\x03\x01\x00\x00"

FRAME_AMQP = 0
FRAME_SASL = 1

# performative / section / outcome descriptor codes (amqp-core v1.0)
OPEN, BEGIN, ATTACH, FLOW, TRANSFER = 0x10, 0x11, 0x12, 0x13, 0x14
DISPOSITION, DETACH, END, CLOSE = 0x15, 0x16, 0x17, 0x18
SASL_MECHANISMS, SASL_INIT, SASL_OUTCOME = 0x40, 0x41, 0x44
SOURCE, TARGET = 0x28, 0x29
ACCEPTED = 0x24
SEC_HEADER, SEC_DELIVERY_ANN, SEC_MESSAGE_ANN = 0x70, 0x71, 0x72
SEC_PROPERTIES, SEC_APP_PROPERTIES = 0x73, 0x74
SEC_DATA, SEC_SEQUENCE, SEC_VALUE, SEC_FOOTER = 0x75, 0x76, 0x77, 0x78

# Event Hubs annotation / filter names
OFFSET_ANNOTATION = "x-opt-offset"
SELECTOR_FILTER = "apache.org:selector-filter:string"


class Amqp10Error(Exception):
    """Protocol violation or peer-initiated close."""


# --------------------------------------------------------------------------
# Type system


class Symbol(str):
    """AMQP symbol (encoded 0xA3/0xB3) — distinct from string on the wire."""


class Described:
    """A described value: ``descriptor`` applied to ``value``."""

    __slots__ = ("descriptor", "value")

    def __init__(self, descriptor, value):
        self.descriptor = descriptor
        self.value = value

    def __repr__(self):  # pragma: no cover - debug aid
        return f"Described({self.descriptor!r}, {self.value!r})"

    def __eq__(self, other):
        return (isinstance(other, Described)
                and other.descriptor == self.descriptor
                and other.value == self.value)


class _Uint(int):
    """Force uint encoding (performative fields like handle/credit)."""


class _Ulong(int):
    """Force ulong encoding (descriptor codes)."""


def encode_value(v) -> bytes:
    """Encode one AMQP value (the subset the client emits)."""
    if v is None:
        return b"\x40"
    if isinstance(v, Described):
        return b"\x00" + encode_value(v.descriptor) + encode_value(v.value)
    if isinstance(v, bool):
        return b"\x41" if v else b"\x42"
    if isinstance(v, _Ulong):
        if v == 0:
            return b"\x44"
        if v < 256:
            return b"\x53" + bytes([v])
        return b"\x80" + struct.pack(">Q", v)
    if isinstance(v, _Uint):
        if v == 0:
            return b"\x43"
        if v < 256:
            return b"\x52" + bytes([v])
        return b"\x70" + struct.pack(">I", v)
    if isinstance(v, int):
        # plain ints encode as long (covers the signed range we use)
        if -128 <= v < 128:
            return b"\x55" + struct.pack(">b", v)
        return b"\x81" + struct.pack(">q", v)
    if isinstance(v, Symbol):
        raw = v.encode("ascii")
        if len(raw) < 256:
            return b"\xa3" + bytes([len(raw)]) + raw
        return b"\xb3" + struct.pack(">I", len(raw)) + raw
    if isinstance(v, str):
        raw = v.encode("utf-8")
        if len(raw) < 256:
            return b"\xa1" + bytes([len(raw)]) + raw
        return b"\xb1" + struct.pack(">I", len(raw)) + raw
    if isinstance(v, (bytes, bytearray)):
        raw = bytes(v)
        if len(raw) < 256:
            return b"\xa0" + bytes([len(raw)]) + raw
        return b"\xb0" + struct.pack(">I", len(raw)) + raw
    if isinstance(v, float):
        return b"\x82" + struct.pack(">d", v)
    if isinstance(v, (list, tuple)):
        if not v:
            return b"\x45"
        body = b"".join(encode_value(x) for x in v)
        count = len(v)
        if len(body) + 1 < 256 and count < 256:
            return b"\xc0" + bytes([len(body) + 1, count]) + body
        return (b"\xd0" + struct.pack(">II", len(body) + 4, count) + body)
    if isinstance(v, dict):
        body = b"".join(
            encode_value(k) + encode_value(val) for k, val in v.items())
        count = 2 * len(v)
        if len(body) + 1 < 256 and count < 256:
            return b"\xc1" + bytes([len(body) + 1, count]) + body
        return b"\xd1" + struct.pack(">II", len(body) + 4, count) + body
    raise Amqp10Error(f"cannot encode {type(v).__name__}")


def decode_value(buf: bytes, off: int) -> Tuple[object, int]:
    """Decode one AMQP value; returns (value, next_offset)."""
    code = buf[off]
    off += 1
    if code == 0x00:  # described
        descriptor, off = decode_value(buf, off)
        value, off = decode_value(buf, off)
        return Described(descriptor, value), off
    if code == 0x40:
        return None, off
    if code == 0x41:
        return True, off
    if code == 0x42:
        return False, off
    if code == 0x56:
        return buf[off] != 0, off + 1
    if code == 0x43:
        return 0, off
    if code == 0x44:
        return 0, off
    if code in (0x50, 0x52, 0x53):  # ubyte / smalluint / smallulong
        return buf[off], off + 1
    if code in (0x51, 0x54, 0x55):  # byte / smallint / smalllong
        return struct.unpack_from(">b", buf, off)[0], off + 1
    if code == 0x60:
        return struct.unpack_from(">H", buf, off)[0], off + 2
    if code == 0x61:
        return struct.unpack_from(">h", buf, off)[0], off + 2
    if code == 0x70:
        return struct.unpack_from(">I", buf, off)[0], off + 4
    if code == 0x71:
        return struct.unpack_from(">i", buf, off)[0], off + 4
    if code == 0x72:
        return struct.unpack_from(">f", buf, off)[0], off + 4
    if code in (0x80, 0x83):  # ulong / timestamp(ms)
        return struct.unpack_from(">Q", buf, off)[0], off + 8
    if code == 0x81:
        return struct.unpack_from(">q", buf, off)[0], off + 8
    if code == 0x82:
        return struct.unpack_from(">d", buf, off)[0], off + 8
    if code == 0x98:
        return _uuid.UUID(bytes=buf[off:off + 16]), off + 16
    if code in (0xA0, 0xA1, 0xA3):
        n = buf[off]
        raw = buf[off + 1:off + 1 + n]
        off += 1 + n
    elif code in (0xB0, 0xB1, 0xB3):
        n = struct.unpack_from(">I", buf, off)[0]
        raw = buf[off + 4:off + 4 + n]
        off += 4 + n
    else:
        raw = None
    if raw is not None:
        if code in (0xA0, 0xB0):
            return bytes(raw), off
        if code in (0xA3, 0xB3):
            return Symbol(raw.decode("ascii")), off
        return raw.decode("utf-8"), off
    if code in (0x45, 0xC0, 0xD0):  # lists
        if code == 0x45:
            return [], off
        if code == 0xC0:
            size, count = buf[off], buf[off + 1]
            off += 2
        else:
            size, count = struct.unpack_from(">II", buf, off)
            off += 8
        out: List[object] = []
        for _ in range(count):
            item, off = decode_value(buf, off)
            out.append(item)
        return out, off
    if code in (0xC1, 0xD1):  # maps
        if code == 0xC1:
            _, count = buf[off], buf[off + 1]
            off += 2
        else:
            _, count = struct.unpack_from(">II", buf, off)
            off += 8
        d: Dict[object, object] = {}
        for _ in range(count // 2):
            k, off = decode_value(buf, off)
            val, off = decode_value(buf, off)
            d[k] = val
        return d, off
    raise Amqp10Error(f"unsupported type code 0x{code:02x}")


def performative(code: int, fields: List[object]) -> bytes:
    """Encode a performative: described list with a ulong descriptor."""
    return b"\x00" + encode_value(_Ulong(code)) + encode_value(list(fields))


def amqp_frame(channel: int, body: bytes, ftype: int = FRAME_AMQP) -> bytes:
    return struct.pack(">IBBH", 8 + len(body), 2, ftype, channel) + body


EMPTY_FRAME = struct.pack(">IBBH", 8, 2, FRAME_AMQP, 0)  # keepalive


class FrameReader:
    """Incremental AMQP 1.0 framing: 4-byte size + DOFF + type + channel."""

    def __init__(self, max_frame: int = 16 << 20):
        self._buf = bytearray()
        self.max_frame = max_frame

    def feed(self, data: bytes) -> List[Tuple[int, int, bytes]]:
        self._buf.extend(data)
        frames: List[Tuple[int, int, bytes]] = []
        while len(self._buf) >= 8:
            size, doff, ftype, channel = struct.unpack_from(">IBBH", self._buf)
            if size < 8 or size > self.max_frame:
                raise Amqp10Error(f"bad frame size {size}")
            if len(self._buf) < size:
                break
            body = bytes(self._buf[4 * doff:size])
            del self._buf[:size]
            frames.append((ftype, channel, body))
        return frames


def parse_frame_body(body: bytes) -> Tuple[Optional[Described], bytes]:
    """Split a frame body into (performative, trailing payload bytes).

    Empty (keepalive) frames return (None, b"")."""
    if not body:
        return None, b""
    perf, off = decode_value(body, 0)
    if not isinstance(perf, Described):
        raise Amqp10Error("frame body is not a performative")
    return perf, body[off:]


def parse_message(payload: bytes) -> Tuple[bytes, Dict[object, object]]:
    """Parse a bare message's sections → (body bytes, message annotations).

    ``data`` sections concatenate; an ``amqp-value`` string body encodes
    as UTF-8.  Unknown sections are skipped by construction (every
    section is one described value)."""
    off = 0
    body = b""
    annotations: Dict[object, object] = {}
    while off < len(payload):
        section, off = decode_value(payload, off)
        if not isinstance(section, Described):
            raise Amqp10Error("message section is not described")
        code = section.descriptor
        if code == SEC_MESSAGE_ANN and isinstance(section.value, dict):
            annotations = section.value
        elif code == SEC_DATA:
            body += section.value if isinstance(section.value, bytes) else b""
        elif code == SEC_VALUE:
            v = section.value
            if isinstance(v, bytes):
                body += v
            elif isinstance(v, str):
                body += v.encode("utf-8")
    return body, annotations


# --------------------------------------------------------------------------
# Receiver


def _field(fields: List[object], i: int, default=None):
    return fields[i] if i < len(fields) else default


class EventHubReceiver(Receiver):
    """Consume Event-Hub-style AMQP 1.0 partitions.

    One link per partition at ``{hub}/ConsumerGroups/{group}/
    Partitions/{n}``; per-partition offset checkpoints in
    ``checkpoint_dir`` (when set) make reconnects resume instead of
    replaying (the EventProcessorHost lease/checkpoint analog,
    EventHubInboundEventReceiver.java)."""

    def __init__(self, host: str, port: int = 5672,
                 event_hub: str = "sitewhere",
                 consumer_group: str = "$default",
                 partitions: int = 1,
                 username: str = "", password: str = "",
                 sasl: str = "anonymous",
                 credit: int = 64,
                 idle_timeout_s: float = 30.0,
                 checkpoint_dir: Optional[str] = None,
                 reconnect_delay_s: float = 0.5,
                 max_reconnect_delay_s: float = 30.0):
        super().__init__(name=f"eventhub-receiver:{host}:{port}/{event_hub}")
        # disposition(accepted) settles only AFTER the sink accepts:
        # ack-gated, so the ingest decode pool keeps this source sync
        self.acks_on_emit = True
        self.host, self.port = host, port
        self.event_hub = event_hub
        self.consumer_group = consumer_group
        self.partitions = int(partitions)
        self.username, self.password = username, password
        self.sasl = sasl.lower()
        if self.sasl not in ("plain", "anonymous", "none"):
            raise ValueError(f"sasl must be plain/anonymous/none: {sasl!r}")
        self.credit = int(credit)
        self.idle_timeout_s = float(idle_timeout_s)
        self.checkpoint_dir = checkpoint_dir
        self.reconnect_delay_s = reconnect_delay_s
        self.max_reconnect_delay_s = max_reconnect_delay_s
        self._alive = False
        self._stop_evt = threading.Event()
        self._socks: Dict[int, socket.socket] = {}
        self.connects = 0
        self.accepted = 0
        self.emit_errors = 0
        self._offsets: Dict[int, str] = {}
        # one lock for all partition threads: the checkpoint file is
        # shared, and json.dump over a dict another thread mutates raises
        self._ckpt_lock = threading.Lock()
        self._ckpt_dirty = False
        if checkpoint_dir:
            os.makedirs(checkpoint_dir, exist_ok=True)
            self._load_offsets()

    # -- checkpoints ---------------------------------------------------------

    def _ckpt_path(self) -> str:
        return os.path.join(self.checkpoint_dir,
                            f"eventhub-{self.event_hub}.json")

    def _load_offsets(self) -> None:
        try:
            with open(self._ckpt_path()) as f:
                raw = json.load(f)
            self._offsets = {int(k): str(v) for k, v in raw.items()}
        except (OSError, ValueError):
            self._offsets = {}

    def _save_offsets(self) -> None:
        if not self.checkpoint_dir:
            return
        path = self._ckpt_path()
        with self._ckpt_lock:
            snapshot = {str(k): v for k, v in self._offsets.items()}
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(snapshot, f)
            os.replace(tmp, path)
            self._ckpt_dirty = False

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self._alive = True
        self._stop_evt.clear()
        # One supervisor per partition (ROADMAP: remaining-receiver
        # chaos coverage): the reconnect loop handles transport errors
        # itself; the supervisor catches anything unexpected — a codec
        # bug, an injected fault escaping the per-delivery guard — and
        # restarts THAT partition's loop with backoff, escalating
        # terminally after max_restarts.  Partitions fail independently.
        for p in range(self.partitions):
            self._spawn_supervised(
                lambda p=p: self._partition_loop(p),
                name=f"{self.name}[{p}]")
        super().start()

    def stop(self) -> None:
        self._alive = False
        self._stop_evt.set()
        for sock in list(self._socks.values()):
            try:
                sock.close()
            except OSError:
                pass
        self._stop_supervisor()
        if self._ckpt_dirty:
            try:
                self._save_offsets()
            except OSError:
                logger.exception("%s: final checkpoint save failed", self.name)
        super().stop()

    # -- session -------------------------------------------------------------

    def _recv_performative(self, sock, reader, pending,
                           want: int) -> Tuple[Described, bytes, int]:
        """Read frames until the wanted performative arrives; keepalives
        are tolerated, ``close`` raises.  Coalesced frames after the
        wanted one stay on ``pending`` (the 0-9-1 lesson: returning
        mid-batch must not drop them)."""
        while True:
            while pending:
                ftype, channel, body = pending.pop(0)
                perf, payload = parse_frame_body(body)
                if perf is None:
                    continue
                code = perf.descriptor
                if code == CLOSE:
                    err = _field(perf.value, 0)
                    raise Amqp10Error(f"peer closed: {err!r}")
                if code != want:
                    raise Amqp10Error(
                        f"expected 0x{want:02x}, got 0x{code:02x}")
                return perf, payload, channel
            data = sock.recv(65536)
            if not data:
                raise Amqp10Error("peer closed during bring-up")
            pending.extend(reader.feed(data))

    @staticmethod
    def _read_exact(sock, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = sock.recv(n - len(buf))
            if not chunk:
                raise Amqp10Error("peer closed mid-read")
            buf += chunk
        return buf

    def _read_sasl_frame(self, sock, want: int) -> Described:
        """Read EXACTLY one SASL frame (keepalives tolerated).

        Exact-size reads, no buffering reader: a server may pipeline its
        AMQP protocol header (and open) right behind sasl-outcome in one
        TCP segment — bytes past the frame boundary must stay in the
        kernel buffer for the AMQP layer, not be misparsed as SASL
        frames (the coalesced-frame lesson from the 0-9-1 client)."""
        while True:
            size, doff, ftype, channel = struct.unpack(
                ">IBBH", self._read_exact(sock, 8))
            if size < 8 or size > 16 << 20:
                raise Amqp10Error(f"bad frame size {size}")
            body = self._read_exact(sock, size - 8)[max(0, 4 * doff - 8):]
            perf, _ = parse_frame_body(body)
            if perf is None:
                continue  # keepalive
            if perf.descriptor != want:
                raise Amqp10Error(
                    f"expected 0x{want:02x}, got 0x{perf.descriptor:02x}")
            return perf

    def _sasl_handshake(self, sock) -> None:
        sock.sendall(SASL_HEADER)
        header = self._read_exact(sock, 8)
        if header != SASL_HEADER:
            raise Amqp10Error(f"unexpected SASL header {header!r}")
        self._read_sasl_frame(sock, SASL_MECHANISMS)
        if self.sasl == "plain":
            init = b"\x00" + self.username.encode() + b"\x00" \
                + self.password.encode()
            mech = Symbol("PLAIN")
        else:
            init = b""
            mech = Symbol("ANONYMOUS")
        sock.sendall(amqp_frame(
            0, performative(SASL_INIT, [mech, init]), FRAME_SASL))
        outcome = self._read_sasl_frame(sock, SASL_OUTCOME)
        code = _field(outcome.value, 0, 1)
        if code != 0:
            raise Amqp10Error(f"SASL failed: code {code}")

    def _attach_source(self, partition: int) -> Described:
        address = (f"{self.event_hub}/ConsumerGroups/{self.consumer_group}"
                   f"/Partitions/{partition}")
        # source list: address, durable, expiry-policy, timeout, dynamic,
        # dynamic-node-properties, distribution-mode, filter, ...
        fields: List[object] = [address, None, None, None, None, None, None]
        offset = self._offsets.get(partition)
        if offset is not None:
            # Event-Hub resume filter: replay only past the checkpoint
            fields.append({
                Symbol(SELECTOR_FILTER): Described(
                    Symbol(SELECTOR_FILTER),
                    f"amqp.annotation.{OFFSET_ANNOTATION} > '{offset}'"),
            })
        return Described(_Ulong(SOURCE), fields)

    def _bring_up(self, partition: int):
        sock = socket.create_connection((self.host, self.port), timeout=10)
        try:
            reader = FrameReader()
            if self.sasl != "none":
                self._sasl_handshake(sock)
            sock.sendall(AMQP_HEADER)
            header = self._read_exact(sock, 8)
            if header != AMQP_HEADER:
                raise Amqp10Error(f"unexpected AMQP header {header!r}")
            pending: List[Tuple[int, int, bytes]] = []
            container = f"sitewhere-tpu-{os.getpid()}-{partition}"
            # open: container-id, hostname, max-frame-size, channel-max,
            # idle-time-out(ms)
            sock.sendall(amqp_frame(0, performative(OPEN, [
                container, self.host, _Uint(1 << 20), _Uint(0),
                _Uint(int(self.idle_timeout_s * 1000))])))
            open_perf, _, _ = self._recv_performative(
                sock, reader, pending, OPEN)
            peer_idle_ms = _field(open_perf.value, 4)
            # begin: remote-channel, next-outgoing-id, incoming-window,
            # outgoing-window
            sock.sendall(amqp_frame(0, performative(BEGIN, [
                None, _Uint(0), _Uint(2048), _Uint(2048)])))
            self._recv_performative(sock, reader, pending, BEGIN)
            # attach: name, handle, role(true=receiver), snd-settle-mode,
            # rcv-settle-mode(0=first), source, target, unsettled,
            # incomplete-unsettled, initial-delivery-count
            link_name = f"{container}-link"
            # rcv-settle-mode None = first (settle on our disposition)
            sock.sendall(amqp_frame(0, performative(ATTACH, [
                link_name, _Uint(0), True, None, None,
                self._attach_source(partition),
                Described(_Ulong(TARGET), [container])])))
            attach, _, _ = self._recv_performative(
                sock, reader, pending, ATTACH)
            # broker's initial-delivery-count seeds our flow bookkeeping
            idc = _field(attach.value, 9, 0) or 0
            return sock, reader, pending, int(idc), peer_idle_ms
        except BaseException:
            try:
                sock.close()
            except OSError:
                pass
            raise

    def _send_flow(self, sock, delivery_count: int, credit: int,
                   next_incoming: int) -> None:
        # flow: next-incoming-id, incoming-window, next-outgoing-id,
        # outgoing-window, handle, delivery-count, link-credit
        sock.sendall(amqp_frame(0, performative(FLOW, [
            _Uint(next_incoming), _Uint(2048), _Uint(0), _Uint(2048),
            _Uint(0), _Uint(delivery_count), _Uint(credit)])))

    def _settle(self, sock, delivery_id: int) -> None:
        # disposition: role(true=receiver), first, last, settled, state
        sock.sendall(amqp_frame(0, performative(DISPOSITION, [
            True, _Uint(delivery_id), None, True,
            Described(_Ulong(ACCEPTED), [])])))

    # -- the consume loop ----------------------------------------------------

    def _partition_loop(self, partition: int) -> None:
        delay = self.reconnect_delay_s
        while self._alive:
            try:
                sock, reader, pending, idc, peer_idle_ms = (
                    self._bring_up(partition))
            except Exception as e:
                if not self._alive:
                    return
                logger.warning("%s[%d]: connect failed: %s",
                               self.name, partition, e)
                if self._stop_evt.wait(delay):
                    return
                delay = min(delay * 2, self.max_reconnect_delay_s)
                continue
            self._socks[partition] = sock
            self.connects += 1
            delay = self.reconnect_delay_s
            try:
                self._consume(sock, reader, pending, partition, idc,
                              peer_idle_ms)
            except Exception as e:
                # broader than (OSError, Amqp10Error): a malformed frame
                # surfaces as struct.error/IndexError/UnicodeDecodeError
                # from the decode layer, and a dead partition thread is
                # strictly worse than a reconnect
                if self._alive:
                    logger.warning("%s[%d]: session dropped: %s",
                                   self.name, partition, e)
            finally:
                self._socks.pop(partition, None)
                try:
                    sock.close()
                except OSError:
                    pass
                if self._ckpt_dirty:
                    try:
                        self._save_offsets()
                    except OSError:
                        logger.exception("%s[%d]: checkpoint save failed",
                                         self.name, partition)
            if self._alive and self._stop_evt.wait(delay):
                return

    def _consume(self, sock, reader, pending, partition: int,
                 delivery_count: int, peer_idle_ms) -> None:
        credit = self.credit
        self._send_flow(sock, delivery_count, credit, 0)
        keepalive = (peer_idle_ms / 1000.0 / 2.0
                     if peer_idle_ms else self.idle_timeout_s)
        sock.settimeout(max(0.2, keepalive))
        last_send = time.monotonic()
        assembling: Dict[int, bytes] = {}  # delivery-id → partial payload
        next_incoming = 0
        while self._alive:
            frames = list(pending)
            pending.clear()
            if not frames:
                try:
                    data = sock.recv(65536)
                except socket.timeout:
                    if time.monotonic() - last_send >= keepalive:
                        sock.sendall(EMPTY_FRAME)
                        last_send = time.monotonic()
                    continue
                if not data:
                    raise Amqp10Error("peer closed")
                frames = reader.feed(data)
            for ftype, channel, body in frames:
                perf, payload = parse_frame_body(body)
                if perf is None:
                    continue  # keepalive
                code = perf.descriptor
                if code == CLOSE:
                    raise Amqp10Error(f"peer closed: {_field(perf.value, 0)!r}")
                if code in (DETACH, END):
                    raise Amqp10Error(f"peer detached (0x{code:02x})")
                if code == FLOW:
                    continue
                if code != TRANSFER:
                    continue
                # every transfer FRAME consumes one session transfer-id,
                # continuations included — next-incoming-id must track
                # frames, not deliveries, or the advertised window
                # drifts one id per split transfer
                next_incoming += 1
                fields = perf.value
                delivery_id = _field(fields, 1)
                settled = bool(_field(fields, 4, False))
                more = bool(_field(fields, 5, False))
                if delivery_id is None:
                    # continuation transfers may omit delivery-id
                    delivery_id = next(iter(assembling), None)
                if delivery_id is None:
                    raise Amqp10Error("transfer without delivery-id")
                assembling[delivery_id] = (
                    assembling.get(delivery_id, b"") + payload)
                if more:
                    continue
                message = assembling.pop(delivery_id)
                delivery_count += 1
                credit -= 1
                self._handle_message(sock, partition, delivery_id,
                                     settled, message)
                if credit <= self.credit // 2:
                    credit = self.credit
                    self._send_flow(sock, delivery_count, credit,
                                    next_incoming)
                    last_send = time.monotonic()
            if self._ckpt_dirty:
                self._save_offsets()

    def _handle_message(self, sock, partition: int, delivery_id: int,
                        settled: bool, message: bytes) -> None:
        body, annotations = parse_message(message)
        try:
            self._emit(body)
        except OverloadShed:
            # admission shed: leave the delivery UNSETTLED, do NOT
            # checkpoint, and recycle the link — the broker redelivers
            # every unsettled message on detach (at-least-once), and
            # the partition loop's reconnect backoff IS the pause
            # overload wants from this source
            raise Amqp10Error("intake shed; recycling link for redelivery")
        except Exception:
            # The sink journals before returning; a failure here is a
            # local fault — leave the delivery unsettled so the broker
            # redelivers after reconnect (at-least-once).
            self.emit_errors += 1
            logger.exception("%s[%d]: sink rejected delivery %d",
                             self.name, partition, delivery_id)
            raise Amqp10Error("sink failure; recycling for redelivery")
        # Checkpoint BEFORE settling: the sink has accepted (journaled)
        # the message, so it counts as processed even if the settle dies
        # with the socket — the resume filter then suppresses the
        # redelivery a lost disposition would otherwise cause.  The dict
        # updates here; the file write batches per recv burst (_consume)
        # + session end, not per message.
        self.accepted += 1
        offset = annotations.get(Symbol(OFFSET_ANNOTATION))
        if offset is None:
            offset = annotations.get(OFFSET_ANNOTATION)
        if offset is not None:
            with self._ckpt_lock:
                self._offsets[partition] = str(offset)
                self._ckpt_dirty = True
        if not settled:
            self._settle(sock, delivery_id)
