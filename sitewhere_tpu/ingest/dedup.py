"""Event deduplication at the ingest edge.

Reference: ``IDeviceEventDeduplicator`` implementations —
``deduplicator/AlternateIdDeduplicator.java`` (drop events whose alternate
id already exists in the event store) and ``GroovyEventDeduplicator.java``
(scripted predicate).  Here:

- :class:`AlternateIdDeduplicator` keeps a bounded LRU of recently seen
  alternate-id hashes (the store-lookup becomes an O(1) in-memory check;
  the bound makes memory static, trading exactness beyond the window — the
  journal retains everything for offline exact dedup).
- The Groovy analog is any ``Callable[[DecodedRequest], bool]`` predicate
  (return True = duplicate) plugged into the source.
"""

from __future__ import annotations

from collections import OrderedDict

from sitewhere_tpu.ids import stable_hash64
from sitewhere_tpu.ingest.decoders import DecodedRequest


class AlternateIdDeduplicator:
    """Bounded-LRU alternate-id dedup; thread-compatible (single pump)."""

    def __init__(self, window: int = 1 << 20):
        self.window = window
        self._seen: OrderedDict[int, None] = OrderedDict()
        self.duplicates = 0

    def is_duplicate(self, req: DecodedRequest) -> bool:
        alt = req.alternate_id
        if not alt:
            return False
        key = stable_hash64(f"{req.device_token}\x00{alt}")
        if key in self._seen:
            self._seen.move_to_end(key)
            self.duplicates += 1
            return True
        self._seen[key] = None
        if len(self._seen) > self.window:
            self._seen.popitem(last=False)
        return False

    # -- checkpoint integration (runtime/checkpoint.py) ---------------------

    def export_keys(self) -> list:
        """LRU keys, oldest first — the checkpoint payload.  Hashes only
        (the raw alternate ids were never retained)."""
        return list(self._seen.keys())

    def import_keys(self, keys) -> None:
        """Re-seed the window from exported keys (restore): a restarted
        instance keeps catching duplicates the window had already seen
        instead of re-admitting them until the LRU refills."""
        self._seen.clear()
        for key in keys[-self.window:]:
            self._seen[int(key)] = None
