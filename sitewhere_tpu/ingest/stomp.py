"""STOMP 1.2 client: broker-subscription ingest (ActiveMQ/RabbitMQ analog).

Reference: ``service-event-sources`` terminates broker protocols with
client libraries — ``activemq/ActiveMQClientEventReceiver.java`` (JMS) and
``rabbitmq/RabbitMqInboundEventReceiver.java`` (AMQP).  Neither client
stack exists in this image, but both brokers natively speak STOMP (Simple
Text Oriented Messaging Protocol), so the capability — subscribe to a
broker queue/topic, feed every message body to the decoder, acknowledge
for at-least-once redelivery — is implemented here as a from-scratch
STOMP 1.2 client (https://stomp.github.io/stomp-specification-1.2.html):

- full frame codec (header escaping, ``content-length`` binary bodies,
  NUL termination, heart-beat LFs between frames);
- ``client-individual`` ack mode by default: a message is ACKed only
  after the sink accepts its payload, so a crash between delivery and
  journal append redelivers (the broker plays the Kafka-offset role the
  reference relies on, ``MicroserviceKafkaConsumer.java:94``);
- negotiated bidirectional heart-beats with a dead-connection cutoff;
- capped-exponential reconnect like the other socket receivers.
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Dict, List, Optional, Tuple

from sitewhere_tpu.ingest.sources import Receiver, logger
from sitewhere_tpu.runtime.overload import OverloadShed
from sitewhere_tpu.runtime.resilience import Backoff, RetryPolicy

_ESCAPES = {"\\": "\\\\", "\r": "\\r", "\n": "\\n", ":": "\\c"}
_UNESCAPES = {"\\\\": "\\", "\\r": "\r", "\\n": "\n", "\\c": ":"}


class StompError(Exception):
    """Protocol violation or broker ERROR frame."""


def _escape(value: str) -> str:
    return "".join(_ESCAPES.get(ch, ch) for ch in value)


def _unescape(value: str) -> str:
    out: List[str] = []
    i = 0
    while i < len(value):
        if value[i] == "\\":
            pair = value[i:i + 2]
            if pair not in _UNESCAPES:
                raise StompError(f"invalid header escape {pair!r}")
            out.append(_UNESCAPES[pair])
            i += 2
        else:
            out.append(value[i])
            i += 1
    return "".join(out)


def encode_frame(command: str, headers: Dict[str, str], body: bytes = b"",
                 escape: bool = True) -> bytes:
    """One STOMP frame.  ``CONNECT``/``CONNECTED`` never escape headers
    (spec: 1.0 compatibility); every other frame does."""
    esc = (lambda s: s) if not escape else _escape
    lines = [command]
    for k, v in headers.items():
        lines.append(f"{esc(str(k))}:{esc(str(v))}")
    if body and "content-length" not in headers:
        lines.append(f"content-length:{len(body)}")
    head = ("\n".join(lines) + "\n\n").encode("utf-8")
    return head + body + b"\x00"


class FrameReader:
    """Incremental STOMP frame parser (handles heart-beat LFs and
    ``content-length`` bodies containing NULs)."""

    def __init__(self, max_frame: int = 16 << 20):
        self._buf = bytearray()
        self.max_frame = max_frame

    def feed(self, data: bytes) -> List[Tuple[str, Dict[str, str], bytes]]:
        self._buf += data
        if len(self._buf) > self.max_frame:
            raise StompError(f"frame exceeds {self.max_frame} bytes")
        frames = []
        while True:
            frame = self._try_parse()
            if frame is None:
                return frames
            frames.append(frame)

    def _try_parse(self):
        buf = self._buf
        # skip heart-beat EOLs between frames
        start = 0
        while start < len(buf) and buf[start:start + 1] in (b"\n", b"\r"):
            start += 1
        if start:
            del buf[:start]
        if not buf:
            return None
        head_end = buf.find(b"\n\n")
        crlf = buf.find(b"\r\n\r\n")
        if crlf != -1 and (head_end == -1 or crlf < head_end):
            head_end, sep = crlf, 4
        elif head_end != -1:
            sep = 2
        else:
            return None
        head = buf[:head_end].decode("utf-8", "replace").replace("\r\n", "\n")
        lines = head.split("\n")
        command = lines[0]
        headers: Dict[str, str] = {}
        unescape = command not in ("CONNECTED",)
        for line in lines[1:]:
            if not line:
                continue
            if ":" not in line:
                raise StompError(f"malformed header line {line!r}")
            k, v = line.split(":", 1)
            if unescape:
                k, v = _unescape(k), _unescape(v)
            headers.setdefault(k, v)  # spec: first occurrence wins
        body_start = head_end + sep
        if "content-length" in headers:
            try:
                length = int(headers["content-length"])
            except ValueError as e:
                raise StompError("bad content-length") from e
            if len(buf) < body_start + length + 1:
                return None
            body = bytes(buf[body_start:body_start + length])
            if buf[body_start + length:body_start + length + 1] != b"\x00":
                raise StompError("frame body not NUL-terminated")
            del buf[:body_start + length + 1]
        else:
            nul = buf.find(b"\x00", body_start)
            if nul == -1:
                return None
            body = bytes(buf[body_start:nul])
            del buf[:nul + 1]
        return command, headers, body


class StompReceiver(Receiver):
    """Subscribe to a broker destination over STOMP; every MESSAGE body is
    an encoded event payload.

    ``ack="client-individual"`` (default) acknowledges each message only
    after the sink returns, giving broker-side redelivery on crash;
    ``ack="auto"`` trades that for throughput.
    """

    def __init__(self, host: str, port: int = 61613,
                 destination: str = "/queue/sitewhere.input",
                 login: Optional[str] = None, passcode: Optional[str] = None,
                 ack: str = "client-individual",
                 heartbeat_ms: int = 10_000,
                 reconnect_delay_s: float = 0.5,
                 max_reconnect_delay_s: float = 30.0):
        super().__init__(name=f"stomp-receiver:{host}:{port}{destination}")
        if ack not in ("auto", "client", "client-individual"):
            raise ValueError(f"bad ack mode {ack!r}")
        self.host, self.port = host, port
        self.destination = destination
        self.login, self.passcode = login, passcode
        self.ack = ack
        self.heartbeat_ms = heartbeat_ms
        self.reconnect_delay_s = reconnect_delay_s
        self.max_reconnect_delay_s = max_reconnect_delay_s
        self._alive = False
        self._stop_evt = threading.Event()
        self._sock: Optional[socket.socket] = None
        self.connects = 0
        self.acked = 0
        self.emit_errors = 0
        # Broker-ack semantics: with per-message acks, the ACK is gated
        # on the sink accepting the payload — the ingest decode pool must
        # not run this source's decode asynchronously (an async ack would
        # acknowledge a payload the journal has not seen).
        self.acks_on_emit = ack != "auto"
        # reconnect schedule on the shared primitive (was ad-hoc
        # delay-doubling state)
        self._backoff = Backoff(
            RetryPolicy(initial_s=reconnect_delay_s,
                        max_s=max_reconnect_delay_s),
            name="ingest.stomp-reconnect")

    def start(self) -> None:
        self._alive = True
        self._stop_evt.clear()
        # Supervised (ROADMAP: remaining-receiver chaos coverage):
        # transport errors are handled by the reconnect loop itself; the
        # supervisor catches anything unexpected — a frame-codec bug, an
        # injected fault escaping the per-message emit guard — and
        # restarts the whole loop with backoff instead of silently
        # killing the thread, escalating terminally after max_restarts.
        self._spawn_supervised(self._loop)
        super().start()

    def stop(self) -> None:
        self._alive = False
        self._stop_evt.set()
        sock = self._sock
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        self._stop_supervisor()
        super().stop()

    # -- session ------------------------------------------------------------

    def _connect(self) -> Tuple[socket.socket, float, float]:
        sock = socket.create_connection((self.host, self.port), timeout=10)
        try:
            return self._handshake(sock)
        except BaseException:
            # _loop only closes self._sock, which isn't assigned until the
            # handshake succeeds — close here or a refusing broker leaks
            # one fd per reconnect attempt
            try:
                sock.close()
            except OSError:
                pass
            raise

    def _handshake(self, sock: socket.socket) -> Tuple[socket.socket, float, float]:
        headers = {
            "accept-version": "1.2",
            "host": self.host,
            "heart-beat": f"{self.heartbeat_ms},{self.heartbeat_ms}",
        }
        if self.login is not None:
            headers["login"] = self.login
        if self.passcode is not None:
            headers["passcode"] = self.passcode
        sock.sendall(encode_frame("CONNECT", headers, escape=False))
        reader = FrameReader()
        sock.settimeout(10)
        while True:
            data = sock.recv(65536)
            if not data:
                raise StompError("broker closed during CONNECT")
            frames = reader.feed(data)
            if frames:
                break
        command, headers, body = frames[0]
        if command == "ERROR":
            raise StompError(
                f"broker refused connection: {headers.get('message', body)}")
        if command != "CONNECTED":
            raise StompError(f"expected CONNECTED, got {command}")
        # negotiate heart-beats: we send every max(ours, their-wanted);
        # we expect traffic every max(theirs, our-wanted); 0 disables
        sx, sy = 0, 0
        hb = headers.get("heart-beat", "0,0")
        try:
            sx, sy = (int(x) for x in hb.split(",", 1))
        except ValueError:
            pass
        send_every = max(self.heartbeat_ms, sy) / 1e3 if (
            self.heartbeat_ms and sy) else 0.0
        expect_every = max(sx, self.heartbeat_ms) / 1e3 if (
            sx and self.heartbeat_ms) else 0.0
        sock.sendall(encode_frame("SUBSCRIBE", {
            "id": "0", "destination": self.destination, "ack": self.ack,
        }))
        self._reader = reader
        return sock, send_every, expect_every

    def _loop(self) -> None:
        while self._alive:
            try:
                self._sock, send_every, expect_every = self._connect()
                self.connects += 1
                self._backoff.reset()  # connected: fresh schedule
                self._session(self._sock, send_every, expect_every)
            except (OSError, StompError) as e:
                if self._alive:
                    logger.debug("stomp receiver %s: %s", self.name, e)
            finally:
                sock, self._sock = self._sock, None
                if sock is not None:
                    try:
                        sock.close()
                    except OSError:
                        pass
            if self._alive:
                self._stop_evt.wait(self._backoff.next_delay())

    def _session(self, sock: socket.socket, send_every: float,
                 expect_every: float) -> None:
        last_sent = last_seen = time.monotonic()
        sock.settimeout(min(send_every or 1.0, 1.0))
        while self._alive:
            now = time.monotonic()
            if send_every and now - last_sent >= send_every:
                sock.sendall(b"\n")
                last_sent = now
            if expect_every and now - last_seen > 2 * expect_every:
                raise StompError("heart-beat timeout: broker silent")
            try:
                data = sock.recv(65536)
            except socket.timeout:
                continue
            if not data:
                raise StompError("broker closed the connection")
            last_seen = time.monotonic()
            for command, headers, body in self._reader.feed(data):
                if command == "MESSAGE":
                    delivered = True
                    if body:
                        try:
                            self._emit(body)
                        except OverloadShed:
                            # STOMP-native backpressure: leave the
                            # MESSAGE unacked — the broker redelivers
                            # once the subscription recovers (shed ≠
                            # loss; the payload is also dead-lettered
                            # at the admission edge for audit/replay)
                            delivered = False
                        except Exception:
                            # a poison message must not kill the receiver
                            # thread; leaving it unacked makes the broker
                            # redeliver (the at-least-once contract)
                            delivered = False
                            self.emit_errors += 1
                            logger.exception(
                                "%s: sink failed; message left unacked",
                                self.name)
                    if self.ack != "auto" and delivered:
                        ack_id = headers.get("ack")
                        if ack_id:
                            sock.sendall(
                                encode_frame("ACK", {"id": ack_id}))
                            last_sent = time.monotonic()
                            self.acked += 1
                elif command == "ERROR":
                    raise StompError(
                        headers.get("message", "broker ERROR"))
                # RECEIPT and others: ignore
