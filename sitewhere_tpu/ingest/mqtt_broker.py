"""In-process MQTT 3.1.1 broker: device fleets connect with NO middleware.

Reference: ``service-event-sources/.../activemq/ActiveMQBrokerEventReceiver.java``
starts an ActiveMQ ``BrokerService`` inside the microservice so devices
connect directly to SiteWhere — no external broker process.  Every other
receiver here is *client-side* toward MQTT/AMQP/STOMP brokers; this module
closes that gap for the dominant device protocol: a from-scratch hosted
MQTT broker speaking the same 3.1.1 subset as the client
(:mod:`sitewhere_tpu.ingest.mqtt`, whose wire primitives it reuses):

- CONNECT/CONNACK (client-id takeover: a reconnect under the same id
  replaces the old session, per MQTT-3.1.4-2), keepalive enforcement at
  1.5x the negotiated interval (MQTT-3.1.2-24);
- SUBSCRIBE/SUBACK + UNSUBSCRIBE/UNSUBACK with ``+``/``#`` wildcard
  matching (MQTT 4.7); granted QoS is capped at 1;
- PUBLISH QoS 0/1 (PUBACK to the publisher; fan-out to every matching
  subscriber at min(publish qos, subscription qos)); QoS 2 is refused by
  disconnecting the offender (subset contract, like the reference
  broker's transport rejecting an unsupported protocol level);
- PINGREQ/PINGRESP, DISCONNECT.  Will messages and retained messages
  are parsed and ignored (no state carried for them).

:class:`MqttBrokerReceiver` hosts the broker inside an event source and
taps every PUBLISH matching a topic filter as an inbound payload — the
``ActiveMQBrokerEventReceiver`` capability with MQTT as the hosted
protocol.
"""

from __future__ import annotations

import socket
import struct
import threading
import time
import uuid
from typing import Callable, Dict, List, Optional, Tuple

from sitewhere_tpu.runtime.overload import OverloadShed

from sitewhere_tpu.ingest.mqtt import (
    CONNACK,
    CONNECT,
    DISCONNECT,
    PINGREQ,
    PINGRESP,
    PUBACK,
    PUBLISH,
    SUBACK,
    SUBSCRIBE,
    UNSUBACK,
    UNSUBSCRIBE,
    MqttError,
    _encode_remaining,
    parse_publish,
    read_packet,
    write_publish,
)
from sitewhere_tpu.ingest.sources import Receiver, logger


def topic_matches(filt: str, topic: str) -> bool:
    """MQTT 4.7 wildcard match: ``+`` one level, ``#`` trailing multi.

    ``$``-prefixed topics never match a wildcard at the first level
    (MQTT-4.7.2-1)."""
    if topic.startswith("$") and filt[:1] in ("+", "#"):
        return False
    f_parts = filt.split("/")
    t_parts = topic.split("/")
    for i, fp in enumerate(f_parts):
        if fp == "#":
            return i == len(f_parts) - 1
        if i >= len(t_parts):
            return False
        if fp != "+" and fp != t_parts[i]:
            return False
    return len(f_parts) == len(t_parts)


def _parse_string(body: bytes, pos: int) -> Tuple[str, int]:
    (n,) = struct.unpack_from(">H", body, pos)
    return body[pos + 2: pos + 2 + n].decode("utf-8"), pos + 2 + n


class _Session:
    """One connected client: socket + subscriptions + a write lock
    (fan-out writes come from OTHER clients' reader threads)."""

    def __init__(self, client_id: str, sock: socket.socket,
                 keepalive: int = 0):
        self.client_id = client_id
        self.sock = sock
        self.keepalive = int(keepalive)  # negotiated seconds; 0 = none
        self.subs: Dict[str, int] = {}  # filter -> granted qos
        self.lock = threading.Lock()
        self.packet_id = 0

    def next_packet_id(self) -> int:
        self.packet_id = self.packet_id % 65535 + 1
        return self.packet_id

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


class MqttBroker:
    """Minimal hosted broker (see module docstring for the subset)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 max_keepalive_grace: float = 1.5):
        self.host = host
        self.port = port
        self.max_keepalive_grace = max_keepalive_grace
        # internal taps (the hosting receiver): called for EVERY publish
        # before subscriber fan-out, on the publisher's reader thread
        self.on_publish: List[Callable[[str, bytes], None]] = []
        self._srv: Optional[socket.socket] = None
        self._sessions: Dict[str, _Session] = {}
        self._lock = threading.Lock()
        self._alive = False
        self._accept_thread: Optional[threading.Thread] = None
        self.connects = 0
        self.published = 0
        self.delivered = 0
        self.tap_failures = 0
        self.sheds = 0
        # floor cap on the per-shed read pause; sessions that negotiated
        # a keepalive get a LONGER per-session deadline derived from it
        # (see shed_pause_s) — chatty devices pause longer without
        # tripping the 1.5x keepalive reaper
        self.max_shed_pause_s = 0.25

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        srv = socket.socket()
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((self.host, self.port))
        srv.listen(64)
        self.port = srv.getsockname()[1]
        self._srv = srv
        self._alive = True
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"mqtt-broker:{self.port}")
        self._accept_thread.start()

    def stop(self) -> None:
        self._alive = False
        if self._srv is not None:
            try:
                self._srv.close()
            except OSError:
                pass
            self._srv = None
        with self._lock:
            sessions = list(self._sessions.values())
            self._sessions.clear()
        for s in sessions:
            s.close()
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5)
            self._accept_thread = None

    @property
    def session_count(self) -> int:
        with self._lock:
            return len(self._sessions)

    # -- accept / session ----------------------------------------------------

    def _accept_loop(self) -> None:
        while self._alive:
            try:
                conn, addr = self._srv.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve, args=(conn,), daemon=True,
                name=f"mqtt-broker-session:{addr[0]}:{addr[1]}").start()

    def _handle_connect(self, conn: socket.socket) -> Optional[_Session]:
        conn.settimeout(10.0)
        ptype, _, body = read_packet(conn)
        if ptype != CONNECT:
            raise MqttError(f"expected CONNECT, got {ptype}")
        proto, pos = _parse_string(body, 0)
        level = body[pos]
        flags = body[pos + 1]
        (keepalive,) = struct.unpack_from(">H", body, pos + 2)
        pos += 4
        client_id, pos = _parse_string(body, pos)
        if flags & 0x04:  # will flag: parse + ignore (no will state kept)
            _, pos = _parse_string(body, pos)   # will topic
            (wn,) = struct.unpack_from(">H", body, pos)
            pos += 2 + wn                       # will message
        if flags & 0x80:
            _, pos = _parse_string(body, pos)   # username (unauthenticated
        if flags & 0x40:                        # hosting; parse + ignore)
            (pn,) = struct.unpack_from(">H", body, pos)
            pos += 2 + pn
        if proto != "MQTT" or level != 4:
            # 0x01 = unacceptable protocol level
            conn.sendall(bytes([CONNACK << 4, 2, 0, 0x01]))
            return None
        if not client_id:
            if not flags & 0x02:  # empty id REQUIRES clean session
                conn.sendall(bytes([CONNACK << 4, 2, 0, 0x02]))
                return None
            client_id = f"auto-{uuid.uuid4().hex[:12]}"
        session = _Session(client_id, conn, keepalive=keepalive)
        with self._lock:
            old = self._sessions.pop(client_id, None)
            self._sessions[client_id] = session
        if old is not None:
            old.close()  # MQTT-3.1.4-2: same client id takes over
        # keepalive enforcement: 1.5x grace, else drop the session
        conn.settimeout(keepalive * self.max_keepalive_grace
                        if keepalive else None)
        # Bounded SENDS even for keepalive-0 (blocking-mode) sessions: a
        # subscriber that stops reading fills its buffers, and an
        # unbounded sendall to it would wedge whichever publisher thread
        # is fanning out (SO_SNDTIMEO only applies in blocking mode; the
        # keepalive>0 path's settimeout already bounds sends).
        conn.setsockopt(socket.SOL_SOCKET, socket.SO_SNDTIMEO,
                        struct.pack("ll", 5, 0))
        conn.sendall(bytes([CONNACK << 4, 2, 0, 0]))  # session-present=0
        self.connects += 1
        return session

    def _serve(self, conn: socket.socket) -> None:
        session: Optional[_Session] = None
        try:
            session = self._handle_connect(conn)
            if session is None:
                return
            while self._alive:
                # interruptible: an idle-timeout (keepalive * grace with
                # no inbound packet) propagates and reaps the session;
                # a timeout MID-packet keeps waiting for the remainder
                ptype, flags, body = read_packet(conn, interruptible=True)
                if ptype == PUBLISH:
                    self._handle_publish(session, flags, body)
                elif ptype == SUBSCRIBE:
                    self._handle_subscribe(session, body)
                elif ptype == UNSUBSCRIBE:
                    self._handle_unsubscribe(session, body)
                elif ptype == PINGREQ:
                    with session.lock:
                        conn.sendall(bytes([PINGRESP << 4, 0]))
                elif ptype == DISCONNECT:
                    return
                elif ptype == PUBACK:
                    pass  # subscriber acks for our QoS1 fan-out
                else:
                    raise MqttError(f"unsupported packet type {ptype}")
        except (MqttError, OSError, socket.timeout, struct.error,
                IndexError, UnicodeDecodeError):
            pass  # dead/violating client: drop the session
        finally:
            if session is not None:
                with self._lock:
                    if self._sessions.get(session.client_id) is session:
                        del self._sessions[session.client_id]
            try:
                conn.close()
            except OSError:
                pass

    def shed_pause_s(self, session: _Session, hint_s: float) -> float:
        """Per-session shed-pause deadline, tied to the NEGOTIATED
        keepalive.

        The pause blocks the session's reader thread, so its bound is
        what keeps backpressure from looking like death: a keepalive-0
        session has no liveness contract, so only the conservative
        broker-wide floor applies; a session with keepalive K may pause
        up to the reaper's slack — ``(grace - 1) * K`` (grace is the
        MQTT-3.1.2-24 1.5x multiplier, so half a keepalive) — because
        the device's next scheduled packet still lands inside the
        ``K * grace`` silence window the reaper enforces.  Chatty
        high-keepalive devices therefore absorb a long Retry-After as
        one pause instead of a redelivery storm, while short-keepalive
        devices keep their snappy reap behavior."""
        cap = self.max_shed_pause_s
        if session.keepalive > 0:
            cap = max(cap, (self.max_keepalive_grace - 1.0)
                      * session.keepalive)
        return min(float(hint_s), cap)

    # -- packet handlers -----------------------------------------------------

    def _handle_publish(self, session: _Session, flags: int,
                        body: bytes) -> None:
        topic, payload, qos, pid = parse_publish(flags, body)
        if qos > 1:
            raise MqttError("QoS 2 not supported by the hosted broker")
        # Deliver FIRST, ack LAST (at-least-once): a device that fires
        # its publishes and closes immediately can make the PUBACK send
        # fail with EPIPE — the message it successfully delivered must
        # not be dropped with the session.
        self.published += 1
        for tap in self.on_publish:
            try:
                tap(topic, payload)
            except OverloadShed as e:
                # MQTT-native backpressure: withhold the PUBACK (the
                # device's unacked QoS-1 publish is its redelivery cue)
                # and PAUSE reading this session briefly — the TCP
                # receive window fills behind the paused read, slowing
                # the publisher at the socket layer.  The session stays
                # up: shedding is flow control, not a fault.
                self.sheds += 1
                time.sleep(self.shed_pause_s(session, e.retry_after_s))
                return
            except Exception as e:
                # At-least-once REQUIRES withholding the PUBACK when the
                # tap (the platform's intake) failed: dropping the
                # session makes the publisher's drain time out and the
                # device redeliver — acking here would silently lose the
                # event.  Contract: taps must swallow PAYLOAD-level
                # errors themselves (InboundEventSource.on_encoded_payload
                # does — decode failures dead-letter, forward failures
                # are counted), so what reaches here is crash-grade or
                # injected; a tap that raised deterministically per
                # payload would otherwise make the device redeliver the
                # same poison forever.
                self.tap_failures += 1
                logger.warning("mqtt broker tap failed for topic %s: %s "
                               "(withholding PUBACK; publisher retries)",
                               topic, e)
                raise MqttError(f"tap failed: {e}") from e
        # ack after the taps (the at-least-once state that matters) but
        # BEFORE subscriber fan-out: a stalled subscriber's full send
        # buffer must not block the publisher's PUBACK
        if qos == 1:
            with session.lock:
                session.sock.sendall(
                    bytes([PUBACK << 4, 2]) + struct.pack(">H", pid))
        self._fanout(topic, payload, qos, exclude=None)

    def _fanout(self, topic: str, payload: bytes, qos: int,
                exclude: Optional[_Session]) -> None:
        with self._lock:
            targets = [
                (s, min(qos, sub_qos))
                for s in self._sessions.values() if s is not exclude
                for filt, sub_qos in list(s.subs.items())
                if topic_matches(filt, topic)
            ]
        for s, out_qos in targets:
            try:
                with s.lock:
                    write_publish(s.sock, topic, payload, out_qos,
                                  s.next_packet_id() if out_qos else 0)
                self.delivered += 1
            except OSError:
                # A send failure/timeout means the subscriber is dead or
                # not reading (full buffers) — and a timed-out sendall
                # may have written a PARTIAL frame, corrupting its
                # stream.  Close the socket so its reader thread reaps
                # the session; otherwise every future matching publish
                # would stall the full send timeout on it, forever.
                s.close()

    def _handle_subscribe(self, session: _Session, body: bytes) -> None:
        (pid,) = struct.unpack_from(">H", body, 0)
        pos = 2
        granted = bytearray()
        while pos < len(body):
            filt, pos = _parse_string(body, pos)
            want_qos = body[pos] & 0x03
            pos += 1
            qos = min(want_qos, 1)  # QoS 2 capped (subset)
            session.subs[filt] = qos
            granted.append(qos)
        if not granted:
            raise MqttError("SUBSCRIBE with no topic filters")
        out = struct.pack(">H", pid) + bytes(granted)
        with session.lock:
            session.sock.sendall(
                bytes([SUBACK << 4]) + _encode_remaining(len(out)) + out)

    def _handle_unsubscribe(self, session: _Session, body: bytes) -> None:
        (pid,) = struct.unpack_from(">H", body, 0)
        pos = 2
        while pos < len(body):
            filt, pos = _parse_string(body, pos)
            session.subs.pop(filt, None)
        with session.lock:
            session.sock.sendall(
                bytes([UNSUBACK << 4, 2]) + struct.pack(">H", pid))


class MqttBrokerReceiver(Receiver):
    """Event receiver that HOSTS the broker (no external middleware).

    Devices connect straight to this port and publish; every PUBLISH
    whose topic matches ``topic_filter`` feeds the source's decoder.
    Reference: ``ActiveMQBrokerEventReceiver.java`` (embedded
    BrokerService + consumer), with MQTT as the hosted protocol.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 topic_filter: str = "sitewhere/input/#"):
        super().__init__(name=f"mqtt-broker-receiver:{host}:{port}")
        self.topic_filter = topic_filter
        self.broker = MqttBroker(host=host, port=port)
        self.broker.on_publish.append(self._tap)
        # QoS-1 PUBACK is withheld when the intake tap crashes — the ack
        # is gated on emit returning, so the ingest decode pool must keep
        # this source synchronous (see InboundEventSource.decode_pool)
        self.acks_on_emit = True

    @property
    def port(self) -> int:
        return self.broker.port

    def _tap(self, topic: str, payload: bytes) -> None:
        if topic_matches(self.topic_filter, topic):
            self._emit(payload)

    def start(self) -> None:
        self.broker.start()
        super().start()

    def stop(self) -> None:
        self.broker.stop()
        super().stop()
