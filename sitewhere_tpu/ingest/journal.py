"""Durable append-only event journal with offsets — the Kafka analog.

The reference gets durability + replay from Kafka: producers append protobuf
records to partitioned topics, consumers track offsets with manual commit
and resume after a crash (``MicroserviceKafkaConsumer.java:94,116-139``;
README: "events stack up in Kafka… resume where it left off").  Here the
boundary durability lives in a host-side segmented journal:

- records are length-prefixed, CRC-checked blobs appended to segment files;
- every record has a monotonically increasing offset;
- consumers (:class:`JournalReader`) poll batches from a committed offset
  and commit back — replay after crash = reopen at the committed offset;
- dead-letter streams (failed-decode, unregistered, undelivered — the
  reference's ``KafkaTopicNaming.java:48-78`` topics) are just more journals.

Segment format: ``[u32 len][u32 crc32][len bytes]*``.  Offsets are logical
record indices; a sparse index maps offsets to (segment, file position).
"""

from __future__ import annotations

import bisect
import json
import os
import struct
import threading
import time
import zlib
from typing import Iterator, List, Optional, Tuple

_HEADER = struct.Struct("<II")  # (length, crc32)
_INDEX_EVERY = 64  # sparse-index granularity (records)


class CorruptJournal(Exception):
    pass


class Journal:
    """A named, durable, append-only record log.

    ``fsync_every`` trades durability for throughput the same way the
    reference's Mongo event buffer trades flush interval
    (``DeviceEventBuffer.java:40-46``): 0 = fsync on every append (safest),
    N = fsync every N appends and on close/rotate.
    """

    def __init__(
        self,
        root: str,
        name: str = "events",
        segment_bytes: int = 64 << 20,
        fsync_every: int = 256,
        index_every: int = _INDEX_EVERY,
    ):
        self.dir = os.path.join(root, name)
        os.makedirs(self.dir, exist_ok=True)
        self.segment_bytes = segment_bytes
        self.fsync_every = fsync_every
        # 1 = dense index (O(1) point reads — e.g. large media chunks);
        # higher = sparser index, less memory, scans seek then roll forward.
        self.index_every = max(1, index_every)
        self._lock = threading.Lock()
        self._unsynced = 0
        # duration of the most recent fsync — an overload pressure
        # signal (a saturated disk shows up here before queues fill)
        self.last_fsync_s = 0.0
        # Offset index: (offset, segment path, byte pos) every
        # index_every records, so scans seek instead of replaying segments.
        self._index: List[Tuple[int, str, int]] = []
        # segments: sorted list of (base_offset, path)
        self._segments: List[Tuple[int, str]] = self._scan_segments()
        if not self._segments:
            self._segments = [(0, self._segment_path(0))]
        # Index EVERY segment on open so point reads into older segments
        # keep their granularity.  Rotated segments are immutable: their
        # index is persisted in a sidecar at rotation, so reopen cost is
        # O(sidecar) not O(segment bytes); a missing/stale sidecar falls
        # back to a scan (which also rebuilds it).  Only the final segment
        # may carry a torn tail (rotation fsyncs + closes the others).
        for base, path in self._segments[:-1]:
            if not self._load_sidecar(base, path):
                self._count_records(path, base, truncate_tail=False)
                self._write_sidecar(base, path)
        base, path = self._segments[-1]
        self._next_offset = base + self._count_records(path, base)
        self._file = open(path, "ab")

    # -- segment bookkeeping ------------------------------------------------

    def _segment_path(self, base_offset: int) -> str:
        return os.path.join(self.dir, f"{base_offset:020d}.log")

    def _sidecar_path(self, path: str) -> str:
        return path[:-4] + ".idx"

    def _load_sidecar(self, base: int, path: str) -> bool:
        """Load a rotated segment's persisted index; False on miss/stale."""
        try:
            with open(self._sidecar_path(path)) as f:
                doc = json.load(f)
        except (FileNotFoundError, ValueError):
            return False
        if doc.get("index_every") != self.index_every \
                or doc.get("size") != os.path.getsize(path):
            return False
        self._index.extend((base + off, path, pos)
                           for off, pos in doc.get("entries", []))
        return True

    def _write_sidecar(self, base: int, path: str) -> None:
        entries = [[off - base, pos] for off, ipath, pos in self._index
                   if ipath == path]
        tmp = self._sidecar_path(path) + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"index_every": self.index_every,
                       "size": os.path.getsize(path),
                       "entries": entries}, f)
        os.replace(tmp, self._sidecar_path(path))

    def _scan_segments(self) -> List[Tuple[int, str]]:
        segs = []
        for fname in sorted(os.listdir(self.dir)):
            if fname.endswith(".log"):
                segs.append((int(fname[:-4]), os.path.join(self.dir, fname)))
        return segs

    def _count_records(self, path: str, base: int = 0,
                       truncate_tail: bool = True) -> int:
        """Count (and index) a segment's records on open.

        ``truncate_tail=True`` (final segment only): a torn tail from a
        crash mid-append is truncated.  ``False`` (rotated segments): any
        invalid record is real corruption → :class:`CorruptJournal`.
        """
        n = 0
        try:
            size = os.path.getsize(path)
        except FileNotFoundError:
            return 0
        with open(path, "rb") as f:
            pos = 0
            while True:
                if pos + _HEADER.size > size:
                    if pos < size:
                        if not truncate_tail:
                            raise CorruptJournal(f"{path} @ byte {pos}")
                        # Stray partial header from a crash mid-append:
                        # truncate so later appends stay readable.
                        with open(path, "ab") as tf:
                            tf.truncate(pos)
                    break
                length, crc = _HEADER.unpack(f.read(_HEADER.size))
                payload = f.read(length)
                if len(payload) < length:
                    if not truncate_tail:
                        raise CorruptJournal(f"{path} @ byte {pos}")
                    # Ran past EOF: torn tail from a crash mid-append.
                    with open(path, "ab") as tf:
                        tf.truncate(pos)
                    break
                if zlib.crc32(payload) != crc:
                    if truncate_tail and pos + _HEADER.size + length >= size:
                        # Final record, bad checksum: torn tail — truncate.
                        with open(path, "ab") as tf:
                            tf.truncate(pos)
                        break
                    # Corruption with valid data after it: not a crash
                    # artifact — refuse to silently drop records.
                    raise CorruptJournal(f"{path} @ byte {pos}")
                if (base + n) % self.index_every == 0:
                    self._index.append((base + n, path, pos))
                pos += _HEADER.size + length
                n += 1
        return n

    # -- producer side ------------------------------------------------------

    def append(self, payload: bytes) -> int:
        """Append one record; returns its offset."""
        with self._lock:
            offset = self._next_offset
            if offset % self.index_every == 0:
                self._index.append((offset, self._file.name, self._file.tell()))
            self._file.write(_HEADER.pack(len(payload), zlib.crc32(payload)))
            self._file.write(payload)
            self._next_offset += 1
            self._unsynced += 1
            if self.fsync_every == 0 or self._unsynced >= self.fsync_every:
                self._file.flush()
                t0 = time.perf_counter()
                os.fsync(self._file.fileno())
                self.last_fsync_s = time.perf_counter() - t0
                self._unsynced = 0
            if self._file.tell() >= self.segment_bytes:
                self._rotate()
            return offset

    def append_json(self, obj) -> int:
        return self.append(json.dumps(obj, separators=(",", ":")).encode())

    def _rotate(self) -> None:
        self._file.flush()
        os.fsync(self._file.fileno())
        self._unsynced = 0
        self._file.close()
        # persist the finished segment's index (it is immutable from here)
        finished_base, finished_path = self._segments[-1]
        self._write_sidecar(finished_base, finished_path)
        path = self._segment_path(self._next_offset)
        self._segments.append((self._next_offset, path))
        self._file = open(path, "ab")

    def flush(self) -> None:
        with self._lock:
            self._file.flush()
            t0 = time.perf_counter()
            os.fsync(self._file.fileno())
            self.last_fsync_s = time.perf_counter() - t0
            self._unsynced = 0

    def close(self) -> None:
        self.flush()
        self._file.close()

    def prune(self, upto: int) -> int:
        """Delete whole segments every record of which is below ``upto``.

        The Kafka retention analog, applied at the commit frontier
        instead of by wall-clock: callers prune only below a durably
        committed consumer offset (e.g. the forward spool after the peer
        acked).  The active segment is never deleted; reads below the
        new first base become invalid by contract.  Returns the number
        of segments removed."""
        removed = 0
        with self._lock:
            while len(self._segments) > 1 and self._segments[1][0] <= upto:
                _base, path = self._segments.pop(0)
                first_base = self._segments[0][0]
                self._index = [e for e in self._index if e[0] >= first_base]
                for victim in (path, self._sidecar_path(path)):
                    try:
                        os.unlink(victim)
                    except FileNotFoundError:
                        pass
                removed += 1
        return removed

    @property
    def end_offset(self) -> int:
        """Offset one past the last appended record."""
        return self._next_offset

    # -- random access (host payload_ref resolution) ------------------------

    def read_one(self, offset: int) -> bytes:
        """Read the record at ``offset`` (used to resolve ``payload_ref``)."""
        for rec_offset, payload in self.scan(offset, offset + 1):
            return payload
        raise KeyError(f"offset {offset} not in journal")

    def scan(self, start: int, stop: Optional[int] = None) -> Iterator[Tuple[int, bytes]]:
        """Yield ``(offset, payload)`` for offsets in ``[start, stop)``."""
        with self._lock:
            # Make appended bytes visible to readers of the same files;
            # durability (fsync) stays on the append policy.  Segments
            # snapshot under the lock so a concurrent prune() can't pull
            # the list out from under the iteration.
            self._file.flush()
            index = list(self._index)
            segments = list(self._segments)
            next_offset = self._next_offset
        for i, (base, path) in enumerate(segments):
            nxt = (
                segments[i + 1][0]
                if i + 1 < len(segments)
                else next_offset
            )
            if nxt <= start:
                continue
            offset, seek_pos = base, 0
            # Binary-search the index for the newest entry in THIS segment
            # at or before max(start, base).
            target = max(start, base)
            lo = bisect.bisect_right(index, (target, chr(0x10FFFF), 0)) - 1
            while lo >= 0:
                ioff, ipath, ipos = index[lo]
                if ioff < base:
                    break
                if ipath == path:
                    offset, seek_pos = ioff, ipos
                    break
                lo -= 1
            try:
                f = open(path, "rb")
            except FileNotFoundError:
                continue   # pruned between snapshot and open
            with f:
                f.seek(seek_pos)
                while True:
                    header = f.read(_HEADER.size)
                    if len(header) < _HEADER.size:
                        break
                    length, crc = _HEADER.unpack(header)
                    payload = f.read(length)
                    if len(payload) < length:
                        break
                    if zlib.crc32(payload) != crc:
                        raise CorruptJournal(f"{path} @ record {offset}")
                    if offset >= start:
                        if stop is not None and offset >= stop:
                            return
                        yield offset, payload
                    offset += 1


class JournalReader:
    """A named consumer with a committed offset (consumer-group analog).

    Commit semantics match the reference's manual Kafka commit: records are
    redelivered after a crash unless committed
    (``MicroserviceKafkaConsumer.java:94``) — at-least-once.
    """

    def __init__(self, journal: Journal, group: str):
        self.journal = journal
        self.group = group
        self._offset_path = os.path.join(journal.dir, f"{group}.offset")
        # Cached: the file changes only through this object's commit(), and
        # callers poll `committed` on every idle dispatch cycle.
        self._committed = self._load_committed()
        self.position = self._committed

    def _load_committed(self) -> int:
        try:
            with open(self._offset_path) as f:
                return int(f.read().strip() or 0)
        except FileNotFoundError:
            return 0

    @property
    def committed(self) -> int:
        return self._committed

    @property
    def lag(self) -> int:
        return self.journal.end_offset - self.position

    def poll(self, max_records: int) -> List[Tuple[int, bytes]]:
        """Fetch up to ``max_records`` from the current (uncommitted) position."""
        out = list(
            self.journal.scan(self.position, self.position + max_records)
        )
        if out:
            self.position = out[-1][0] + 1
        return out

    def commit(self, upto: Optional[int] = None) -> None:
        """Durably record progress (``upto`` = offset one past last processed)."""
        value = self.position if upto is None else upto
        tmp = f"{self._offset_path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(str(value))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._offset_path)
        self._committed = value

    def seek(self, offset: int) -> None:
        """Rewind/replay from an arbitrary offset (reprocess-topic analog,
        reference ``KafkaTopicNaming.java:172-174``)."""
        self.position = offset
