"""Payload decoders: raw protocol bytes → typed device requests.

Reference: ``IDeviceEventDecoder`` implementations in
``service-event-sources`` — JSON (``decoder/json/JsonDeviceRequestDecoder.java``,
``JsonBatchEventDecoder.java``), protobuf
(``decoder/protobuf/ProtobufDeviceEventDecoder.java``), scripted decoders
(``decoder/GroovyEventDecoder.java``), and a composite decoder that picks a
sub-decoder per device type
(``decoder/composite/BinaryCompositeDeviceEventDecoder.java``).

Here decoders are plain callables ``bytes -> list[DecodedRequest]``:

- :class:`JsonDecoder` — the envelope ``{"deviceToken": ..., "type": ...,
  "request": {...}}`` (the shape the reference's MQTT conformance senders
  emit, ``MqttTests.java:107-168``; ``hardwareId`` accepted as alias).
- :class:`JsonBatchDecoder` — ``{"deviceToken": ..., "events": [...]}``.
- :class:`BinaryDecoder` — a compact length-prefixed binary framing (the
  protobuf-decoder analog, without a schema compiler dependency).
- :class:`CompositeDecoder` — metadata extractor chooses a sub-decoder.
- "Scripting" (the Groovy analog) = any user-supplied callable with the
  same signature.
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
import enum
import json
import struct
from typing import Callable, Dict, List, Optional, Tuple

from sitewhere_tpu.schema import AlertLevel, EventType


class DecodeError(Exception):
    """Failed decode → dead-letter journal (reference: failed-decode topic,
    ``EventSourcesManager.java:189``)."""


class RequestKind(enum.IntEnum):
    # The 6 event types (EventType values 0..5), plus host-plane requests.
    MEASUREMENT = 0
    LOCATION = 1
    ALERT = 2
    COMMAND_INVOCATION = 3
    COMMAND_RESPONSE = 4
    STATE_CHANGE = 5
    REGISTRATION = 10       # reference: RegisterDevice → registration topic
    STREAM_DATA = 11        # reference: device stream chunks
    MAPPING = 12            # reference: DeviceMappingCreateRequest
    STREAM_CREATE = 13      # reference: DeviceStreamCreateRequest
    STREAM_SEND = 14        # reference: SendDeviceStreamDataRequest


_TYPE_ALIASES = {
    "measurement": RequestKind.MEASUREMENT,
    "measurements": RequestKind.MEASUREMENT,
    "devicemeasurements": RequestKind.MEASUREMENT,
    "location": RequestKind.LOCATION,
    "devicelocation": RequestKind.LOCATION,
    "alert": RequestKind.ALERT,
    "devicealert": RequestKind.ALERT,
    "registerdevice": RequestKind.REGISTRATION,
    "registration": RequestKind.REGISTRATION,
    "acknowledge": RequestKind.COMMAND_RESPONSE,
    "commandresponse": RequestKind.COMMAND_RESPONSE,
    "commandinvocation": RequestKind.COMMAND_INVOCATION,
    "statechange": RequestKind.STATE_CHANGE,
    "streamdata": RequestKind.STREAM_DATA,
    "devicestreamdata": RequestKind.STREAM_DATA,
    "devicestream": RequestKind.STREAM_CREATE,
    "devicestreamcreate": RequestKind.STREAM_CREATE,
    "sendstreamdata": RequestKind.STREAM_SEND,
}

_LEVEL_ALIASES = {
    "info": AlertLevel.INFO,
    "warning": AlertLevel.WARNING,
    "error": AlertLevel.ERROR,
    "critical": AlertLevel.CRITICAL,
}


@dataclasses.dataclass
class DecodedRequest:
    """One typed inbound request (reference: ``IDecodedDeviceRequest``)."""

    kind: RequestKind
    device_token: str
    ts_s: int
    ts_ns: int = 0
    # measurement
    mtype: Optional[str] = None
    value: float = 0.0
    # location
    lat: float = 0.0
    lon: float = 0.0
    elevation: float = 0.0
    # alert
    alert_type: Optional[str] = None
    alert_level: int = AlertLevel.INFO
    alert_message: Optional[str] = None
    # command response (reference: Acknowledge w/ originating event id)
    originating_event: Optional[str] = None
    response: Optional[str] = None
    # registration
    device_type_token: Optional[str] = None
    area_token: Optional[str] = None
    customer_token: Optional[str] = None
    # generic
    metadata: Optional[dict] = None
    # device stream requests (host plane)
    stream_id: Optional[str] = None
    sequence_number: int = 0
    stream_data: Optional[bytes] = None
    content_type: Optional[str] = None
    alternate_id: Optional[str] = None   # dedup key (AlternateIdDeduplicator)
    update_state: bool = True            # reference: event.isUpdateState()

    @property
    def event_type(self) -> Optional[EventType]:
        """The on-device event type, or None for host-plane requests."""
        if self.kind <= RequestKind.STATE_CHANGE:
            return EventType(int(self.kind))
        return None


def _ts_from_seconds(value: float) -> Tuple[int, int]:
    """Epoch-SECONDS float → (ts_s, ts_ns) with the int32 schema check.

    No epoch-millis heuristic: callers whose wire format DEFINES the
    field as seconds (the binary framing) must not reinterpret corrupt
    values in (1e11, ~2.1e12] as milliseconds — they dead-letter."""
    s = int(value)  # OverflowError (inf) / ValueError (nan) → DecodeError
    if not -(1 << 31) <= s < (1 << 31):
        raise DecodeError(f"timestamp out of range: {value!r}")
    return s, int(round((value - s) * 1e9))


def _parse_ts(value) -> Tuple[int, int]:
    """Accept epoch seconds (int/float), epoch millis (int > 1e11), or ISO."""
    if value is None:
        return 0, 0
    if isinstance(value, (int, float)):
        if value > 1e11:  # epoch millis
            value = value / 1000.0
        s = int(value)
        if not -(1 << 31) <= s < (1 << 31):
            # the schema stores epoch seconds as int32; a huge finite
            # literal would otherwise escape as OverflowError at the
            # batcher's column conversion (fuzz-found crash vector)
            raise DecodeError(f"eventDate out of range: {value!r}")
        return s, int(round((value - s) * 1e9))
    if isinstance(value, str):
        try:
            dt = _dt.datetime.fromisoformat(value.replace("Z", "+00:00"))
        except ValueError as e:
            raise DecodeError(f"bad eventDate {value!r}") from e
        ts = dt.timestamp()
        s = int(ts)
        if not -(1 << 31) <= s < (1 << 31):
            raise DecodeError(f"eventDate out of range: {value!r}")
        return s, int(round((ts - s) * 1e9))
    raise DecodeError(f"bad eventDate {value!r}")


def _decode_one(token: str, kind_name: str, req: dict) -> DecodedRequest:
    try:
        return _decode_one_inner(token, kind_name, req)
    except DecodeError:
        raise
    except (ValueError, TypeError, KeyError, OverflowError) as e:
        # Malformed field values (float("abc"), int(None), and the
        # OverflowError from int(inf) — json.loads parses "1e999" and
        # the "Infinity" literal to float inf) must become DecodeError
        # so sources dead-letter them instead of the exception killing
        # the receiver thread.  Fuzz-found: an eventDate of 1e999 on
        # any scalar-path line escaped here as OverflowError.
        raise DecodeError(f"bad field in {kind_name!r} request: {e}") from e


def _decode_one_inner(token: str, kind_name: str, req: dict) -> DecodedRequest:
    kind = _TYPE_ALIASES.get(kind_name.strip().lower())
    if kind is None:
        raise DecodeError(f"unknown request type {kind_name!r}")
    ts_s, ts_ns = _parse_ts(req.get("eventDate", req.get("timestamp")))
    common = dict(
        kind=kind,
        device_token=token,
        ts_s=ts_s,
        ts_ns=ts_ns,
        metadata=req.get("metadata"),
        alternate_id=req.get("alternateId"),
        update_state=bool(req.get("updateState", True)),
    )
    if kind == RequestKind.MEASUREMENT:
        # `or`: an empty name falls through to the alias (same rule on
        # the columnar and native paths — they must never diverge)
        name = req.get("name") or req.get("measurementId")
        if not name or "value" not in req:
            raise DecodeError("measurement needs name+value")
        return DecodedRequest(mtype=str(name), value=float(req["value"]), **common)
    if kind == RequestKind.LOCATION:
        try:
            return DecodedRequest(
                lat=float(req["latitude"]),
                lon=float(req["longitude"]),
                elevation=float(req.get("elevation", 0.0)),
                **common,
            )
        except KeyError as e:
            raise DecodeError(f"location missing {e}") from e
    if kind == RequestKind.ALERT:
        level = req.get("level", "info")
        if isinstance(level, str):
            level = _LEVEL_ALIASES.get(level.lower())
            if level is None:
                raise DecodeError(f"bad alert level {req.get('level')!r}")
        level = int(level)
        if not -(1 << 31) <= level < (1 << 31):
            raise DecodeError(f"alert level out of range: {level!r}")
        return DecodedRequest(
            alert_type=str(req.get("type", req.get("alertType", "alert"))),
            alert_level=level,
            alert_message=req.get("message"),
            **common,
        )
    if kind == RequestKind.COMMAND_RESPONSE:
        return DecodedRequest(
            originating_event=req.get("originatingEventId"),
            response=req.get("response"),
            **common,
        )
    if kind == RequestKind.COMMAND_INVOCATION:
        # journaled invocation payloads (create_command_invocation) must
        # re-decode on crash replay; the invocation token correlates the
        # row with its responses
        return DecodedRequest(
            originating_event=req.get("invocationToken"),
            **common,
        )
    if kind == RequestKind.REGISTRATION:
        return DecodedRequest(
            device_type_token=req.get("deviceTypeToken", req.get("specificationToken")),
            area_token=req.get("areaToken"),
            customer_token=req.get("customerToken"),
            **common,
        )
    if kind in (RequestKind.STREAM_CREATE, RequestKind.STREAM_DATA,
                RequestKind.STREAM_SEND):
        stream_id = req.get("streamId")
        if not stream_id:
            raise DecodeError("stream request needs streamId")
        if kind == RequestKind.STREAM_CREATE:
            return DecodedRequest(
                stream_id=str(stream_id),
                # `or`: an explicit JSON null must fall back, not become
                # the literal string "None"
                content_type=str(req.get("contentType")
                                 or "application/octet-stream"),
                **common)
        seq = req.get("sequenceNumber")
        if seq is None:
            raise DecodeError("stream request needs sequenceNumber")
        if kind == RequestKind.STREAM_SEND:
            return DecodedRequest(stream_id=str(stream_id),
                                  sequence_number=int(seq), **common)
        raw = req.get("data")
        if raw is None:
            raise DecodeError("stream data needs data (base64)")
        import base64 as _base64

        try:
            blob = _base64.b64decode(raw, validate=True)
        except Exception as e:
            raise DecodeError(f"bad stream data base64: {e}") from e
        return DecodedRequest(stream_id=str(stream_id),
                              sequence_number=int(seq),
                              stream_data=blob, **common)
    if kind in (RequestKind.STATE_CHANGE, RequestKind.MAPPING):
        return DecodedRequest(**common)
    raise DecodeError(f"unsupported request type {kind_name!r}")


class JsonDecoder:
    """``{"deviceToken"|"hardwareId": ..., "type": ..., "request": {...}}``"""

    def __call__(self, payload: bytes) -> List[DecodedRequest]:
        try:
            doc = json.loads(payload)
        except (ValueError, UnicodeDecodeError) as e:
            raise DecodeError(f"bad json: {e}") from e
        if not isinstance(doc, dict):
            raise DecodeError("json payload must be an object")
        token = doc.get("deviceToken", doc.get("hardwareId"))
        if not token:
            raise DecodeError("missing deviceToken/hardwareId")
        kind = doc.get("type")
        if not kind:
            raise DecodeError("missing type")
        req = doc.get("request", {})
        if not isinstance(req, dict):
            raise DecodeError("request must be an object")
        return [_decode_one(str(token), str(kind), req)]


class JsonBatchDecoder:
    """``{"deviceToken": ..., "events": [{"type": ..., ...}, ...]}``

    Reference: ``JsonBatchEventDecoder.java`` — many events in one payload.
    """

    def __call__(self, payload: bytes) -> List[DecodedRequest]:
        try:
            doc = json.loads(payload)
        except (ValueError, UnicodeDecodeError) as e:
            raise DecodeError(f"bad json: {e}") from e
        token = doc.get("deviceToken", doc.get("hardwareId"))
        if not token:
            raise DecodeError("missing deviceToken/hardwareId")
        events = doc.get("events")
        if not isinstance(events, list) or not events:
            raise DecodeError("missing events[]")
        out = []
        for ev in events:
            if not isinstance(ev, dict) or "type" not in ev:
                raise DecodeError("each event needs a type")
            out.append(_decode_one(str(token), str(ev["type"]), ev))
        return out


def parse_envelopes(payload: bytes) -> List[dict]:
    """Parse wire bytes — one JSON envelope, a JSON array of envelopes, or
    NDJSON — into a list of envelope dicts.  Shared by the scalar
    :class:`JsonLinesDecoder` and the columnar wire edge
    (:func:`sitewhere_tpu.ingest.columnar.decode_json_lines`)."""
    text = payload.strip()
    if not text:
        raise DecodeError("empty payload")
    try:
        if text.startswith(b"["):
            docs = json.loads(text)
        elif b"\n" in text:
            # one synthesized array parse instead of N json.loads calls;
            # blank interior lines are legal NDJSON and are skipped
            lines = [ln for ln in text.split(b"\n") if ln.strip()]
            try:
                docs = json.loads(b"[" + b",".join(lines) + b"]")
            except ValueError:
                # not NDJSON after all — a pretty-printed single envelope
                # (journaled by the scalar path) also contains newlines
                docs = [json.loads(text)]
        else:
            docs = [json.loads(text)]
    except (ValueError, UnicodeDecodeError) as e:
        raise DecodeError(f"bad json: {e}") from e
    if not isinstance(docs, list):
        raise DecodeError("wire batch must be envelope(s)")
    return docs


_KIND_WIRE_NAMES = {
    RequestKind.MEASUREMENT: "Measurement",
    RequestKind.LOCATION: "Location",
    RequestKind.ALERT: "Alert",
    RequestKind.COMMAND_RESPONSE: "CommandResponse",
    RequestKind.COMMAND_INVOCATION: "CommandInvocation",
    RequestKind.REGISTRATION: "Registration",
    RequestKind.STATE_CHANGE: "StateChange",
    RequestKind.STREAM_DATA: "StreamData",
    RequestKind.STREAM_CREATE: "DeviceStream",
    RequestKind.STREAM_SEND: "SendStreamData",
}


def encode_envelope(req: DecodedRequest) -> bytes:
    """:class:`DecodedRequest` → the JSON wire envelope
    :func:`_decode_one` accepts — the inverse of decode for the fields
    the pipeline carries.  Used when an already-decoded row must cross
    DCN to its owning host (``rpc/forward.py``) and re-enter that host's
    wire intake: re-encoding beats inventing a second serialization for
    the same data (one wire format, as the reference keeps one protobuf
    payload schema end to end)."""
    kind_name = _KIND_WIRE_NAMES.get(req.kind)
    if kind_name is None:
        raise ValueError(f"kind {req.kind!r} has no wire envelope")
    body: Dict[str, object] = {
        "eventDate": (req.ts_s + req.ts_ns / 1e9) if req.ts_ns else req.ts_s,
    }
    if req.metadata:
        body["metadata"] = req.metadata
    if req.alternate_id:
        body["alternateId"] = req.alternate_id
    if not req.update_state:
        body["updateState"] = False
    if req.kind == RequestKind.MEASUREMENT:
        body["name"] = req.mtype
        body["value"] = req.value
    elif req.kind == RequestKind.LOCATION:
        body["latitude"] = req.lat
        body["longitude"] = req.lon
        if req.elevation:
            body["elevation"] = req.elevation
    elif req.kind == RequestKind.ALERT:
        body["type"] = req.alert_type
        body["level"] = int(req.alert_level)
        if req.alert_message is not None:
            body["message"] = req.alert_message
    elif req.kind == RequestKind.COMMAND_RESPONSE:
        if req.originating_event is not None:
            body["originatingEventId"] = req.originating_event
        if req.response is not None:
            body["response"] = req.response
    elif req.kind == RequestKind.COMMAND_INVOCATION:
        if req.originating_event is not None:
            body["invocationToken"] = req.originating_event
    elif req.kind == RequestKind.REGISTRATION:
        if req.device_type_token:
            body["deviceTypeToken"] = req.device_type_token
        if req.area_token:
            body["areaToken"] = req.area_token
        if req.customer_token:
            body["customerToken"] = req.customer_token
    elif req.kind in (RequestKind.STREAM_CREATE, RequestKind.STREAM_DATA,
                      RequestKind.STREAM_SEND):
        import base64 as _base64

        body["streamId"] = req.stream_id
        if req.kind == RequestKind.STREAM_CREATE:
            if req.content_type:
                body["contentType"] = req.content_type
        else:
            body["sequenceNumber"] = req.sequence_number
        if req.kind == RequestKind.STREAM_DATA:
            body["data"] = _base64.b64encode(
                req.stream_data or b"").decode("ascii")
    return json.dumps(
        {"deviceToken": req.device_token, "type": kind_name, "request": body},
        separators=(",", ":")).encode("utf-8")


def envelope_fields(doc) -> Tuple[str, str, dict]:
    """Validate one envelope → ``(device_token, type_name, request)``."""
    if not isinstance(doc, dict):
        raise DecodeError("each line must be a JSON object")
    token = doc.get("deviceToken", doc.get("hardwareId"))
    kind = doc.get("type")
    if not token or not kind:
        raise DecodeError("line missing deviceToken/type")
    req = doc.get("request", {})
    if not isinstance(req, dict):
        raise DecodeError("request must be an object")
    return str(token), str(kind), req


class JsonLinesDecoder:
    """Scalar fallback for NDJSON wire batches (and plain envelopes).

    Used where individual :class:`DecodedRequest` objects are needed for
    payloads that may have arrived through the columnar wire edge
    (journal replay, unregistered-row re-decode); the hot path decodes
    the same bytes columnar-ly via
    :func:`sitewhere_tpu.ingest.columnar.decode_json_lines`.
    """

    def __call__(self, payload: bytes) -> List[DecodedRequest]:
        return [
            _decode_one(*envelope_fields(doc))
            for doc in parse_envelopes(payload)
        ]


# Compact binary framing:  magic "SW" | u8 kind | u8 token_len | token |
# f64 ts | kind-specific payload.  The schema-compiled-protobuf analog.
_BIN_MAGIC = b"SW"
_BIN_HEAD = struct.Struct("<2sBB")
_BIN_TS = struct.Struct("<d")
_BIN_MEAS = struct.Struct("<Bd")       # mtype_len follows; value
_BIN_LOC = struct.Struct("<ddd")       # lat, lon, elevation
_BIN_ALERT = struct.Struct("<BB")      # level, type_len


class BinaryDecoder:
    """Compact binary event framing (see module source for layout)."""

    def __call__(self, payload: bytes) -> List[DecodedRequest]:
        try:
            magic, kind, token_len = _BIN_HEAD.unpack_from(payload, 0)
            if magic != _BIN_MAGIC:
                raise DecodeError("bad magic")
            pos = _BIN_HEAD.size
            token = payload[pos : pos + token_len].decode("utf-8")
            pos += token_len
            (ts,) = _BIN_TS.unpack_from(payload, pos)
            pos += _BIN_TS.size
            # range/finiteness checks: wire bytes can encode inf/nan
            # or out-of-int32 floats, which must dead-letter like the
            # JSON paths, never escape as OverflowError (seconds-only:
            # the binary field is DEFINED as epoch seconds, so no
            # millis heuristic)
            ts_s, ts_ns = _ts_from_seconds(ts)
            kind = RequestKind(kind)
            if kind == RequestKind.MEASUREMENT:
                name_len, value = _BIN_MEAS.unpack_from(payload, pos)
                pos += _BIN_MEAS.size
                name = payload[pos : pos + name_len].decode("utf-8")
                return [
                    DecodedRequest(
                        kind=kind, device_token=token, ts_s=ts_s, ts_ns=ts_ns,
                        mtype=name, value=value,
                    )
                ]
            if kind == RequestKind.LOCATION:
                lat, lon, elev = _BIN_LOC.unpack_from(payload, pos)
                return [
                    DecodedRequest(
                        kind=kind, device_token=token, ts_s=ts_s, ts_ns=ts_ns,
                        lat=lat, lon=lon, elevation=elev,
                    )
                ]
            if kind == RequestKind.ALERT:
                level, type_len = _BIN_ALERT.unpack_from(payload, pos)
                pos += _BIN_ALERT.size
                atype = payload[pos : pos + type_len].decode("utf-8")
                return [
                    DecodedRequest(
                        kind=kind, device_token=token, ts_s=ts_s, ts_ns=ts_ns,
                        alert_type=atype, alert_level=level,
                    )
                ]
            if kind == RequestKind.REGISTRATION:
                (dt_len,) = struct.unpack_from("<B", payload, pos)
                pos += 1
                dt_token = payload[pos : pos + dt_len].decode("utf-8")
                return [
                    DecodedRequest(
                        kind=kind, device_token=token, ts_s=ts_s, ts_ns=ts_ns,
                        device_type_token=dt_token or None,
                    )
                ]
            raise DecodeError(f"unsupported binary kind {int(kind)}")
        except (struct.error, UnicodeDecodeError, ValueError,
                OverflowError) as e:
            raise DecodeError(f"bad binary payload: {e}") from e

    @staticmethod
    def encode(req: DecodedRequest) -> bytes:
        """Inverse framing (used by tests and device simulators)."""
        token = req.device_token.encode("utf-8")
        ts = req.ts_s + req.ts_ns / 1e9
        head = _BIN_HEAD.pack(_BIN_MAGIC, int(req.kind), len(token))
        body = head + token + _BIN_TS.pack(ts)
        if req.kind == RequestKind.MEASUREMENT:
            name = (req.mtype or "").encode("utf-8")
            return body + _BIN_MEAS.pack(len(name), req.value) + name
        if req.kind == RequestKind.LOCATION:
            return body + _BIN_LOC.pack(req.lat, req.lon, req.elevation)
        if req.kind == RequestKind.ALERT:
            atype = (req.alert_type or "").encode("utf-8")
            return body + _BIN_ALERT.pack(req.alert_level, len(atype)) + atype
        if req.kind == RequestKind.REGISTRATION:
            dt = (req.device_type_token or "").encode("utf-8")
            return body + struct.pack("<B", len(dt)) + dt
        raise ValueError(f"cannot encode kind {req.kind}")


class CompositeDecoder:
    """Metadata extractor chooses a sub-decoder per payload.

    Reference: ``BinaryCompositeDeviceEventDecoder`` — a metadata extractor
    reads the payload, yields a key (there: the device type), and a keyed
    sub-decoder decodes the body.
    """

    def __init__(
        self,
        extractor: Callable[[bytes], Tuple[str, bytes]],
        decoders: Dict[str, Callable[[bytes], List[DecodedRequest]]],
    ):
        self.extractor = extractor
        self.decoders = decoders

    def __call__(self, payload: bytes) -> List[DecodedRequest]:
        key, body = self.extractor(payload)
        decoder = self.decoders.get(key)
        if decoder is None:
            raise DecodeError(f"no decoder for key {key!r}")
        return decoder(body)
