"""Command execution encoders — bytes on the wire to the device.

Reference: ``service-command-delivery/.../encoding/`` offers a protobuf
encoder whose message schema is built *at runtime from the device type's
command specs* (``ProtobufExecutionEncoder.java`` using
``sitewhere-communication/.../protobuf/DeviceTypeProtoBuilder.java:27`` —
a ``DescriptorProto`` assembled from data), plus JSON and Java-hybrid
encoders.  Here:

- :class:`JsonCommandEncoder` — self-describing JSON (the JSON encoder
  analog; also the fixture format of the reference's MQTT tests).
- :class:`BinaryCommandEncoder` — compact tag/length/varint wire format
  derived from the command's declared parameter list, implementing the
  runtime-schema-from-device-type semantic without a protoc dependency.
  Layout: header ``magic u8, version u8, command-name str, namespace str,
  invocation-token str, param-count varint`` then per parameter
  ``name str, type u8, value`` (varint/zigzag for ints+bool, f64 LE for
  double, length-prefixed UTF-8 for string/bytes).  Strings are
  ``varint length + bytes``.
"""

from __future__ import annotations

import base64
import json
import struct
from typing import List, Tuple

from sitewhere_tpu.commands.model import CommandExecution
from sitewhere_tpu.services.common import ValidationError

_MAGIC = 0xC7
_VERSION = 1
_TYPE_CODES = {"string": 0, "double": 1, "int32": 2, "int64": 3, "bool": 4, "bytes": 5}
_TYPE_NAMES = {v: k for k, v in _TYPE_CODES.items()}


class JsonCommandEncoder:
    """Self-describing JSON encoding of an execution."""

    content_type = "application/json"

    def __call__(self, execution: CommandExecution) -> bytes:
        doc = {
            "invocation": execution.invocation.token,
            "command": execution.command_name,
            "namespace": execution.namespace,
            "parameters": {
                # bytes params ride as base64 (JSON has no binary type).
                name: (
                    base64.b64encode(bytes(value)).decode("ascii")
                    if _type == "bytes"
                    else value
                )
                for (name, _type, value) in execution.parameters
            },
        }
        return json.dumps(doc, sort_keys=True).encode("utf-8")


def _varint(n: int) -> bytes:
    if n < 0:
        raise ValidationError("varint requires non-negative")
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _zigzag(n: int) -> int:
    if not -(1 << 63) <= n < (1 << 63):
        raise ValidationError(f"integer {n} outside int64 range")
    return (n << 1) ^ (n >> 63) if n < 0 else n << 1


def _unzigzag(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    shift = 0
    result = 0
    while True:
        if pos >= len(buf):
            raise ValidationError("truncated varint")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _put_str(s: str) -> bytes:
    raw = s.encode("utf-8")
    return _varint(len(raw)) + raw


def _read_str(buf: bytes, pos: int) -> Tuple[str, int]:
    n, pos = _read_varint(buf, pos)
    if pos + n > len(buf):
        raise ValidationError("truncated string")
    return buf[pos : pos + n].decode("utf-8"), pos + n


class BinaryCommandEncoder:
    """Schema-derived compact binary encoding (see module docstring)."""

    content_type = "application/octet-stream"

    def __call__(self, execution: CommandExecution) -> bytes:
        out = bytearray((_MAGIC, _VERSION))
        out += _put_str(execution.command_name)
        out += _put_str(execution.namespace)
        out += _put_str(execution.invocation.token)
        out += _varint(len(execution.parameters))
        for name, ptype, value in execution.parameters:
            if ptype not in _TYPE_CODES:
                raise ValidationError(f"unknown parameter type {ptype}")
            out += _put_str(name)
            out.append(_TYPE_CODES[ptype])
            if ptype == "string":
                out += _put_str(str(value))
            elif ptype == "bytes":
                raw = bytes(value)
                out += _varint(len(raw)) + raw
            elif ptype == "double":
                out += struct.pack("<d", float(value))
            elif ptype == "bool":
                out += _varint(1 if value else 0)
            else:  # int32 / int64
                out += _varint(_zigzag(int(value)))
        return bytes(out)


def decode_binary_execution(payload: bytes) -> dict:
    """Device-side decode of :class:`BinaryCommandEncoder` output (used by
    tests and the reference-style conformance fixtures)."""
    if len(payload) < 2 or payload[0] != _MAGIC:
        raise ValidationError("bad magic")
    if payload[1] != _VERSION:
        raise ValidationError(f"unsupported version {payload[1]}")
    pos = 2
    command, pos = _read_str(payload, pos)
    namespace, pos = _read_str(payload, pos)
    invocation, pos = _read_str(payload, pos)
    count, pos = _read_varint(payload, pos)
    params = {}
    for _ in range(count):
        name, pos = _read_str(payload, pos)
        if pos >= len(payload):
            raise ValidationError("truncated parameter")
        code = payload[pos]
        pos += 1
        ptype = _TYPE_NAMES.get(code)
        if ptype is None:
            raise ValidationError(f"unknown type code {code}")
        if ptype == "string":
            value, pos = _read_str(payload, pos)
        elif ptype == "bytes":
            n, pos = _read_varint(payload, pos)
            if pos + n > len(payload):
                raise ValidationError("truncated bytes value")
            value = payload[pos : pos + n]
            pos += n
        elif ptype == "double":
            if pos + 8 > len(payload):
                raise ValidationError("truncated double value")
            (value,) = struct.unpack_from("<d", payload, pos)
            pos += 8
        elif ptype == "bool":
            raw, pos = _read_varint(payload, pos)
            value = bool(raw)
        else:
            raw, pos = _read_varint(payload, pos)
            value = _unzigzag(raw)
        params[name] = value
    return {
        "command": command,
        "namespace": namespace,
        "invocation": invocation,
        "parameters": params,
    }
