"""Command invocation/execution records.

Reference model: ``IDeviceCommandInvocation`` (a device event carrying a
command token + parameter values + initiator/target) and
``IDeviceCommandExecution`` (invocation joined with its ``IDeviceCommand``
definition, built by ``ICommandExecutionBuilder``
(``service-command-delivery/.../DefaultCommandProcessingStrategy.java:61-84``).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

from sitewhere_tpu.services.common import mint_token, now_s


@dataclasses.dataclass
class CommandInvocation:
    """A request to run one command on one target assignment."""

    command_token: str
    target_assignment: str
    parameter_values: Dict[str, object] = dataclasses.field(default_factory=dict)
    initiator: str = "REST"          # reference enum: REST/TOOL/SCRIPT/SCHEDULER
    initiator_id: Optional[str] = None
    target: str = "Assignment"
    token: str = dataclasses.field(default_factory=lambda: mint_token("inv"))
    created_s: int = dataclasses.field(default_factory=now_s)
    # Filled during processing:
    device_token: Optional[str] = None
    device_type_token: Optional[str] = None
    tenant: Optional[str] = None


@dataclasses.dataclass
class CommandExecution:
    """Invocation + resolved command definition, ready to encode."""

    invocation: CommandInvocation
    command_name: str
    namespace: str
    # [(name, type, value)] in the command's declared parameter order —
    # the encoding schema is *derived from the device-type data*, the
    # ProtobufMessageBuilder semantic (sitewhere-communication/.../
    # protobuf/DeviceTypeProtoBuilder.java:27).
    parameters: list = dataclasses.field(default_factory=list)
    # the target device's metadata — per-device delivery parameters
    # (e.g. coap_host/coap_port, MetadataCoapParameterExtractor.java)
    device_metadata: dict = dataclasses.field(default_factory=dict)
