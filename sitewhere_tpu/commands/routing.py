"""Command routers: pick a destination for an execution.

Reference: ``ICommandRouter`` impls — ``DeviceTypeMappingCommandRouter``
(device-type token → destination id with a default fallback) and the
scripted router (``service-command-delivery/.../routing/``).  The scripted
variant is any callable registered through
:mod:`sitewhere_tpu.runtime.scripting`.
"""

from __future__ import annotations

from typing import Dict, Optional

from sitewhere_tpu.commands.model import CommandExecution
from sitewhere_tpu.services.common import EntityNotFound


class SingleDestinationRouter:
    """Route everything to the one configured destination."""

    def __init__(self, destination_id: str):
        self.destination_id = destination_id

    def __call__(self, execution: CommandExecution) -> str:
        return self.destination_id


class DeviceTypeMappingRouter:
    """Map device-type token → destination id, with optional default.

    Reference: ``DeviceTypeMappingCommandRouter.java``.
    """

    def __init__(self, mappings: Dict[str, str], default: Optional[str] = None):
        self.mappings = dict(mappings)
        self.default = default

    def __call__(self, execution: CommandExecution) -> str:
        dt = execution.invocation.device_type_token
        dest = self.mappings.get(dt or "", self.default)
        if dest is None:
            raise EntityNotFound(f"no destination mapped for device type {dt}")
        return dest
