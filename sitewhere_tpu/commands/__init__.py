"""Command delivery — invocations out to devices.

Reference: ``service-command-delivery`` (SURVEY.md §2.2, §3.4): enriched
command-invocation events flow through a processing strategy (target
resolver → execution builder), a router picks a destination, and the
destination encodes + parameter-extracts + delivers (MQTT/CoAP/SMS).
Failures land on the undelivered dead-letter topic.
"""

from sitewhere_tpu.commands.model import CommandExecution, CommandInvocation
from sitewhere_tpu.commands.encoders import (
    BinaryCommandEncoder,
    JsonCommandEncoder,
    decode_binary_execution,
)
from sitewhere_tpu.commands.destinations import (
    CallbackDeliveryProvider,
    CoapDeliveryProvider,
    CoapParameterExtractor,
    CommandDestination,
    HttpDeliveryProvider,
    MqttDeliveryProvider,
    SmsParameterExtractor,
    TopicParameterExtractor,
)
from sitewhere_tpu.commands.routing import (
    DeviceTypeMappingRouter,
    SingleDestinationRouter,
)
from sitewhere_tpu.commands.processing import CommandProcessor

__all__ = [
    "CommandExecution",
    "CommandInvocation",
    "BinaryCommandEncoder",
    "JsonCommandEncoder",
    "decode_binary_execution",
    "CallbackDeliveryProvider",
    "CommandDestination",
    "HttpDeliveryProvider",
    "MqttDeliveryProvider",
    "SmsParameterExtractor",
    "TopicParameterExtractor",
    "DeviceTypeMappingRouter",
    "SingleDestinationRouter",
    "CommandProcessor",
]
