"""Command processing strategy: resolve target → build execution → route →
deliver, with undelivered dead-lettering.

Reference: ``DefaultCommandProcessingStrategy.java:61-102`` +
``CommandRoutingLogic.routeCommand:38-55`` (SURVEY.md §3.4).  The reference
consumes enriched command-invocation events from Kafka; here the pipeline
dispatcher hands :class:`CommandProcessor` the command-invocation rows it
diverted (they are also persisted as events, preserving the
invocation-is-an-event model).
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Dict, List, Optional

from sitewhere_tpu.commands.destinations import CommandDestination, DeliveryError
from sitewhere_tpu.commands.model import CommandExecution, CommandInvocation
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent, LifecycleState
from sitewhere_tpu.runtime.tracing import _NOOP_TRACE
from sitewhere_tpu.services.common import EntityNotFound, ServiceError
from sitewhere_tpu.services.device_management import DeviceManagement

logger = logging.getLogger("sitewhere_tpu.commands")

Undelivered = Callable[[CommandInvocation, str], None]


class CommandProcessor(LifecycleComponent):
    """The command-delivery service head.

    ``invoke`` is the full path; partial failures dead-letter through
    ``on_undelivered`` (reference: undelivered-command-invocations topic,
    ``KafkaTopicNaming.java:70-73``).
    """

    def __init__(
        self,
        device_management: DeviceManagement,
        destinations: Optional[List[CommandDestination]] = None,
        router: Optional[Callable[[CommandExecution], str]] = None,
        on_undelivered: Optional[Undelivered] = None,
        metrics=None,
        name: str = "command-processor",
    ):
        super().__init__(name)
        self.dm = device_management
        self.destinations: Dict[str, CommandDestination] = {}
        for d in destinations or []:
            self.add_destination(d)
        self.router = router
        self.on_undelivered = on_undelivered
        self._lock = threading.Lock()
        self.delivered = 0
        self.undelivered = 0
        # registry surface (scraped via /api/instance/metrics.prom)
        self._m_delivered = (metrics.counter("commands.delivered")
                             if metrics is not None else None)
        self._m_undelivered = (metrics.counter("commands.undelivered")
                               if metrics is not None else None)

    def add_destination(self, destination: CommandDestination) -> None:
        replaced = self.destinations.get(destination.destination_id)
        self.destinations[destination.destination_id] = destination
        if replaced is not None and isinstance(replaced.provider, LifecycleComponent):
            # Providers can be shared across destinations (one broker
            # connection, many routes) — only retire one no longer
            # referenced by any destination.
            still_used = any(
                d.provider is replaced.provider for d in self.destinations.values()
            )
            if not still_used:
                if replaced.provider.state == LifecycleState.STARTED:
                    replaced.provider.stop()
                self._children.remove(replaced.provider)
        # Providers with a lifecycle (e.g. MqttDeliveryProvider owning a
        # broker connection) start/stop with the processor — including ones
        # registered after the processor is already running.
        if isinstance(destination.provider, LifecycleComponent):
            if destination.provider not in self._children:  # shared providers register once
                self.add_child(destination.provider)
            if (self.state == LifecycleState.STARTED
                    and destination.provider.state != LifecycleState.STARTED):
                destination.provider.start()

    # -- target resolution + execution build --------------------------------

    def resolve_target(self, invocation: CommandInvocation) -> CommandInvocation:
        """Fill device/type/tenant from the target assignment.

        Reference: ``ICommandTargetResolver`` (invocation → assignments).
        """
        a = self.dm.get_device_assignment(invocation.target_assignment)
        dev = self.dm.get_device(a.device)
        invocation.device_token = dev.token
        invocation.device_type_token = dev.device_type
        invocation.tenant = self.dm.tenant
        return invocation

    def build_execution(self, invocation: CommandInvocation) -> CommandExecution:
        """Join invocation with its command definition.

        Reference: ``ICommandExecutionBuilder.createExecution``.  Parameter
        values are validated against the declared specs: required params
        must be present, unknown params are rejected, and values are coerced
        to their declared types — the schema comes from the device type's
        data, not from compiled code.
        """
        if invocation.device_type_token is None:
            self.resolve_target(invocation)
        dt = self.dm.get_device_type(invocation.device_type_token)
        cmd = dt.commands.get(invocation.command_token)
        if cmd is None:
            raise EntityNotFound(
                f"command {invocation.command_token} not in type {dt.token}"
            )
        declared = {name for (name, _t, _r) in cmd.parameters}
        unknown = set(invocation.parameter_values) - declared
        if unknown:
            raise ServiceError(f"unknown parameters {sorted(unknown)}")
        params = []
        for name, ptype, required in cmd.parameters:
            if name in invocation.parameter_values:
                params.append(
                    (name, ptype, _coerce(ptype, invocation.parameter_values[name]))
                )
            elif required:
                raise ServiceError(f"missing required parameter {name}")
        device_metadata: dict = {}
        if invocation.device_token:
            try:
                device_metadata = dict(
                    self.dm.get_device(invocation.device_token).metadata)
            except Exception:  # metadata is best-effort delivery hints
                device_metadata = {}
        return CommandExecution(
            invocation=invocation,
            command_name=cmd.name,
            namespace=cmd.namespace,
            parameters=params,
            device_metadata=device_metadata,
        )

    # -- routing + delivery --------------------------------------------------

    def route(self, execution: CommandExecution) -> CommandDestination:
        if not self.destinations:
            raise ServiceError("no command destinations registered")
        if self.router is not None:
            dest_id = self.router(execution)
        elif len(self.destinations) == 1:
            dest_id = next(iter(self.destinations))
        else:
            raise ServiceError("multiple destinations but no router configured")
        dest = self.destinations.get(dest_id)
        if dest is None:
            raise EntityNotFound(f"destination {dest_id}")
        return dest

    def invoke(self, invocation: CommandInvocation, trace=None) -> bool:
        """Full delivery path; returns True when the device got the bytes.

        ``trace`` (the originating pipeline plan's trace, when the
        invocation came through the dispatcher's command egress) wraps
        the destination delivery in a ``commands.deliver`` span so a
        retained trace shows the command fan-out leg too."""
        # the span covers resolve/build/route too: a routing or encoding
        # failure must error the span just like a destination failure,
        # or tail sampling would drop the trace of an undelivered command
        span = (trace or _NOOP_TRACE).span("commands.deliver")
        span.tag("command", invocation.command_token)
        with span:
            try:
                self.resolve_target(invocation)
                execution = self.build_execution(invocation)
                dest = self.route(execution)
                span.tag("destination", dest.destination_id)
                dest.deliver(execution)
            except Exception as e:
                # EVERY failure dead-letters (reference: undelivered
                # topic) — including coercion/encoding surprises
                # (ValueError/TypeError), so one bad invocation can never
                # abort a batch.  The exception is handled (not re-raised
                # through __exit__), so flag the span by hand.
                span.error = f"{type(e).__name__}: {e}"
                with self._lock:
                    self.undelivered += 1
                if self._m_undelivered is not None:
                    self._m_undelivered.inc()
                logger.warning("command %s undelivered: %s",
                               invocation.token, e)
                if self.on_undelivered is not None:
                    self.on_undelivered(invocation, str(e))
                return False
        with self._lock:
            self.delivered += 1
        if self._m_delivered is not None:
            self._m_delivered.inc()
        return True

    def invoke_many(self, invocations: List[CommandInvocation],
                    trace=None) -> int:
        """Batch path used by the dispatcher; returns delivered count."""
        return sum(1 for inv in invocations if self.invoke(inv, trace=trace))


_INT_RANGES = {"int32": (-(1 << 31), (1 << 31) - 1), "int64": (-(1 << 63), (1 << 63) - 1)}


def _coerce(ptype: str, value):
    if ptype == "double":
        return float(value)
    if ptype in ("int32", "int64"):
        n = int(value)
        lo, hi = _INT_RANGES[ptype]
        if not lo <= n <= hi:
            raise ServiceError(f"value {n} out of range for {ptype}")
        return n
    if ptype == "bool":
        if isinstance(value, str):
            return value.lower() in ("1", "true", "yes")
        return bool(value)
    if ptype == "bytes":
        return value if isinstance(value, (bytes, bytearray)) else str(value).encode()
    return str(value)
