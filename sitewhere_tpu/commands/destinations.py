"""Command destinations: encoder × parameter extractor × delivery provider.

Reference: ``ICommandDestination`` composes exactly these three SPIs
(``service-command-delivery/.../destination/mqtt/MqttCommandDestination.java``
+ ``MqttParameterExtractor`` computing a per-device topic +
``MqttCommandDeliveryProvider`` publishing).  SMS (Twilio) and CoAP
destinations follow the same shape; here providers without client
libraries in the image are represented by :class:`CallbackDeliveryProvider`
(any callable transport — the SPI point where a Twilio/CoAP client plugs
in).
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Dict, Optional

from sitewhere_tpu.commands.model import CommandExecution
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent
from sitewhere_tpu.services.common import ServiceError

logger = logging.getLogger("sitewhere_tpu.commands")


class DeliveryError(ServiceError):
    """Transport-level delivery failure → undelivered dead-letter."""


class TopicParameterExtractor:
    """Per-device delivery parameters from a topic pattern.

    Reference: ``MqttParameterExtractor`` expands command/system topic
    patterns with the device's hardware id.  Placeholders: ``{device}``,
    ``{tenant}``, ``{type}``.
    """

    def __init__(
        self,
        command_topic: str = "sitewhere/command/{device}",
        system_topic: str = "sitewhere/system/{device}",
    ):
        self.command_topic = command_topic
        self.system_topic = system_topic

    def __call__(self, execution: CommandExecution) -> Dict[str, str]:
        inv = execution.invocation
        fields = {
            "device": inv.device_token or "",
            "tenant": inv.tenant or "",
            "type": inv.device_type_token or "",
        }
        return {
            "topic": self.command_topic.format(**fields),
            "system_topic": self.system_topic.format(**fields),
        }


class MqttDeliveryProvider(LifecycleComponent):
    """Publish encoded executions to a broker topic.

    Reference: ``MqttCommandDeliveryProvider`` over the shared
    ``MqttLifecycleComponent``; here over
    :class:`sitewhere_tpu.ingest.mqtt.MqttClient`.
    """

    def __init__(self, host: str, port: int = 1883, qos: int = 0, client=None):
        super().__init__("mqtt-delivery")
        self.host = host
        self.port = port
        self.qos = qos
        self._client = client  # injectable for tests
        self._lock = threading.Lock()

    def start(self) -> None:
        super().start()
        if self._client is None:
            from sitewhere_tpu.ingest.mqtt import MqttClient

            self._client = MqttClient(self.host, self.port)
            self._client.connect()

    def stop(self) -> None:
        if self._client is not None:
            try:
                self._client.disconnect()
            except Exception:
                pass
            self._client = None
        super().stop()

    def deliver(self, execution: CommandExecution, payload: bytes, params: Dict[str, str]) -> None:
        if self._client is None:
            raise DeliveryError("mqtt delivery provider not started")
        try:
            with self._lock:
                self._client.publish(params["topic"], payload, qos=self.qos)
        except Exception as e:
            raise DeliveryError(f"mqtt publish failed: {e}") from e


class CallbackDeliveryProvider:
    """Deliver through any callable — the plug-in point for transports
    whose client libraries aren't in this image (Twilio SMS, CoAP POST)."""

    def __init__(self, fn: Callable[[CommandExecution, bytes, Dict[str, str]], None]):
        self.fn = fn

    def deliver(self, execution: CommandExecution, payload: bytes, params: Dict[str, str]) -> None:
        try:
            self.fn(execution, payload, params)
        except Exception as e:
            raise DeliveryError(str(e)) from e


class CommandDestination:
    """One named delivery path: encode → extract params → deliver."""

    def __init__(
        self,
        destination_id: str,
        encoder: Callable[[CommandExecution], bytes],
        extractor: Callable[[CommandExecution], Dict[str, str]],
        provider,
    ):
        self.destination_id = destination_id
        self.encoder = encoder
        self.extractor = extractor
        self.provider = provider

    def deliver(self, execution: CommandExecution) -> None:
        payload = self.encoder(execution)
        params = self.extractor(execution)
        self.provider.deliver(execution, payload, params)
