"""Command destinations: encoder × parameter extractor × delivery provider.

Reference: ``ICommandDestination`` composes exactly these three SPIs
(``service-command-delivery/.../destination/mqtt/MqttCommandDestination.java``
+ ``MqttParameterExtractor`` computing a per-device topic +
``MqttCommandDeliveryProvider`` publishing).  CoAP delivery speaks RFC
7252 directly (:class:`CoapDeliveryProvider`); SMS delivery
(``twilio/TwilioCommandDeliveryProvider.java`` — an HTTPS POST of form
fields to a gateway) generalizes to :class:`HttpDeliveryProvider` +
:class:`SmsParameterExtractor`; anything else plugs in through
:class:`CallbackDeliveryProvider`.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Dict, Optional

from sitewhere_tpu.commands.model import CommandExecution
from sitewhere_tpu.runtime import faults
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent
from sitewhere_tpu.runtime.resilience import (
    RetriesExhausted,
    RetryPolicy,
    call_with_retry,
)
from sitewhere_tpu.services.common import ServiceError

logger = logging.getLogger("sitewhere_tpu.commands")


class DeliveryError(ServiceError):
    """Transport-level delivery failure → undelivered dead-letter."""


def _placeholder_fields(execution: CommandExecution) -> Dict[str, str]:
    """The shared ``{device}``/``{tenant}``/``{type}`` pattern vocabulary
    (one place — the MQTT and CoAP extractors must not diverge)."""
    inv = execution.invocation
    return {
        "device": inv.device_token or "",
        "tenant": inv.tenant or "",
        "type": inv.device_type_token or "",
    }


class TopicParameterExtractor:
    """Per-device delivery parameters from a topic pattern.

    Reference: ``MqttParameterExtractor`` expands command/system topic
    patterns with the device's hardware id.  Placeholders: ``{device}``,
    ``{tenant}``, ``{type}``.
    """

    def __init__(
        self,
        command_topic: str = "sitewhere/command/{device}",
        system_topic: str = "sitewhere/system/{device}",
    ):
        self.command_topic = command_topic
        self.system_topic = system_topic

    def __call__(self, execution: CommandExecution) -> Dict[str, str]:
        fields = _placeholder_fields(execution)
        return {
            "topic": self.command_topic.format(**fields),
            "system_topic": self.system_topic.format(**fields),
        }


class MqttDeliveryProvider(LifecycleComponent):
    """Publish encoded executions to a broker topic.

    Reference: ``MqttCommandDeliveryProvider`` over the shared
    ``MqttLifecycleComponent``; here over
    :class:`sitewhere_tpu.ingest.mqtt.MqttClient`.
    """

    def __init__(self, host: str, port: int = 1883, qos: int = 0, client=None):
        super().__init__("mqtt-delivery")
        self.host = host
        self.port = port
        self.qos = qos
        self._client = client  # injectable for tests
        self._lock = threading.Lock()

    def start(self) -> None:
        super().start()
        if self._client is None:
            from sitewhere_tpu.ingest.mqtt import MqttClient

            self._client = MqttClient(self.host, self.port)
            self._client.connect()

    def stop(self) -> None:
        if self._client is not None:
            try:
                self._client.disconnect()
            except Exception:
                pass
            self._client = None
        super().stop()

    def deliver(self, execution: CommandExecution, payload: bytes, params: Dict[str, str]) -> None:
        if self._client is None:
            raise DeliveryError("mqtt delivery provider not started")
        try:
            with self._lock:
                self._client.publish(params["topic"], payload, qos=self.qos)
        except Exception as e:
            raise DeliveryError(f"mqtt publish failed: {e}") from e


class CoapParameterExtractor:
    """Per-device CoAP endpoint parameters.

    Reference: ``destination/coap/MetadataCoapParameterExtractor.java`` —
    host/port come from device metadata with configured defaults; the
    URI path is a pattern (``{device}``/``{tenant}``/``{type}``).
    """

    def __init__(self, default_host: str = "127.0.0.1",
                 default_port: int = 5683,
                 path: str = "commands/{device}",
                 metadata_host_key: str = "coap_host",
                 metadata_port_key: str = "coap_port"):
        self.default_host = default_host
        self.default_port = default_port
        self.path = path
        self.metadata_host_key = metadata_host_key
        self.metadata_port_key = metadata_port_key

    def __call__(self, execution: CommandExecution) -> Dict[str, str]:
        meta = dict(execution.device_metadata or {})
        return {
            "host": str(meta.get(self.metadata_host_key, self.default_host)),
            "port": str(meta.get(self.metadata_port_key, self.default_port)),
            "path": self.path.format(**_placeholder_fields(execution)),
        }


class CoapDeliveryProvider(LifecycleComponent):
    """POST encoded executions to the device's CoAP endpoint (RFC 7252
    confirmable exchange with client-side retransmission).

    Reference: ``destination/coap/CoapCommandDeliveryProvider.java``
    (Californium client).  Here the from-scratch codec in
    :mod:`sitewhere_tpu.ingest.coap` does the framing; CON requests
    retransmit on the RFC schedule (ACK_TIMEOUT 2s doubling,
    MAX_RETRANSMIT 4) and an RST or 4.xx/5.xx response is a delivery
    failure → undelivered dead-letter.
    """

    def __init__(self, ack_timeout_s: float = 2.0, max_retransmit: int = 4,
                 max_wait_s: float = 30.0):
        super().__init__("coap-delivery")
        self.ack_timeout_s = ack_timeout_s
        self.max_retransmit = max_retransmit
        # total exchange budget (caps the RFC 2+4+8+16+32s worst case so
        # one dead endpoint can't stall a command batch for a minute;
        # MAX_TRANSMIT_WAIT-style bound)
        self.max_wait_s = max_wait_s
        self._lock = threading.Lock()
        import random as _random

        # RFC 7252 §4.4: start message ids unpredictably
        self._message_id = _random.SystemRandom().getrandbits(16)

    def _next_mid(self) -> int:
        with self._lock:
            self._message_id = (self._message_id + 1) & 0xFFFF
            return self._message_id

    @staticmethod
    def _check_code(reply) -> None:
        code_class = reply.code >> 5
        if code_class in (4, 5):
            raise DeliveryError(
                f"coap error {code_class}.{reply.code & 0x1F:02d}")

    def deliver(self, execution: CommandExecution, payload: bytes,
                params: Dict[str, str]) -> None:
        import os
        import socket
        import time as _time

        from sitewhere_tpu.ingest import coap

        host = params["host"]
        port = int(params["port"])
        mid = self._next_mid()
        token = os.urandom(4)
        datagram = coap.encode_post(params.get("path", ""), payload,
                                    message_id=mid, confirmable=True,
                                    token=token)
        sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            # connect() makes the kernel drop datagrams from other
            # sources — a stray peer can't fail or fake the exchange
            sock.connect((host, port))
            deadline_total = _time.monotonic() + self.max_wait_s
            timeout = self.ack_timeout_s
            for _ in range(self.max_retransmit + 1):
                try:
                    sock.send(datagram)
                except OSError as e:
                    raise DeliveryError(f"coap send failed: {e}") from e
                attempt_deadline = min(
                    _time.monotonic() + timeout, deadline_total)
                separate = False  # empty ACK seen; response comes later
                while True:
                    remaining = attempt_deadline - _time.monotonic()
                    if remaining <= 0:
                        break
                    sock.settimeout(remaining)
                    try:
                        data = sock.recv(65536)
                    except socket.timeout:
                        break
                    except OSError as e:
                        raise DeliveryError(f"coap recv failed: {e}") from e
                    try:
                        reply = coap.parse_message(data)
                    except coap.CoapError:
                        continue  # garbled datagram: keep waiting
                    if reply.mtype == coap.RST and reply.message_id == mid:
                        raise DeliveryError(
                            "coap endpoint reset the exchange")
                    if reply.mtype == coap.ACK and reply.message_id == mid:
                        if reply.code == 0:
                            # §5.2.2 separate response: the real reply
                            # arrives as a CON/NON with our token — wait
                            # out the remaining total budget
                            separate = True
                            attempt_deadline = deadline_total
                            continue
                        self._check_code(reply)
                        return
                    if reply.mtype in (coap.CON, coap.NON) \
                            and reply.token == token:
                        if reply.mtype == coap.CON:
                            # acknowledge the separate response so the
                            # device stops retransmitting it
                            try:
                                sock.send(coap.encode_message(
                                    coap.CoapMessage(
                                        mtype=coap.ACK, code=0,
                                        message_id=reply.message_id)))
                            except OSError:
                                pass
                        self._check_code(reply)
                        return
                    # unrelated datagram: ignore without consuming the
                    # retransmit budget
                if separate:
                    # request WAS acknowledged — retransmitting would be
                    # a protocol violation; the response just never came
                    raise DeliveryError(
                        "coap separate response never arrived")
                if _time.monotonic() >= deadline_total:
                    break
                timeout *= 2  # RFC 7252 §4.2 exponential backoff
            raise DeliveryError(
                f"coap delivery timed out (budget {self.max_wait_s}s, "
                f"{self.max_retransmit + 1} attempts)")
        finally:
            sock.close()


class SmsParameterExtractor:
    """Per-device SMS parameters (destination phone number).

    Reference: ``destination/sms/SmsParameterExtractor.java`` — the
    phone number comes from device metadata.  Executions for devices
    without one fail delivery (→ undelivered dead-letter), matching the
    reference's null-check.
    """

    def __init__(self, metadata_phone_key: str = "phone_number"):
        self.metadata_phone_key = metadata_phone_key

    def __call__(self, execution: CommandExecution) -> Dict[str, str]:
        meta = dict(execution.device_metadata or {})
        phone = str(meta.get(self.metadata_phone_key, "")).strip()
        fields = _placeholder_fields(execution)
        return {"phone": phone, "device": fields["device"]}


class HttpDeliveryProvider(LifecycleComponent):
    """Deliver encoded executions by POSTing to an HTTP gateway.

    Reference: ``twilio/TwilioCommandDeliveryProvider.java`` — Twilio SMS
    delivery is an HTTPS POST of (from, to, body) form fields to an
    account endpoint.  This provider generalizes that shape: form fields
    come from a template over the extractor's params plus the payload, so
    any SMS/webhook gateway (Twilio-compatible or otherwise) plugs in via
    config rather than code.  A missing required param (e.g. no phone
    number in device metadata) or an HTTP error status raises
    :class:`DeliveryError` → undelivered dead-letter.
    """

    def __init__(
        self,
        url: str,
        field_map: Optional[Dict[str, str]] = None,
        headers: Optional[Dict[str, str]] = None,
        require: tuple = ("phone",),
        timeout_s: float = 10.0,
        name: str = "http-delivery",
    ):
        super().__init__(name)
        from urllib.parse import urlsplit

        parts = urlsplit(url)
        if parts.scheme not in ("http", "https"):
            raise ValueError(f"unsupported gateway scheme: {parts.scheme!r}")
        self._scheme = parts.scheme
        self._netloc = parts.netloc
        self._path = (parts.path or "/") + (
            "?" + parts.query if parts.query else "")
        # each value is a str.format template over params ∪ {payload}
        self.field_map = dict(field_map or {"To": "{phone}", "Body": "{payload}"})
        self.headers = dict(headers or {})
        self.require = tuple(require)
        self.timeout_s = timeout_s

    def deliver(self, execution: CommandExecution, payload: bytes,
                params: Dict[str, str]) -> None:
        import http.client
        from urllib.parse import urlencode

        for key in self.require:
            if not params.get(key):
                raise DeliveryError(
                    f"missing delivery parameter {key!r} "
                    f"(device metadata incomplete)")
        fields = dict(params)
        fields["payload"] = payload.decode("utf-8", "replace")
        body = urlencode(
            {k: v.format(**fields) for k, v in self.field_map.items()})
        headers = {
            "Content-Type": "application/x-www-form-urlencoded",
            **self.headers,
        }
        cls = (http.client.HTTPSConnection if self._scheme == "https"
               else http.client.HTTPConnection)
        conn = cls(self._netloc, timeout=self.timeout_s)
        try:
            conn.request("POST", self._path, body=body.encode(), headers=headers)
            resp = conn.getresponse()
            resp.read()
            # only 2xx is delivery: redirects are not followed, so a 3xx
            # means the gateway never got the command
            if not 200 <= resp.status < 300:
                raise DeliveryError(f"gateway returned {resp.status}")
        except DeliveryError:
            raise
        except Exception as e:
            raise DeliveryError(f"gateway POST failed: {e}") from e
        finally:
            conn.close()


class CallbackDeliveryProvider:
    """Deliver through any callable — the plug-in point for transports
    whose client libraries aren't in this image (Twilio SMS)."""

    def __init__(self, fn: Callable[[CommandExecution, bytes, Dict[str, str]], None]):
        self.fn = fn

    def deliver(self, execution: CommandExecution, payload: bytes, params: Dict[str, str]) -> None:
        try:
            self.fn(execution, payload, params)
        except Exception as e:
            raise DeliveryError(str(e)) from e


class CommandDestination:
    """One named delivery path: encode → extract params → deliver.

    ``retry`` (a :class:`~sitewhere_tpu.runtime.resilience.RetryPolicy`)
    re-attempts TRANSIENT :class:`DeliveryError` s before the processor
    dead-letters the invocation — e.g. an MQTT broker mid-reconnect.
    Default is no retry (CoAP already retransmits on the RFC 7252
    schedule; double-retrying a confirmable exchange would violate it).
    """

    def __init__(
        self,
        destination_id: str,
        encoder: Callable[[CommandExecution], bytes],
        extractor: Callable[[CommandExecution], Dict[str, str]],
        provider,
        retry: Optional[RetryPolicy] = None,
    ):
        self.destination_id = destination_id
        self.encoder = encoder
        self.extractor = extractor
        self.provider = provider
        self.retry = retry

    def _deliver_once(self, execution: CommandExecution, payload: bytes,
                      params: Dict[str, str]) -> None:
        faults.fire("commands.deliver")
        self.provider.deliver(execution, payload, params)

    def deliver(self, execution: CommandExecution) -> None:
        payload = self.encoder(execution)
        params = self.extractor(execution)
        if self.retry is None:
            self._deliver_once(execution, payload, params)
            return
        try:
            call_with_retry(
                lambda: self._deliver_once(execution, payload, params),
                self.retry, retry_on=(DeliveryError,),
                name=f"commands.{self.destination_id}")
        except RetriesExhausted as e:
            # surface the underlying transport failure to the processor's
            # undelivered dead-letter path, with the retry context
            raise DeliveryError(
                f"{e} (last: {e.__cause__})") from e.__cause__
