"""Cross-host RPC fabric (the ``sitewhere-grpc-client`` analog).

The reference moves every cross-service call over gRPC channels with
round-robin demux, JWT/tenant metadata, and near-caches
(SURVEY.md §1 L3).  On TPU, in-slice "calls" are tensor gathers inside
the fused step; RPC survives only at the host boundary (§2.4) — this
package is that boundary: framed-TCP wire (`wire`), multiplexing
channels + replica demux with backoff/failover (`channel`), the
lifecycle server with JWT/tenant/tracing interceptors (`server`), the
instance's domain surface + cached client facades (`services`), and
keyed cross-host event forwarding (`forward`).
"""

from sitewhere_tpu.rpc.channel import (
    ChannelUnavailable,
    DeadlineExpired,
    RpcChannel,
    RpcDemux,
    RpcError,
)
from sitewhere_tpu.rpc.health import PeerHealthTable, PeerState
from sitewhere_tpu.rpc.domains import (
    DOMAIN_SURFACE,
    RemoteDomain,
    attach_remote_domains,
    bind_domains,
    remote_domains,
)
from sitewhere_tpu.rpc.forward import HostForwarder, owning_process, split_lines
from sitewhere_tpu.rpc.server import CallContext, RpcServer
from sitewhere_tpu.rpc.services import RemoteDeviceManagement, bind_instance

__all__ = [
    "CallContext",
    "ChannelUnavailable",
    "DeadlineExpired",
    "HostForwarder",
    "PeerHealthTable",
    "PeerState",
    "RemoteDeviceManagement",
    "RpcChannel",
    "RpcDemux",
    "RpcError",
    "RpcServer",
    "bind_instance",
    "owning_process",
    "split_lines",
]
