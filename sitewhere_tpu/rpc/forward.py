"""Cross-host event routing at the ingest boundary.

Reference scaling story (SURVEY.md §2.4): producers key every Kafka
record by device token (``MicroserviceKafkaProducer.java:106``,
``EventSourcesManager.java:166``), the key hash picks a partition, and
partition leadership pins that device's stream to one broker — giving
per-device ordering and horizontal scale-out.

TPU translation: each HOST in the multi-host mesh owns the shards its
local devices live on (``parallel/multihost.py``).  A device protocol
frontend, however, terminates wherever the device connected — so rows
that belong to another host's shards must cross DCN exactly once, at the
host plane, before entering the owning host's batcher.  That hop is this
module: a stable token hash picks the owning process (the partition-key
analog), local rows go straight to the local dispatcher's columnar wire
intake, and remote rows batch up per peer and ship over the RPC fabric's
binary lane (``events.ingest``) — journaled and processed by the OWNER,
preserving the reference's per-device ordering and at-least-once
placement (the journal lives where the offsets live, exactly like a
partition's log living on its leader).
"""

from __future__ import annotations

import json
import logging
import threading
import time
import zlib
from typing import Dict, List, Optional

from sitewhere_tpu.rpc.channel import ChannelUnavailable, RpcDemux, RpcError
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent

logger = logging.getLogger("sitewhere_tpu.rpc")


def owning_process(device_token: str, n_processes: int) -> int:
    """Stable token → process mapping (Kafka's murmur2-keyed partition
    analog).  crc32 is stable across processes and Python runs — the
    builtin ``hash`` is salted per process and MUST NOT be used here."""
    return zlib.crc32(device_token.encode("utf-8")) % n_processes


def split_lines(payload: bytes, n_processes: int) -> Dict[int, List[bytes]]:
    """Split one NDJSON wire payload into per-owner line lists.

    Lines that don't parse or carry no device token stay with the LOCAL
    intake (owner -1): the local dispatcher's decode path is the one
    that dead-letters them with full diagnostics, matching the
    failed-decode topic contract (``EventSourcesManager.java:189``).
    """
    out: Dict[int, List[bytes]] = {}
    for line in payload.splitlines():
        if not line.strip():
            continue
        owner = -1
        try:
            env = json.loads(line)
            token = (env.get("deviceToken") or env.get("hardwareId")
                     if isinstance(env, dict) else None)
            if token:
                owner = owning_process(str(token), n_processes)
        except (ValueError, UnicodeDecodeError):
            pass
        out.setdefault(owner, []).append(line)
    return out


class HostForwarder(LifecycleComponent):
    """Per-host ingest boundary: local rows in-process, remote rows over
    the fabric, batched per peer under a flush deadline.

    ``peer_demuxes[p]`` is the :class:`RpcDemux` for process ``p``
    (``None`` at the local index).  Buffered remote rows flush when the
    buffer reaches ``max_buffer_bytes`` or ``deadline_ms`` elapses —
    the producer-side linger/batch knobs every Kafka producer has.  A
    peer that stays unreachable past ``max_retries`` flushes dead-letters
    the batch locally (at-least-once preserved: rows are never dropped
    silently, the dead-letter journal is replayable).
    """

    def __init__(self, dispatcher, process_id: int,
                 peer_demuxes: Dict[int, Optional[RpcDemux]],
                 dead_letters=None,
                 deadline_ms: float = 25.0,
                 max_buffer_bytes: int = 1 << 20,
                 max_retries: int = 3,
                 name: str = "host-forwarder"):
        super().__init__(name)
        self.dispatcher = dispatcher
        self.process_id = process_id
        self.n_processes = len(peer_demuxes)
        self.peers = peer_demuxes
        self.dead_letters = dead_letters
        self.deadline_s = deadline_ms / 1000.0
        self.max_buffer_bytes = max_buffer_bytes
        self.max_retries = max_retries
        self._buffers: Dict[int, List[bytes]] = {}
        self._buffer_bytes: Dict[int, int] = {}
        self._buffer_since: Dict[int, float] = {}
        self._lock = threading.Lock()
        self._senders: set = set()
        self._flusher: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.forwarded_rows = 0
        self.local_rows = 0
        self.dead_lettered = 0

    # -- intake --------------------------------------------------------------

    def ingest_payload(self, payload: bytes, source_id: str = "wire") -> int:
        """Route one NDJSON payload.  Returns rows accepted LOCALLY
        (remote rows are accepted by their owner asynchronously)."""
        by_owner = split_lines(payload, self.n_processes)
        accepted = 0
        local: List[bytes] = []
        for owner, lines in by_owner.items():
            if owner in (-1, self.process_id):
                local.extend(lines)
            else:
                self._buffer(owner, lines)
        if local:
            accepted = self.dispatcher.ingest_wire_lines(
                b"\n".join(local), source_id=source_id)
            self.local_rows += accepted
        return accepted

    def _buffer(self, owner: int, lines: List[bytes]) -> None:
        flush_now: Optional[bytes] = None
        with self._lock:
            buf = self._buffers.setdefault(owner, [])
            if not buf:
                self._buffer_since[owner] = time.monotonic()
            buf.extend(lines)
            self._buffer_bytes[owner] = (
                self._buffer_bytes.get(owner, 0)
                + sum(len(l) + 1 for l in lines))
            if self._buffer_bytes[owner] >= self.max_buffer_bytes:
                flush_now = self._drain_locked(owner)
        if flush_now is not None:
            # off the ingest caller's thread: a slow/down peer must not
            # stall the frontend that happened to fill this buffer
            self._send_async(owner, flush_now)

    def _drain_locked(self, owner: int) -> Optional[bytes]:
        lines = self._buffers.pop(owner, None)
        self._buffer_bytes.pop(owner, None)
        self._buffer_since.pop(owner, None)
        if not lines:
            return None
        return b"\n".join(lines)

    # -- egress --------------------------------------------------------------

    def _send_async(self, owner: int, payload: bytes) -> threading.Thread:
        """Each peer's batch ships on its own thread: a down peer's
        connect timeouts + retry backoffs delay only ITS rows, never a
        healthy peer's (Kafka producers isolate brokers the same way)."""

        def run():
            try:
                self._send(owner, payload)
            finally:
                with self._lock:
                    self._senders.discard(threading.current_thread())

        t = threading.Thread(target=run,
                             name=f"{self.name}-send-{owner}", daemon=True)
        with self._lock:
            self._senders.add(t)
        t.start()
        return t

    def _send(self, owner: int, payload: bytes) -> None:
        demux = self.peers.get(owner)
        if demux is None:
            self._dead_letter(owner, payload, "no demux for peer")
            return
        rows = payload.count(b"\n") + 1
        for attempt in range(self.max_retries):
            try:
                body, _ = demux.call(
                    "events.ingest",
                    {"sourceId": f"fwd:{self.process_id}"},
                    attachment=payload)
                self.forwarded_rows += int(body.get("accepted", rows))
                return
            except ChannelUnavailable as e:
                logger.info("forward to %d failed (%d/%d): %s", owner,
                            attempt + 1, self.max_retries, e)
                time.sleep(min(0.1 * (2 ** attempt), 2.0))
            except RpcError as e:
                self._dead_letter(owner, payload, f"peer rejected: {e}")
                return
        self._dead_letter(owner, payload,
                          f"peer {owner} unreachable after "
                          f"{self.max_retries} attempts")

    def _dead_letter(self, owner: int, payload: bytes, reason: str) -> None:
        self.dead_lettered += payload.count(b"\n") + 1
        logger.warning("dead-lettering forward batch for peer %d: %s",
                       owner, reason)
        if self.dead_letters is not None:
            self.dead_letters.append_json({
                "kind": "undeliverable-forward",
                "peer": owner,
                "reason": reason,
                "payload": payload.decode("utf-8", "replace"),
            })

    # -- lifecycle -----------------------------------------------------------

    def _flush_loop(self) -> None:
        while not self._stop.wait(self.deadline_s / 2):
            self.flush(only_expired=True)

    def flush(self, only_expired: bool = False, wait: bool = False) -> None:
        now = time.monotonic()
        to_send: List = []
        with self._lock:
            for owner in list(self._buffers):
                if only_expired and (
                        now - self._buffer_since.get(owner, now)
                        < self.deadline_s):
                    continue
                payload = self._drain_locked(owner)
                if payload is not None:
                    to_send.append((owner, payload))
        threads = [self._send_async(owner, payload)
                   for owner, payload in to_send]
        if wait:
            with self._lock:
                threads = list(self._senders)
            for t in threads:
                t.join(timeout=self.max_retries * 5.0 + 5.0)

    def start(self) -> None:
        self._stop.clear()
        self._flusher = threading.Thread(
            target=self._flush_loop, name=f"{self.name}-flush", daemon=True)
        self._flusher.start()
        super().start()

    def stop(self) -> None:
        self._stop.set()
        if self._flusher is not None:
            self._flusher.join(timeout=5)
            self._flusher = None
        self.flush(wait=True)
        super().stop()

    def metrics(self) -> Dict[str, int]:
        with self._lock:
            pending = sum(len(v) for v in self._buffers.values())
        return {
            "local_rows": self.local_rows,
            "forwarded_rows": self.forwarded_rows,
            "dead_lettered": self.dead_lettered,
            "pending": pending,
        }
