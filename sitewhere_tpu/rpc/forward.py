"""Cross-host event routing at the ingest boundary.

Reference scaling story (SURVEY.md §2.4): producers key every Kafka
record by device token (``MicroserviceKafkaProducer.java:106``,
``EventSourcesManager.java:166``), the key hash picks a partition, and
partition leadership pins that device's stream to one broker — giving
per-device ordering and horizontal scale-out.

TPU translation: each HOST in the multi-host mesh owns the shards its
local devices live on (``parallel/multihost.py``).  A device protocol
frontend, however, terminates wherever the device connected — so rows
that belong to another host's shards must cross DCN exactly once, at the
host plane, before entering the owning host's batcher.  That hop is this
module: a stable token hash picks the owning process (the partition-key
analog), local rows go straight to the local dispatcher's columnar wire
intake, and remote rows ship over the RPC fabric's binary lane
(``events.ingest``) — journaled and processed by the OWNER, preserving
the reference's per-device ordering and at-least-once placement.

Durability of the DCN hop itself: with a ``data_dir``, remote-owned rows
spool to a per-peer :class:`~sitewhere_tpu.ingest.journal.Journal` at
intake and the sender commits its reader offset only AFTER the owner
accepts the batch — the Kafka producer's replicated-ack, as a local
write-ahead spool.  A crash between intake and send replays the spool on
restart; a peer outage retains rows on disk (a down broker's partition
log, exactly).  Without a ``data_dir`` the buffer is memory-only and an
unreachable peer dead-letters after bounded retries — the
fire-and-forget producer profile, for tests and ephemeral toys.
"""

from __future__ import annotations

import json
import logging
import threading
import time
import zlib
from typing import Dict, List, Optional

from sitewhere_tpu.ingest.journal import Journal, JournalReader
from sitewhere_tpu.rpc.channel import ChannelUnavailable, RpcDemux, RpcError
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent
from sitewhere_tpu.runtime.tracing import _NOOP_TRACE

logger = logging.getLogger("sitewhere_tpu.rpc")

SPOOL_POLL_RECORDS = 64    # batches per send drain


def _fmix32(h: int) -> int:
    """murmur3's 32-bit finalizer — the non-linear mixer rendezvous
    weights need.  CRC32 alone is LINEAR: crc(token+s1) and crc(token+s2)
    differ by a constant XOR for equal-length suffixes, so an argmax over
    raw CRCs is decided by those constants, not the token (measured: up
    to 2.3× load skew at P=12).  Two multiply-xorshift rounds destroy
    the linearity; measured skew ≤1.04 and P→P+1 remap ≈1/(P+1)."""
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & 0xFFFFFFFF
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & 0xFFFFFFFF
    h ^= h >> 16
    return h


def owning_process(device_token: str, n_processes: int) -> int:
    """Stable token → process mapping by rendezvous (highest-random-
    weight) hashing: owner = argmax_p fmix32(crc32(token) ^ crc32("|p")).

    Kafka's keyed partitioning analog, but with the elasticity property
    a plain ``hash % P`` lacks: growing the fleet from P to P+1 hosts
    remaps only ~1/(P+1) of devices instead of nearly all of them — the
    partition-reassignment story without a coordinator.  Ties break to
    the smallest process id (first maximum).  crc32 is stable across
    processes and Python runs — the builtin ``hash`` is salted per
    process and MUST NOT be used here.  The native scanner
    (``swwire.c`` ``hrw_owner``) computes the identical function; the
    two MUST stay in lock-step or one device's stream would split
    across hosts.

    VERSIONING: this function IS the cluster's data placement.  Any
    change to it (or to fmix32) remaps devices to different owners, so
    it must roll out as a coordinated full-fleet restart with registry
    re-registration — a mixed-version fleet splits streams exactly like
    a Python/C mismatch would.
    """
    if n_processes <= 1:
        return 0
    base = zlib.crc32(device_token.encode("utf-8"))
    best, best_h = 0, -1
    for p in range(n_processes):
        h = _fmix32(base ^ zlib.crc32(b"|%d" % p))
        if h > best_h:
            best, best_h = p, h
    return best


def split_lines(payload: bytes, n_processes: int) -> Dict[int, List[bytes]]:
    """Split one NDJSON wire payload into per-owner line lists.

    Lines that don't parse or carry no device token stay with the LOCAL
    intake (owner -1): the local dispatcher's decode path is the one
    that dead-letters them with full diagnostics, matching the
    failed-decode topic contract (``EventSourcesManager.java:189``).

    The native scanner (``native/swwire.c`` ``split_owner_lines``) reads
    each line's token without building objects; it bails to this Python
    path on anything whose ownership it could compute differently
    (escaped keys/tokens, non-string tokens) — routing must agree
    byte-for-byte cluster-wide or one device's stream would split
    across hosts.
    """
    # blank-line predicate MUST match the native scanner exactly (space/
    # tab/CR only — bytes.strip() would also drop \x0b/\x0c lines and
    # misalign the zip with the native owner array)
    lines = [ln for ln in payload.split(b"\n") if ln.strip(b" \t\r")]
    out: Dict[int, List[bytes]] = {}

    from sitewhere_tpu.native import load_swwire

    sw = load_swwire()
    if sw is not None and hasattr(sw, "split_owner_lines"):
        owners = sw.split_owner_lines(payload, n_processes)
        # trust the alignment only when the enumerations provably agree —
        # a length mismatch (future predicate drift) must degrade to the
        # Python path, never zip-misroute rows cluster-wide
        if owners is not None and len(owners) == len(lines):
            for line, owner in zip(lines, owners):
                out.setdefault(owner, []).append(line)
            return out

    for line in lines:
        owner = -1
        try:
            env = json.loads(line)
            token = (env.get("deviceToken") or env.get("hardwareId")
                     if isinstance(env, dict) else None)
            if token:
                owner = owning_process(str(token), n_processes)
        except (ValueError, UnicodeDecodeError, RecursionError):
            # RecursionError: pathologically nested line (the native
            # scanner bails those to here at depth 128) — local intake
            pass
        out.setdefault(owner, []).append(line)
    return out


class HostForwarder(LifecycleComponent):
    """Per-host ingest boundary: local rows in-process, remote rows over
    the fabric, batched per peer under a flush deadline.

    ``peer_demuxes[p]`` is the :class:`RpcDemux` for process ``p``
    (``None`` at the local index).  Buffered remote rows flush when the
    buffer reaches ``max_buffer_bytes`` or ``deadline_ms`` elapses —
    the producer-side linger/batch knobs every Kafka producer has.  Each
    peer's sends run on their own thread, so a down peer's connect
    timeouts and backoffs delay only its own rows.  See the module
    docstring for the durable (``data_dir``) vs memory-only contract.
    """

    def __init__(self, dispatcher, process_id: int,
                 peer_demuxes: Dict[int, Optional[RpcDemux]],
                 dead_letters=None,
                 deadline_ms: float = 25.0,
                 max_buffer_bytes: int = 1 << 20,
                 max_retries: int = 3,
                 data_dir: Optional[str] = None,
                 tracer=None,
                 name: str = "host-forwarder"):
        super().__init__(name)
        self.dispatcher = dispatcher
        # span tracing of the DCN hop: each forwarded batch is one trace
        # whose client/server spans share a trace_id across hosts
        self.tracer = tracer
        # local handler for host-plane requests owned by this host
        # (set by the instance; see ingest_host_request)
        self.on_host_request = None
        self.process_id = process_id
        self.n_processes = len(peer_demuxes)
        self.peers = peer_demuxes
        self.dead_letters = dead_letters
        self.deadline_s = deadline_ms / 1000.0
        self.max_buffer_bytes = max_buffer_bytes
        self.max_retries = max_retries
        self._lock = threading.Lock()     # buffers + counters + sender set
        # memory-mode buffers
        self._buffers: Dict[int, List[bytes]] = {}
        self._buffer_bytes: Dict[int, int] = {}
        self._buffer_since: Dict[int, float] = {}
        # durable-mode spools: write-ahead journal per remote peer, one
        # sender at a time per peer (the owner lock keeps the reader's
        # poll→send→commit sequence atomic)
        self._spools: Dict[int, Journal] = {}
        self._spool_readers: Dict[int, JournalReader] = {}
        self._owner_locks: Dict[int, threading.Lock] = {}
        self._spool_since: Dict[int, float] = {}
        self._data_dir = data_dir
        # membership generation: ownership is computed OUTSIDE the lock
        # (split_lines is the expensive part), then buffered atomically
        # against this counter — a membership swap mid-split makes the
        # caller recompute instead of appending under a stale map (and
        # possibly into a spool being retired).  In-flight LOCAL rows
        # count as processed-before-the-change (they complete locally).
        self._member_gen = 0
        if data_dir is not None:
            for p, demux in peer_demuxes.items():
                if demux is None:
                    continue
                # small segments so delivered traffic prunes promptly
                # (the spool's committed prefix has no future readers)
                spool = Journal(data_dir, name=f"forward-{p}",
                                fsync_every=64, segment_bytes=4 << 20)
                self._spools[p] = spool
                self._spool_readers[p] = JournalReader(spool, "sender")
        for p, demux in peer_demuxes.items():
            if demux is not None:
                self._owner_locks[p] = threading.Lock()
        self._senders: set = set()
        self._active_owners: set = set()
        self._flusher: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.forwarded_rows = 0
        self.local_rows = 0
        self.dead_lettered = 0

    @property
    def durable(self) -> bool:
        return bool(self._spools)

    # -- intake --------------------------------------------------------------

    def ingest_payload(self, payload: bytes, source_id: str = "wire",
                       raise_on_decode_error: bool = False) -> int:
        """Route one NDJSON payload.  Returns rows accepted LOCALLY
        (remote rows are accepted by their owner asynchronously).

        ``raise_on_decode_error`` passes through to the local columnar
        decode (see ``PipelineDispatcher.ingest_wire_lines``): malformed
        lines split to the local bucket, so a raw_wire source's failure
        accounting works unchanged in multi-host topologies."""
        while True:
            with self._lock:
                gen, n, pid = (self._member_gen, self.n_processes,
                               self.process_id)
            by_owner = split_lines(payload, n)
            local: List[bytes] = []
            remote: Dict[int, List[bytes]] = {}
            for owner, lines in by_owner.items():
                if owner in (-1, pid):
                    local.extend(lines)
                else:
                    remote[owner] = lines
            if self._route_remote(remote, gen):
                break  # else: membership changed mid-split; recompute
        accepted = 0
        if local:
            accepted = self.dispatcher.ingest_wire_lines(
                b"\n".join(local), source_id=source_id,
                raise_on_decode_error=raise_on_decode_error)
            with self._lock:
                self.local_rows += accepted
        return accepted

    def ingest_requests(self, reqs, payload: bytes = b"",
                        source_id: str = "wire") -> int:
        """Route one payload's already-decoded requests (the protocol
        sources' batch-forward path).  Local rows take the dispatcher's
        columnar intake; remote rows re-encode to the wire envelope and
        ship with the next batch to their owner.  Returns rows accepted
        locally."""
        from sitewhere_tpu.ingest.decoders import encode_envelope

        while True:
            with self._lock:
                gen, n, pid = (self._member_gen, self.n_processes,
                               self.process_id)
            local = []
            remote: Dict[int, List[bytes]] = {}
            for req in reqs:
                owner = owning_process(req.device_token, n)
                if owner == pid:
                    local.append(req)
                else:
                    remote.setdefault(owner, []).append(encode_envelope(req))
            if self._route_remote(remote, gen):
                break  # else: membership changed mid-split; recompute
        if local:
            # A split payload must NOT journal whole here: replaying it
            # would re-ingest the remote rows on the wrong host.  Journal
            # a local-only re-encoding instead (each owner's journal holds
            # exactly its partition's rows — a partition log, precisely).
            if remote:
                payload = b"\n".join(encode_envelope(r) for r in local)
            self.dispatcher.ingest_many(local, payload)
            with self._lock:
                self.local_rows += len(local)
        return len(local)

    def ingest_registration(self, req, payload: bytes = b"") -> None:
        """Registrations route like events: the owning host mints the
        device (dense handles are host-local, so registration MUST land
        where the device's shard lives)."""
        from sitewhere_tpu.ingest.decoders import encode_envelope

        while True:
            with self._lock:
                gen, n, pid = (self._member_gen, self.n_processes,
                               self.process_id)
            owner = owning_process(req.device_token, n)
            if owner == pid:
                self.dispatcher.ingest_registration(req, payload)
                return
            if self._route_remote({owner: [encode_envelope(req)]}, gen):
                return  # else: membership changed; recompute the owner

    def ingest_host_request(self, req, payload: bytes = b"") -> None:
        """Host-plane requests (device streams) route like registrations:
        streams are assignment-scoped and the device model lives on the
        owning host, so the request must land there.  The owner handles
        it through ``on_host_request`` (set by the instance)."""
        from sitewhere_tpu.ingest.decoders import encode_envelope

        while True:
            with self._lock:
                gen, n, pid = (self._member_gen, self.n_processes,
                               self.process_id)
            owner = owning_process(req.device_token, n)
            if owner == pid:
                if self.on_host_request is not None:
                    self.on_host_request(req, payload)
                return
            if self._route_remote({owner: [encode_envelope(req)]}, gen):
                return  # else: membership changed; recompute the owner

    def _route_remote(self, remote: Dict[int, List[bytes]],
                      gen: int) -> bool:
        """Atomically buffer per-owner line lists whose ownership was
        computed under membership generation ``gen``; False when the
        membership changed underneath (caller must recompute owners).
        """
        kicks: List[int] = []
        drops: List[tuple] = []
        with self._lock:
            if gen != self._member_gen:
                return False
            for owner, lines in remote.items():
                if self.durable:
                    # write-ahead: the spool IS the buffer, so a crash
                    # between intake and send replays on restart.  The
                    # append stays under the lock so a membership swap
                    # can never retire a spool with an append in flight.
                    spool = self._spools.get(owner)
                    if spool is None:
                        drops.append((owner, b"\n".join(lines),
                                      "no spool for peer"))
                        continue
                    spool.append(b"\n".join(lines))
                    self._spool_since.setdefault(owner, time.monotonic())
                    if (self._spool_readers[owner].lag
                            >= SPOOL_POLL_RECORDS):
                        kicks.append(owner)
                    continue
                buf = self._buffers.setdefault(owner, [])
                if not buf:
                    self._buffer_since[owner] = time.monotonic()
                buf.extend(lines)
                self._buffer_bytes[owner] = (
                    self._buffer_bytes.get(owner, 0)
                    + sum(len(l) + 1 for l in lines))
                if self._buffer_bytes[owner] >= self.max_buffer_bytes:
                    kicks.append(owner)
        for owner, payload, reason in drops:
            self._dead_letter(owner, payload, reason)
        for owner in kicks:
            # off the ingest caller's thread: a slow/down peer must not
            # stall the frontend that happened to fill this buffer
            self._send_async(owner)
        return True

    def _drain_memory_locked(self, owner: int) -> Optional[bytes]:
        lines = self._buffers.pop(owner, None)
        self._buffer_bytes.pop(owner, None)
        self._buffer_since.pop(owner, None)
        if not lines:
            return None
        return b"\n".join(lines)

    # -- egress --------------------------------------------------------------

    def _send_async(self, owner: int) -> Optional[threading.Thread]:
        """Each peer's batches ship on their own thread: a down peer's
        connect timeouts + retry backoffs delay only ITS rows, never a
        healthy peer's (Kafka producers isolate brokers the same way).
        One sender per owner at a time — a down peer's still-retrying
        sender must not accrete a queue of blocked duplicates behind the
        owner lock on every flusher tick."""
        with self._lock:
            if owner in self._active_owners:
                return None
            self._active_owners.add(owner)

        def run():
            drained_clean = False
            try:
                drained_clean = self._drain_owner(owner)
            finally:
                with self._lock:
                    self._active_owners.discard(owner)
                    self._senders.discard(threading.current_thread())
                    rekick = drained_clean and self._owner_pending_locked(owner)
                # close the check-then-act window: rows buffered between
                # this sender's last empty poll and the discard above
                # would otherwise strand until the next flusher tick
                # (which may never come during stop).  Only after a CLEAN
                # drain — a peer-down exit must wait for the next tick,
                # not hot-loop.
                if rekick:
                    self._send_async(owner)

        t = threading.Thread(target=run,
                             name=f"{self.name}-send-{owner}", daemon=True)
        with self._lock:
            self._senders.add(t)
        t.start()
        return t

    def _owner_pending_locked(self, owner: int) -> bool:
        if self.durable:
            reader = self._spool_readers.get(owner)
            return reader is not None and reader.lag > 0
        return bool(self._buffers.get(owner))

    def _drain_owner(self, owner: int) -> bool:
        """Send everything pending for one peer.  The per-owner lock
        serializes senders so the spool reader's poll→send→commit is
        atomic and batches stay ordered per peer.  Returns True on a
        clean drain (emptied), False when the peer was unreachable."""
        lock = self._owner_locks.get(owner)
        if lock is None:
            return True
        with lock:
            if not self.durable:
                with self._lock:
                    payload = self._drain_memory_locked(owner)
                if payload is not None:
                    delivered = self._deliver(owner, payload)
                    if not delivered:
                        self._dead_letter(
                            owner, payload,
                            f"peer {owner} unreachable after "
                            f"{self.max_retries} attempts")
                return True
            reader = self._spool_readers[owner]
            while True:
                start = reader.position
                records = reader.poll(SPOOL_POLL_RECORDS)
                if not records:
                    with self._lock:
                        self._spool_since.pop(owner, None)
                    return True
                payload = b"\n".join(r for _, r in records)
                if self._deliver(owner, payload):
                    reader.commit()
                    # delivered prefix has no future readers: reclaim
                    # whole segments below the commit (Kafka retention
                    # at the commit frontier)
                    self._spools[owner].prune(reader.committed)
                else:
                    # peer down: rows stay spooled (a down broker's
                    # partition log); rewind and retry next flush cycle
                    reader.seek(start)
                    logger.warning(
                        "peer %d unreachable; %d spooled batches retained",
                        owner, reader.lag)
                    return False

    def _deliver(self, owner: int, payload: bytes) -> bool:
        """One batch to one peer with bounded retries.  True on success
        or non-retryable rejection (which dead-letters); False when the
        peer is unreachable (caller decides: spool-retain or
        dead-letter)."""
        demux = self.peers.get(owner)
        if demux is None:
            self._dead_letter(owner, payload, "no demux for peer")
            return True
        rows = payload.count(b"\n") + 1
        trace = (self.tracer.trace("forward.batch")
                 if self.tracer is not None else _NOOP_TRACE)
        try:
            # root span names the DCN hop; the per-attempt
            # rpc.client.events.ingest spans share its trace_id
            with trace.span("forward.batch") as span:
                span.tag("peer", owner).tag("rows", rows)
                ok = self._deliver_traced(owner, payload, demux, rows, trace)
                if not ok:
                    # exhausted retries: flag the hop so tail sampling
                    # retains the trace of an unreachable peer
                    span.error = "peer unreachable: retries exhausted"
                return ok
        finally:
            trace.end()

    def _deliver_traced(self, owner: int, payload: bytes, demux,
                        rows: int, trace) -> bool:
        for attempt in range(self.max_retries):
            try:
                body, _ = demux.call(
                    "events.ingest",
                    {"sourceId": f"fwd:{self.process_id}"},
                    attachment=payload, trace=trace)
                with self._lock:
                    self.forwarded_rows += int(body.get("accepted", rows))
                return True
            except ChannelUnavailable as e:
                logger.info("forward to %d failed (%d/%d): %s", owner,
                            attempt + 1, self.max_retries, e)
                time.sleep(min(0.1 * (2 ** attempt), 2.0))
            except RpcError as e:
                if getattr(e, "error", "") == "overloaded":
                    # the owner SHED the rows (admission backpressure):
                    # retryable exactly like an unreachable peer — the
                    # spool rewinds and redelivers once it recovers,
                    # never a dead-letter for rows the owner will take
                    logger.info("forward to %d shed by overload "
                                "(%d/%d)", owner, attempt + 1,
                                self.max_retries)
                    time.sleep(min(0.1 * (2 ** attempt), 2.0))
                    continue
                self._dead_letter(owner, payload, f"peer rejected: {e}")
                return True
        return False

    def _dead_letter(self, owner: int, payload: bytes, reason: str) -> None:
        with self._lock:
            self.dead_lettered += payload.count(b"\n") + 1
        logger.warning("dead-lettering forward batch for peer %d: %s",
                       owner, reason)
        if self.dead_letters is not None:
            self.dead_letters.append_json({
                "kind": "undeliverable-forward",
                "peer": owner,
                "reason": reason,
                "payload": payload.decode("utf-8", "replace"),
            })

    # -- lifecycle -----------------------------------------------------------

    def _flush_loop(self) -> None:
        while not self._stop.wait(self.deadline_s / 2):
            self.flush(only_expired=True)

    def _pending_owners(self, only_expired: bool) -> List[int]:
        now = time.monotonic()
        with self._lock:
            if self.durable:
                since = self._spool_since
                owners = [o for o, r in self._spool_readers.items()
                          if r.lag > 0]
            else:
                since = self._buffer_since
                owners = list(self._buffers)
            if only_expired:
                owners = [o for o in owners
                          if now - since.get(o, 0.0) >= self.deadline_s]
        return owners

    def flush(self, only_expired: bool = False, wait: bool = False) -> None:
        for owner in self._pending_owners(only_expired):
            self._send_async(owner)
        if wait:
            with self._lock:
                threads = list(self._senders)
            for t in threads:
                t.join(timeout=self.max_retries * 5.0 + 5.0)

    def start(self) -> None:
        self._stop.clear()
        self._flusher = threading.Thread(
            target=self._flush_loop, name=f"{self.name}-flush", daemon=True)
        self._flusher.start()
        # crash recovery: anything spooled-but-uncommitted from a prior
        # run ships now (replay-from-offset, MicroserviceKafkaConsumer
        # semantics applied to the producer side)
        if self.durable:
            self.flush()
        super().start()

    def stop(self) -> None:
        self._stop.set()
        if self._flusher is not None:
            self._flusher.join(timeout=5)
            self._flusher = None
        self.flush(wait=True)
        for spool in self._spools.values():
            spool.close()
        super().stop()

    def apply_membership(
            self, peer_demuxes: Dict[int, Optional[RpcDemux]],
            process_id: Optional[int] = None) -> int:
        """Adopt a NEW peers map (count may change) and requeue every
        pending row under the new ownership — the consumer-rebalance
        analog: a departed peer's spooled rows go to their new owners
        (or the local intake) instead of waiting for a host that will
        never return.  Returns rows requeued.

        The caller (Instance.apply_membership_change) is responsible for
        record handoff (:mod:`sitewhere_tpu.rpc.migration`); this method
        only moves the in-flight forwarding state.
        """
        # Drain-stop current senders: swap under a quiet fabric so no
        # sender is mid-poll on a spool we are about to requeue.
        with self._lock:
            old_locks = list(self._owner_locks.values())
        for lock in old_locks:
            lock.acquire()
        old_tails: List[tuple] = []  # (reader, journal, end_position)
        try:
            with self._lock:
                pending: List[bytes] = []
                # memory buffers
                for owner in list(self._buffers):
                    payload = self._drain_memory_locked(owner)
                    if payload:
                        pending.append(payload)
                # durable spools: read (but do NOT commit yet) every
                # uncommitted tail — the old offsets advance only after
                # the rows are durably re-placed, so a crash mid-requeue
                # replays them (at-least-once), never loses them
                for owner, reader in list(self._spool_readers.items()):
                    while True:
                        records = reader.poll(SPOOL_POLL_RECORDS)
                        if not records:
                            break
                        pending.extend(r for _, r in records)
                    old_tails.append(
                        (reader, self._spools[owner], reader.position))
                    self._spool_since.pop(owner, None)

                if process_id is not None:
                    self.process_id = process_id
                self.peers = dict(peer_demuxes)
                self.n_processes = len(peer_demuxes)
                # any split computed under the old map must recompute
                # (see _route_remote's generation check)
                self._member_gen += 1
                # spools/locks for the new peer set (existing Journal
                # objects are reused so their files stay continuous)
                new_spools: Dict[int, Journal] = {}
                new_readers: Dict[int, JournalReader] = {}
                new_locks: Dict[int, threading.Lock] = {}
                durable_root = self._data_dir
                for p, demux in peer_demuxes.items():
                    if demux is None:
                        continue
                    new_locks[p] = self._owner_locks.get(
                        p, threading.Lock())
                    if p in self._spools:
                        new_spools[p] = self._spools[p]
                        new_readers[p] = self._spool_readers[p]
                    elif durable_root is not None:
                        spool = Journal(durable_root, name=f"forward-{p}",
                                        fsync_every=64,
                                        segment_bytes=4 << 20)
                        new_spools[p] = spool
                        new_readers[p] = JournalReader(spool, "sender")
                # departed peers' spools close in the finalize phase
                # below, after their rows are durably re-placed
                self._spools = new_spools
                self._spool_readers = new_readers
                self._owner_locks = new_locks
        finally:
            for lock in old_locks:
                lock.release()

        # Re-ingest outside every lock: rows route freshly under the new
        # map (local rows journal in the dispatcher, remote rows spool
        # for their new owners) — durably re-placed BEFORE the old
        # offsets commit below.
        requeued = 0
        for payload in pending:
            requeued += payload.count(b"\n") + 1
            self.ingest_payload(payload, source_id="membership-requeue")
        for reader, journal, end in old_tails:
            try:
                if end > reader.committed:
                    reader.commit(end)
                journal.prune(reader.committed)
                if journal not in self._spools.values():
                    journal.close()  # departed peer's spool, fully drained
            except Exception:
                logger.exception("old spool finalize failed (harmless: "
                                 "its rows replay as duplicates)")
        if requeued:
            logger.info("membership change: requeued %d pending rows "
                        "under the new ownership", requeued)
        self.flush()
        return requeued

    def metrics(self) -> Dict[str, int]:
        with self._lock:
            if self.durable:
                pending = sum(r.lag for r in self._spool_readers.values())
            else:
                pending = sum(len(v) for v in self._buffers.values())
            return {
                "local_rows": self.local_rows,
                "forwarded_rows": self.forwarded_rows,
                "dead_lettered": self.dead_lettered,
                "pending": pending,
                "durable": self.durable,
            }
