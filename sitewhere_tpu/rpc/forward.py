"""Cross-host event routing at the ingest boundary.

Reference scaling story (SURVEY.md §2.4): producers key every Kafka
record by device token (``MicroserviceKafkaProducer.java:106``,
``EventSourcesManager.java:166``), the key hash picks a partition, and
partition leadership pins that device's stream to one broker — giving
per-device ordering and horizontal scale-out.

TPU translation: each HOST in the multi-host mesh owns the shards its
local devices live on (``parallel/multihost.py``).  A device protocol
frontend, however, terminates wherever the device connected — so rows
that belong to another host's shards must cross DCN exactly once, at the
host plane, before entering the owning host's batcher.  That hop is this
module: a stable token hash picks the owning process (the partition-key
analog), local rows go straight to the local dispatcher's columnar wire
intake, and remote rows ship over the RPC fabric's binary lane
(``events.ingest``) — journaled and processed by the OWNER, preserving
the reference's per-device ordering and at-least-once placement.

Durability of the DCN hop itself: with a ``data_dir``, remote-owned rows
spool to a per-peer :class:`~sitewhere_tpu.ingest.journal.Journal` at
intake and the sender commits its reader offset only AFTER the owner
accepts the batch — the Kafka producer's replicated-ack, as a local
write-ahead spool.  A crash between intake and send replays the spool on
restart; a peer outage retains rows on disk (a down broker's partition
log, exactly).  Without a ``data_dir`` the buffer is memory-only and an
unreachable peer dead-letters after bounded retries — the
fire-and-forget producer profile, for tests and ephemeral toys.
"""

from __future__ import annotations

import json
import logging
import threading
import time
import zlib
from typing import Dict, List, Optional

from sitewhere_tpu.ingest.journal import Journal, JournalReader
from sitewhere_tpu.rpc.channel import (
    ChannelUnavailable,
    DeadlineExpired,
    RpcDemux,
    RpcError,
)
from sitewhere_tpu.rpc.health import PeerHealthTable, PeerState
from sitewhere_tpu.runtime import faults
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent
from sitewhere_tpu.runtime.metrics import MetricsRegistry
from sitewhere_tpu.runtime.overload import (
    OverloadShed,
    OverloadState,
    PriorityClass,
    classify_event_type,
)
from sitewhere_tpu.runtime.tracing import _NOOP_TRACE

logger = logging.getLogger("sitewhere_tpu.rpc")

SPOOL_POLL_RECORDS = 64    # batches per send drain

# delivery outcomes (_deliver): terminal-or-delivered / retain-and-pace
_OK = "ok"        # delivered, or non-retryable rejection (dead-lettered)
_DOWN = "down"    # unreachable / deadline lapsed: rows retained
_SHED = "shed"    # the owner's admission refused: rows retained, paced

# payload markers that exempt the device-facing owner-pressure gate: a
# payload that MIGHT carry an alert / command response is always
# forwarded (the owner's own admission never sheds CRITICAL) — false
# positives only skip the gate, never drop rows
_CRITICAL_MARKERS = (b"alert", b"acknowledge", b"commandresponse")


def _has_critical_marker(payload: bytes) -> bool:
    low = payload.lower()
    return any(m in low for m in _CRITICAL_MARKERS)


def _fmix32(h: int) -> int:
    """murmur3's 32-bit finalizer — the non-linear mixer rendezvous
    weights need.  CRC32 alone is LINEAR: crc(token+s1) and crc(token+s2)
    differ by a constant XOR for equal-length suffixes, so an argmax over
    raw CRCs is decided by those constants, not the token (measured: up
    to 2.3× load skew at P=12).  Two multiply-xorshift rounds destroy
    the linearity; measured skew ≤1.04 and P→P+1 remap ≈1/(P+1)."""
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & 0xFFFFFFFF
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & 0xFFFFFFFF
    h ^= h >> 16
    return h


def owning_process(device_token: str, n_processes: int) -> int:
    """Stable token → process mapping by rendezvous (highest-random-
    weight) hashing: owner = argmax_p fmix32(crc32(token) ^ crc32("|p")).

    Kafka's keyed partitioning analog, but with the elasticity property
    a plain ``hash % P`` lacks: growing the fleet from P to P+1 hosts
    remaps only ~1/(P+1) of devices instead of nearly all of them — the
    partition-reassignment story without a coordinator.  Ties break to
    the smallest process id (first maximum).  crc32 is stable across
    processes and Python runs — the builtin ``hash`` is salted per
    process and MUST NOT be used here.  The native scanner
    (``swwire.c`` ``hrw_owner``) computes the identical function; the
    two MUST stay in lock-step or one device's stream would split
    across hosts.

    VERSIONING: this function IS the cluster's data placement.  Any
    change to it (or to fmix32) remaps devices to different owners, so
    it must roll out as a coordinated full-fleet restart with registry
    re-registration — a mixed-version fleet splits streams exactly like
    a Python/C mismatch would.
    """
    if n_processes <= 1:
        return 0
    base = zlib.crc32(device_token.encode("utf-8"))
    best, best_h = 0, -1
    for p in range(n_processes):
        h = _fmix32(base ^ zlib.crc32(b"|%d" % p))
        if h > best_h:
            best, best_h = p, h
    return best


def split_lines(payload: bytes, n_processes: int) -> Dict[int, List[bytes]]:
    """Split one NDJSON wire payload into per-owner line lists.

    Lines that don't parse or carry no device token stay with the LOCAL
    intake (owner -1): the local dispatcher's decode path is the one
    that dead-letters them with full diagnostics, matching the
    failed-decode topic contract (``EventSourcesManager.java:189``).

    The native scanner (``native/swwire.c`` ``split_owner_lines``) reads
    each line's token without building objects; it bails to this Python
    path on anything whose ownership it could compute differently
    (escaped keys/tokens, non-string tokens) — routing must agree
    byte-for-byte cluster-wide or one device's stream would split
    across hosts.
    """
    # blank-line predicate MUST match the native scanner exactly (space/
    # tab/CR only — bytes.strip() would also drop \x0b/\x0c lines and
    # misalign the zip with the native owner array)
    lines = [ln for ln in payload.split(b"\n") if ln.strip(b" \t\r")]
    out: Dict[int, List[bytes]] = {}

    from sitewhere_tpu.native import load_swwire

    sw = load_swwire()
    if sw is not None and hasattr(sw, "split_owner_lines"):
        owners = sw.split_owner_lines(payload, n_processes)
        # trust the alignment only when the enumerations provably agree —
        # a length mismatch (future predicate drift) must degrade to the
        # Python path, never zip-misroute rows cluster-wide
        if owners is not None and len(owners) == len(lines):
            for line, owner in zip(lines, owners):
                out.setdefault(owner, []).append(line)
            return out

    for line in lines:
        owner = -1
        try:
            env = json.loads(line)
            token = (env.get("deviceToken") or env.get("hardwareId")
                     if isinstance(env, dict) else None)
            if token:
                owner = owning_process(str(token), n_processes)
        except (ValueError, UnicodeDecodeError, RecursionError):
            # RecursionError: pathologically nested line (the native
            # scanner bails those to here at depth 128) — local intake
            pass
        out.setdefault(owner, []).append(line)
    return out


class HostForwarder(LifecycleComponent):
    """Per-host ingest boundary: local rows in-process, remote rows over
    the fabric, batched per peer under a flush deadline.

    ``peer_demuxes[p]`` is the :class:`RpcDemux` for process ``p``
    (``None`` at the local index).  Buffered remote rows flush when the
    buffer reaches ``max_buffer_bytes`` or ``deadline_ms`` elapses —
    the producer-side linger/batch knobs every Kafka producer has.  Each
    peer's sends run on their own thread, so a down peer's connect
    timeouts and backoffs delay only its own rows.  See the module
    docstring for the durable (``data_dir``) vs memory-only contract.

    Fleet health (``rpc/health.py``): the forwarder runs the
    ``fleet.heartbeat`` loop and keeps a :class:`PeerHealthTable` fed
    by heartbeats, per-call response piggybacks, and its own send
    failures.  A SUSPECT/DOWN/SHEDDING peer's sender parks its spool
    and sends ONE paced probe batch per interval (honoring the peer's
    Retry-After hint) instead of hammering full drains; a purely
    remote-owned payload whose owners advertise SHEDDING is refused at
    intake with the owner's hint so the device-facing edge (429 / 5.03
    / MQTT pause) reflects fleet-wide pressure.
    """

    def __init__(self, dispatcher, process_id: int,
                 peer_demuxes: Dict[int, Optional[RpcDemux]],
                 dead_letters=None,
                 deadline_ms: float = 25.0,
                 max_buffer_bytes: int = 1 << 20,
                 max_retries: int = 3,
                 data_dir: Optional[str] = None,
                 tracer=None,
                 metrics=None,
                 overload=None,
                 health: Optional[PeerHealthTable] = None,
                 heartbeat_interval_s: float = 0.5,
                 call_timeout_s: float = 10.0,
                 max_retained_bytes: Optional[int] = None,
                 device_unhealthy=None,
                 device_unhealthy_shards=None,
                 name: str = "host-forwarder"):
        super().__init__(name)
        self.dispatcher = dispatcher
        # span tracing of the DCN hop: each forwarded batch is one trace
        # whose client/server spans share a trace_id across hosts
        self.tracer = tracer
        # local handler for host-plane requests owned by this host
        # (set by the instance; see ingest_host_request)
        self.on_host_request = None
        self.process_id = process_id
        self.n_processes = len(peer_demuxes)
        self.peers = peer_demuxes
        self.dead_letters = dead_letters
        self.deadline_s = deadline_ms / 1000.0
        self.max_buffer_bytes = max_buffer_bytes
        self.max_retries = max_retries
        # this host's own overload controller: the heartbeat body and
        # response piggyback advertise ITS state to peers
        self.overload = overload
        self.heartbeat_interval_s = float(heartbeat_interval_s)
        # per-call budget: propagated as the deadline-ms header so the
        # owner rejects work this sender has already given up on
        self.call_timeout_s = float(call_timeout_s)
        # memory-mode retention bound for overload-shed rows (satellite
        # of the at-least-once contract: the owner WILL take them after
        # recovery, so they buffer instead of dead-lettering — until
        # this bound forces a replayable forward-shed drop)
        self.max_retained_bytes = (int(max_retained_bytes)
                                   if max_retained_bytes is not None
                                   else 4 * max_buffer_bytes)
        # restart epoch for the fleet heartbeat: a rebooted sender's
        # first beat replaces peers' stale view of us atomically
        self.incarnation = int(time.time())
        # zero-arg callable: this host's hung-step watchdog flag
        # (dispatcher.device_unhealthy) — advertised on every beat so
        # peers park forwards while OUR device tier is wedged
        self.device_unhealthy = device_unhealthy
        # zero-arg callable, mesh refinement of the flag above
        # (dispatcher.device_unhealthy_shards): which mesh shards the
        # wedge attributes to.  Empty = whole tier (single-chip, or an
        # unattributable wedge) — peers keep the conservative park.
        self.device_unhealthy_shards = device_unhealthy_shards
        # instance-scoped registry by default (a PRIVATE one when none
        # is injected — forwarders are per-instance objects and their
        # counters must never bleed across co-resident instances)
        self._metrics = metrics if metrics is not None else MetricsRegistry()
        # the forward.* family (analysis/metric_names.py): the canonical
        # observable surface — the legacy local_rows/forwarded_rows/
        # dead_lettered attributes are read-only VIEWS of these (one
        # source of truth; see the properties below)
        self._m_local = self._metrics.counter("forward.local_rows")
        self._m_forwarded = self._metrics.counter("forward.forwarded_rows")
        self._m_dead = self._metrics.counter("forward.dead_lettered")
        self._m_pending = self._metrics.gauge("forward.pending_rows")
        self._m_attempts = self._metrics.counter("forward.send_attempts")
        self._m_probes = self._metrics.counter("forward.probe_sends")
        self._m_shed_retained = self._metrics.counter(
            "forward.shed_retained")
        self._m_edge = self._metrics.counter("forward.edge_refusals")
        self._m_hb_sent = self._metrics.counter("forward.heartbeats_sent")
        self._m_hb_fail = self._metrics.counter("forward.heartbeats_failed")
        self._m_deadline = self._metrics.counter("forward.deadline_expired")
        # the peer health table (rpc/health.py): parked senders, paced
        # probes, and the device-facing owner-pressure gate all read it
        remote = [p for p, d in peer_demuxes.items() if d is not None]
        if health is None:
            if self.heartbeat_interval_s > 0:
                health = PeerHealthTable(
                    remote,
                    heartbeat_interval_s=self.heartbeat_interval_s,
                    metrics=self._metrics)
            else:
                # no heartbeat loop: silence means nothing (only
                # forward traffic refreshes last_heard), so the
                # interval detector must not declare idle peers dead —
                # the send-failure streak remains the liveness signal
                health = PeerHealthTable(
                    remote, metrics=self._metrics,
                    suspect_after_s=float("inf"),
                    down_after_s=float("inf"))
        self.health = health
        # response piggyback: every reply from peer p (any method, error
        # frames included) refreshes p's overload state in the table
        self._bind_piggyback(peer_demuxes)
        self._heartbeater: Optional[threading.Thread] = None
        self._lock = threading.Lock()     # buffers + counters + sender set
        # memory-mode buffers
        self._buffers: Dict[int, List[bytes]] = {}
        self._buffer_bytes: Dict[int, int] = {}
        self._buffer_since: Dict[int, float] = {}
        # durable-mode spools: write-ahead journal per remote peer, one
        # sender at a time per peer (the owner lock keeps the reader's
        # poll→send→commit sequence atomic)
        self._spools: Dict[int, Journal] = {}
        self._spool_readers: Dict[int, JournalReader] = {}
        self._owner_locks: Dict[int, threading.Lock] = {}
        self._spool_since: Dict[int, float] = {}
        # rows retained per owner in durable spools (records are
        # multi-row payloads; see the boot-time count below)
        self._pending_rows: Dict[int, int] = {}
        # consecutive deadline expiries per owner: a healthy-looking
        # peer rejecting every call pre-dispatch usually means host
        # clock skew larger than the call budget — surfaced loudly
        self._deadline_streaks: Dict[int, int] = {}
        self._data_dir = data_dir
        # membership generation: ownership is computed OUTSIDE the lock
        # (split_lines is the expensive part), then buffered atomically
        # against this counter — a membership swap mid-split makes the
        # caller recompute instead of appending under a stale map (and
        # possibly into a spool being retired).  In-flight LOCAL rows
        # count as processed-before-the-change (they complete locally).
        self._member_gen = 0
        if data_dir is not None:
            for p, demux in peer_demuxes.items():
                if demux is None:
                    continue
                # small segments so delivered traffic prunes promptly
                # (the spool's committed prefix has no future readers)
                spool = Journal(data_dir, name=f"forward-{p}",
                                fsync_every=64, segment_bytes=4 << 20)
                self._spools[p] = spool
                self._spool_readers[p] = JournalReader(spool, "sender")
                # ROW-accurate backlog: spool records are multi-row
                # joined payloads, so reader.lag (records) would
                # under-report; count the surviving uncommitted tail
                # once at boot, then track appends/commits
                self._pending_rows[p] = sum(
                    payload.count(b"\n") + 1 for _, payload in
                    spool.scan(self._spool_readers[p].committed))
        for p, demux in peer_demuxes.items():
            if demux is not None:
                self._owner_locks[p] = threading.Lock()
        self._senders: set = set()
        self._active_owners: set = set()
        self._flusher: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # legacy counter surface: read-only views of the registry counters
    # (one source of truth — an increment site cannot forget one half)

    @property
    def local_rows(self) -> int:
        return int(self._m_local.value)

    @property
    def forwarded_rows(self) -> int:
        return int(self._m_forwarded.value)

    @property
    def dead_lettered(self) -> int:
        return int(self._m_dead.value)

    @property
    def durable(self) -> bool:
        return bool(self._spools)

    def _bind_piggyback(self, peer_demuxes) -> None:
        """Tap every peer demux's response headers into the health
        table (the per-call overload piggyback intake)."""
        for p, demux in peer_demuxes.items():
            if demux is not None and hasattr(demux, "set_header_listener"):
                demux.set_header_listener(
                    lambda h, _p=p: self.health.observe_piggyback(_p, h))

    # -- intake --------------------------------------------------------------

    def _edge_backpressure(self, remote, n_local: int,
                           critical_fn) -> None:
        """Device-facing owner-pressure gate: a payload whose rows are
        ALL remote-owned by SHEDDING+ owners is refused with the
        OWNER's Retry-After hint — the receiving transport turns it
        into HTTP 429 / CoAP 5.03 / an MQTT pause, so fleet-wide
        backpressure reaches the device that can act on it.

        Refusal is whole-payload (the intake-shed granularity): nothing
        was accepted or buffered, the device owns the retry, and the
        owner's admission re-decides then.  Payloads with any local
        share, any possibly-CRITICAL row, or any healthy owner forward
        as usual — the spool absorbs the pressure instead."""
        if n_local or not remote:
            return
        worst: Optional[tuple] = None
        for owner in remote:
            pressure = self.health.owner_pressure(owner)
            if pressure is None:
                return          # at least one owner can take traffic
            if worst is None or pressure[0] > worst[0]:
                worst = pressure
        # the (comparatively pricey) critical scan runs LAST — only for
        # a purely remote payload whose owners all advertise SHEDDING
        if critical_fn():
            return
        state, retry_after_s = worst
        self._m_edge.inc()
        raise OverloadShed(
            PriorityClass.TELEMETRY,
            OverloadState(min(int(state), int(OverloadState.EMERGENCY))),
            retry_after_s,
            reason=f"remote owner(s) {sorted(remote)} shedding")

    def ingest_payload(self, payload: bytes, source_id: str = "wire",
                       raise_on_decode_error: bool = False) -> int:
        """Route one NDJSON payload.  Returns rows accepted LOCALLY
        (remote rows are accepted by their owner asynchronously).

        ``raise_on_decode_error`` passes through to the local columnar
        decode (see ``PipelineDispatcher.ingest_wire_lines``): malformed
        lines split to the local bucket, so a raw_wire source's failure
        accounting works unchanged in multi-host topologies."""
        while True:
            with self._lock:
                gen, n, pid = (self._member_gen, self.n_processes,
                               self.process_id)
            by_owner = split_lines(payload, n)
            local: List[bytes] = []
            remote: Dict[int, List[bytes]] = {}
            for owner, lines in by_owner.items():
                if owner in (-1, pid):
                    local.extend(lines)
                else:
                    remote[owner] = lines
            # BEFORE buffering anything: a purely-remote payload whose
            # owners advertise SHEDDING is refused outright with the
            # owners' hint (the device retries; nothing duplicates)
            self._edge_backpressure(
                remote, len(local),
                lambda p=payload: _has_critical_marker(p))
            if self._route_remote(remote, gen):
                break  # else: membership changed mid-split; recompute
        accepted = 0
        if local:
            accepted = self.dispatcher.ingest_wire_lines(
                b"\n".join(local), source_id=source_id,
                raise_on_decode_error=raise_on_decode_error)
            self._m_local.inc(accepted)
        return accepted

    def ingest_requests(self, reqs, payload: bytes = b"",
                        source_id: str = "wire") -> int:
        """Route one payload's already-decoded requests (the protocol
        sources' batch-forward path).  Local rows take the dispatcher's
        columnar intake; remote rows re-encode to the wire envelope and
        ship with the next batch to their owner.  Returns rows accepted
        locally."""
        from sitewhere_tpu.ingest.decoders import encode_envelope

        while True:
            with self._lock:
                gen, n, pid = (self._member_gen, self.n_processes,
                               self.process_id)
            local = []
            remote: Dict[int, List[bytes]] = {}
            critical_possible = False
            for req in reqs:
                owner = owning_process(req.device_token, n)
                if (req.event_type is None
                        or classify_event_type(int(req.event_type))
                        != PriorityClass.TELEMETRY):
                    critical_possible = True   # decoded: classify exactly
                if owner == pid:
                    local.append(req)
                else:
                    remote.setdefault(owner, []).append(encode_envelope(req))
            self._edge_backpressure(remote, len(local),
                                    lambda c=critical_possible: c)
            if self._route_remote(remote, gen):
                break  # else: membership changed mid-split; recompute
        if local:
            # A split payload must NOT journal whole here: replaying it
            # would re-ingest the remote rows on the wrong host.  Journal
            # a local-only re-encoding instead (each owner's journal holds
            # exactly its partition's rows — a partition log, precisely).
            if remote:
                payload = b"\n".join(encode_envelope(r) for r in local)
            self.dispatcher.ingest_many(local, payload)
            self._m_local.inc(len(local))
        return len(local)

    def ingest_registration(self, req, payload: bytes = b"") -> None:
        """Registrations route like events: the owning host mints the
        device (dense handles are host-local, so registration MUST land
        where the device's shard lives)."""
        from sitewhere_tpu.ingest.decoders import encode_envelope

        while True:
            with self._lock:
                gen, n, pid = (self._member_gen, self.n_processes,
                               self.process_id)
            owner = owning_process(req.device_token, n)
            if owner == pid:
                self.dispatcher.ingest_registration(req, payload)
                return
            if self._route_remote({owner: [encode_envelope(req)]}, gen):
                return  # else: membership changed; recompute the owner

    def ingest_host_request(self, req, payload: bytes = b"") -> None:
        """Host-plane requests (device streams) route like registrations:
        streams are assignment-scoped and the device model lives on the
        owning host, so the request must land there.  The owner handles
        it through ``on_host_request`` (set by the instance)."""
        from sitewhere_tpu.ingest.decoders import encode_envelope

        while True:
            with self._lock:
                gen, n, pid = (self._member_gen, self.n_processes,
                               self.process_id)
            owner = owning_process(req.device_token, n)
            if owner == pid:
                if self.on_host_request is not None:
                    self.on_host_request(req, payload)
                return
            if self._route_remote({owner: [encode_envelope(req)]}, gen):
                return  # else: membership changed; recompute the owner

    def _route_remote(self, remote: Dict[int, List[bytes]],
                      gen: int) -> bool:
        """Atomically buffer per-owner line lists whose ownership was
        computed under membership generation ``gen``; False when the
        membership changed underneath (caller must recompute owners).
        """
        kicks: List[int] = []
        drops: List[tuple] = []
        with self._lock:
            if gen != self._member_gen:
                return False
            for owner, lines in remote.items():
                if self.durable:
                    # write-ahead: the spool IS the buffer, so a crash
                    # between intake and send replays on restart.  The
                    # append stays under the lock so a membership swap
                    # can never retire a spool with an append in flight.
                    spool = self._spools.get(owner)
                    if spool is None:
                        drops.append((owner, b"\n".join(lines),
                                      "no spool for peer"))
                        continue
                    spool.append(b"\n".join(lines))
                    self._pending_rows[owner] = (
                        self._pending_rows.get(owner, 0) + len(lines))
                    self._spool_since.setdefault(owner, time.monotonic())
                    if (self._spool_readers[owner].lag
                            >= SPOOL_POLL_RECORDS):
                        kicks.append(owner)
                    continue
                buf = self._buffers.setdefault(owner, [])
                if not buf:
                    self._buffer_since[owner] = time.monotonic()
                buf.extend(lines)
                self._buffer_bytes[owner] = (
                    self._buffer_bytes.get(owner, 0)
                    + sum(len(l) + 1 for l in lines))
                if self._buffer_bytes[owner] >= self.max_buffer_bytes:
                    kicks.append(owner)
        for owner, payload, reason in drops:
            self._dead_letter(owner, payload, reason)
        for owner in kicks:
            # off the ingest caller's thread: a slow/down peer must not
            # stall the frontend that happened to fill this buffer
            self._send_async(owner)
        return True

    def _drain_memory_locked(self, owner: int) -> Optional[bytes]:
        lines = self._buffers.pop(owner, None)
        self._buffer_bytes.pop(owner, None)
        self._buffer_since.pop(owner, None)
        if not lines:
            return None
        return b"\n".join(lines)

    # -- egress --------------------------------------------------------------

    def _send_async(self, owner: int) -> Optional[threading.Thread]:
        """Each peer's batches ship on their own thread: a down peer's
        connect timeouts + retry backoffs delay only ITS rows, never a
        healthy peer's (Kafka producers isolate brokers the same way).
        One sender per owner at a time — a down peer's still-retrying
        sender must not accrete a queue of blocked duplicates behind the
        owner lock on every flusher tick."""
        with self._lock:
            if owner in self._active_owners:
                return None
            self._active_owners.add(owner)

        def run():
            drained_clean = False
            try:
                drained_clean = self._drain_owner(owner)
            finally:
                with self._lock:
                    self._active_owners.discard(owner)
                    self._senders.discard(threading.current_thread())
                    rekick = drained_clean and self._owner_pending_locked(owner)
                # close the check-then-act window: rows buffered between
                # this sender's last empty poll and the discard above
                # would otherwise strand until the next flusher tick
                # (which may never come during stop).  Only after a CLEAN
                # drain — a peer-down exit must wait for the next tick,
                # not hot-loop.
                if rekick:
                    self._send_async(owner)

        t = threading.Thread(target=run,
                             name=f"{self.name}-send-{owner}", daemon=True)
        with self._lock:
            self._senders.add(t)
        t.start()
        return t

    def _owner_pending_locked(self, owner: int) -> bool:
        if self.durable:
            reader = self._spool_readers.get(owner)
            return reader is not None and reader.lag > 0
        return bool(self._buffers.get(owner))

    def _drain_owner(self, owner: int) -> bool:
        """Send everything pending for one peer.  The per-owner lock
        serializes senders so the spool reader's poll→send→commit is
        atomic and batches stay ordered per peer.  Returns True on a
        clean drain (emptied), False when rows were retained (peer
        unreachable / shedding / parked).

        Health gate: a SUSPECT/DOWN/SHEDDING peer's sender PARKS — at
        most one paced probe batch per probe interval instead of a full
        drain — so an unhealthy peer costs the fleet a bounded trickle,
        not a retry storm.  A delivered probe whose piggyback shows
        recovery resumes the full drain in the same pass."""
        lock = self._owner_locks.get(owner)
        if lock is None:
            return True
        with lock:
            probing = False
            if not self.health.can_drain(owner):
                if not self.health.probe_due(owner):
                    return False     # parked: rows stay put, no attempt
                probing = True
                self._m_probes.inc()
            if not self.durable:
                with self._lock:
                    payload = self._drain_memory_locked(owner)
                if payload is not None:
                    outcome = self._deliver(owner, payload, probe=probing)
                    if outcome == _SHED:
                        # the owner WILL take these rows after recovery:
                        # keep them buffered (bounded) instead of
                        # dead-lettering work that isn't dead
                        self._retain_shed(owner, payload)
                        return False
                    if outcome == _DOWN:
                        if self._stop.is_set():
                            # stopping: fire-and-forget mode records the
                            # loss rather than silently vanishing with
                            # the process
                            self._dead_letter(
                                owner, payload,
                                f"peer {owner} unreachable at stop")
                            return True
                        self._dead_letter(
                            owner, payload,
                            f"peer {owner} unreachable after "
                            f"{self.max_retries} attempts")
                return True
            reader = self._spool_readers[owner]
            while True:
                start = reader.position
                records = reader.poll(1 if probing else SPOOL_POLL_RECORDS)
                if not records:
                    with self._lock:
                        self._spool_since.pop(owner, None)
                    return True
                # kill window under test: rows polled (reader.position
                # advanced in memory) but the peer has not acked — a
                # SIGKILL here must replay this tail from the committed
                # offset on restart (crashrec_bench crash.mid_forward)
                faults.crosspoint("crash.mid_forward")
                payload = b"\n".join(r for _, r in records)
                outcome = self._deliver(owner, payload, probe=probing)
                if outcome == _OK:
                    reader.commit()
                    with self._lock:
                        self._pending_rows[owner] = max(
                            0, self._pending_rows.get(owner, 0)
                            - (payload.count(b"\n") + 1))
                    # delivered prefix has no future readers: reclaim
                    # whole segments below the commit (Kafka retention
                    # at the commit frontier)
                    self._spools[owner].prune(reader.committed)
                    if probing and not self.health.can_drain(owner):
                        # probe landed but the owner still sheds (its
                        # piggyback said so): stay paced
                        return False
                    probing = False
                    continue
                # peer down or shedding: rows stay spooled (a down
                # broker's partition log); rewind and let the paced
                # probe schedule own the redelivery
                reader.seek(start)
                logger.warning(
                    "peer %d %s; %d spooled batches retained", owner,
                    "shedding" if outcome == _SHED else "unreachable",
                    reader.lag)
                return False

    def _retain_shed(self, owner: int, payload: bytes) -> None:
        """Memory-mode shed retention: push the refused lines back to
        the FRONT of the buffer (order preserved) under
        ``max_retained_bytes``; overflow dead-letters the OLDEST lines
        with the replayable ``forward-shed`` kind (mirroring the intake
        path's ``intake-shed`` contract — audit + requeue, not loss)."""
        lines = payload.split(b"\n")
        dropped: List[bytes] = []
        with self._lock:
            buf = self._buffers.setdefault(owner, [])
            self._buffer_since.setdefault(owner, time.monotonic())
            buf[:0] = lines
            size = self._buffer_bytes.get(owner, 0) \
                + sum(len(l) + 1 for l in lines)
            while size > self.max_retained_bytes and buf:
                line = buf.pop(0)
                size -= len(line) + 1
                dropped.append(line)
            self._buffer_bytes[owner] = size
        self._m_shed_retained.inc(max(0, len(lines) - len(dropped)))
        if dropped:
            self._dead_letter(
                owner, b"\n".join(dropped),
                f"shed-retention bound ({self.max_retained_bytes}B) "
                f"exceeded while peer {owner} sheds",
                kind="forward-shed")

    def _deliver(self, owner: int, payload: bytes,
                 probe: bool = False) -> str:
        """One batch to one peer with bounded retries.  ``_OK`` on
        success or non-retryable rejection (which dead-letters);
        ``_DOWN`` when the peer is unreachable; ``_SHED`` when the
        owner's admission refused the rows (both retain — the caller
        decides spool-rewind vs re-buffer vs dead-letter)."""
        demux = self.peers.get(owner)
        if demux is None:
            self._dead_letter(owner, payload, "no demux for peer")
            return _OK
        rows = payload.count(b"\n") + 1
        trace = (self.tracer.trace("forward.batch")
                 if self.tracer is not None else _NOOP_TRACE)
        try:
            # root span names the DCN hop; the per-attempt
            # rpc.client.events.ingest spans share its trace_id
            with trace.span("forward.batch") as span:
                span.tag("peer", owner).tag("rows", rows)
                if probe:
                    span.tag("probe", 1)
                outcome = self._deliver_traced(owner, payload, demux, rows,
                                               trace, probe)
                if outcome == _DOWN:
                    # exhausted retries: flag the hop so tail sampling
                    # retains the trace of an unreachable peer
                    span.error = "peer unreachable: retries exhausted"
                return outcome
        finally:
            trace.end()

    def _deliver_traced(self, owner: int, payload: bytes, demux,
                        rows: int, trace, probe: bool = False) -> str:
        attempts = 1 if probe else self.max_retries
        for attempt in range(attempts):
            self._m_attempts.inc()
            try:
                body, _ = demux.call(
                    "events.ingest",
                    {"sourceId": f"fwd:{self.process_id}"},
                    attachment=payload, trace=trace,
                    timeout_s=self.call_timeout_s,
                    deadline_s=self.call_timeout_s)
                self._m_forwarded.inc(int(body.get("accepted", rows)))
                self._deadline_streaks.pop(owner, None)
                self.health.observe_alive(owner)
                return _OK
            except ChannelUnavailable as e:
                logger.info("forward to %d failed (%d/%d): %s", owner,
                            attempt + 1, attempts, e)
                self.health.observe_failure(owner)
                # stop-aware backoff: stop() must not wait out 2s-grade
                # sleeps on sender threads — the wait aborts the moment
                # the stop event sets and the retry loop exits
                if self._stop.wait(min(0.1 * (2 ** attempt), 2.0)):
                    return _DOWN
            except DeadlineExpired as e:
                # the budget died, not the peer: rows are retained and
                # the next paced pass retries with a fresh budget
                logger.info("forward to %d deadline expired (%d/%d): %s",
                            owner, attempt + 1, attempts, e)
                self._m_deadline.inc()
                # deadline-ms is wall-clock: a peer that answers but
                # rejects EVERY call pre-dispatch usually means host
                # clock skew larger than call_timeout_s — without this
                # the spool grows silently (the peer looks ALIVE)
                streak = self._deadline_streaks.get(owner, 0) + 1
                self._deadline_streaks[owner] = streak
                if streak % 5 == 0:
                    logger.warning(
                        "%d consecutive deadline expiries toward peer "
                        "%d; if the peer is otherwise healthy, check "
                        "host clock sync (the deadline-ms header is "
                        "wall-clock epoch)", streak, owner)
                if self._stop.wait(min(0.1 * (2 ** attempt), 2.0)):
                    return _DOWN
            except RpcError as e:
                if getattr(e, "error", "") == "overloaded":
                    # the owner SHED the rows (admission backpressure):
                    # record its advertised state (the error frame's
                    # piggyback headers carried it) and PARK — the
                    # paced probe schedule redelivers once it recovers,
                    # never a dead-letter for rows the owner will take
                    logger.info("forward to %d shed by overload", owner)
                    self.health.observe_alive(owner)
                    pressure = self.health.owner_pressure(owner)
                    if pressure is None:
                        # no piggyback reached us (older peer): assume
                        # SHEDDING with the default hint so pacing holds
                        self.health.observe_heartbeat(
                            owner, overload_state=int(
                                OverloadState.SHEDDING),
                            retry_after_s=1.0)
                    return _SHED
                self.health.observe_alive(owner)   # it answered
                self._dead_letter(owner, payload, f"peer rejected: {e}")
                return _OK
        return _DOWN

    def _dead_letter(self, owner: int, payload: bytes, reason: str,
                     kind: str = "undeliverable-forward") -> None:
        self._m_dead.inc(payload.count(b"\n") + 1)
        logger.warning("dead-lettering forward batch for peer %d: %s",
                       owner, reason)
        if self.dead_letters is not None:
            doc = {
                "kind": kind,
                "peer": owner,
                "reason": reason,
            }
            if kind == "forward-shed":
                # replayable contract (mirrors intake-shed): hex payload
                # so Instance.requeue_dead_letter re-routes it through
                # ingest_payload once the owner recovers
                doc["payload"] = payload.hex()
                doc["state"] = self.health.snapshot().get(
                    str(owner), {}).get("overload", "SHEDDING")
            else:
                doc["payload"] = payload.decode("utf-8", "replace")
            self.dead_letters.append_json(doc)

    # -- lifecycle -----------------------------------------------------------

    def _flush_loop(self) -> None:
        while not self._stop.wait(self.deadline_s / 2):
            self.flush(only_expired=True)
            self.health.tick()

    def _heartbeat_loop(self) -> None:
        """The fleet heartbeat: every interval, one ``fleet.heartbeat``
        per remote peer carrying this host's overload state, Retry-After
        hint, per-peer pending spool lag, and incarnation; the RESPONSE
        body is the peer's same record, so one exchange teaches both
        directions.  Failures feed the failure detector — the heartbeat
        IS the liveness probe for peers with no traffic."""
        while not self._stop.wait(self.heartbeat_interval_s):
            with self._lock:
                peers = [(p, d) for p, d in self.peers.items()
                         if d is not None]
            for p, demux in peers:
                if self._stop.is_set():
                    return
                try:
                    body, _ = demux.call(
                        "fleet.heartbeat", self.heartbeat_body(p),
                        timeout_s=max(1.0, 2 * self.heartbeat_interval_s),
                        deadline_s=max(1.0, 2 * self.heartbeat_interval_s))
                    self._m_hb_sent.inc()
                    self.observe_peer_heartbeat(p, body)
                except ChannelUnavailable:
                    self._m_hb_fail.inc()
                    self.health.observe_failure(p)
                except DeadlineExpired:
                    # NEUTRAL: a client-side budget lapse (e.g. every
                    # replica's connect timeout burned it) is not
                    # liveness evidence — counting it as life would pin
                    # a dead peer ALIVE forever.  A server-side
                    # rejection DID answer, but its piggyback headers
                    # already fed observe_piggyback via the channel's
                    # header listener, so nothing is lost here.
                    self._m_hb_fail.inc()
                except RpcError:
                    # the peer ANSWERED (an old peer without the method
                    # says not_found): liveness evidence, no state
                    self._m_hb_sent.inc()
                    self.health.observe_alive(p)
            self.health.tick()
            self._m_pending.set(self.pending_rows())

    def heartbeat_body(self, target: int) -> Dict[str, object]:
        """This host's health record as the heartbeat wire shape."""
        state, retry_after = 0, 0.0
        if self.overload is not None:
            state = int(self.overload.state)
            retry_after = float(self.overload.retry_after())
        unhealthy = False
        if self.device_unhealthy is not None:
            try:
                unhealthy = bool(self.device_unhealthy())
            except Exception:
                logger.exception("device_unhealthy probe failed")
        shards: list = []
        if unhealthy and self.device_unhealthy_shards is not None:
            try:
                shards = [int(s) for s in self.device_unhealthy_shards()]
            except Exception:
                logger.exception("device_unhealthy_shards probe failed")
        return {
            "processId": int(self.process_id),
            "incarnation": int(self.incarnation),
            "state": state,
            "retryAfterS": round(retry_after, 3),
            "spoolLag": int(self.pending_for(target)),
            "deviceUnhealthy": unhealthy,
            "unhealthyShards": shards,
        }

    def observe_peer_heartbeat(self, peer: int, body) -> None:
        """Feed one heartbeat body (request or response side) into the
        health table — the ``fleet.heartbeat`` server handler calls this
        so receiving a beat teaches as much as sending one."""
        if not isinstance(body, dict):
            return
        try:
            self.health.observe_heartbeat(
                int(peer),
                incarnation=int(body.get("incarnation", 0)),
                overload_state=int(body.get("state", 0)),
                retry_after_s=float(body.get("retryAfterS", 0.0)),
                spool_lag=int(body.get("spoolLag", 0)),
                device_unhealthy=bool(body.get("deviceUnhealthy", False)),
                unhealthy_shards=tuple(
                    int(s) for s in body.get("unhealthyShards", ()) or ()))
        except (TypeError, ValueError):
            logger.warning("malformed heartbeat from peer %s ignored", peer)

    def pending_for(self, owner: int) -> int:
        """Rows currently retained toward one peer (spool or buffer —
        ROW units in both modes; spool records are multi-row payloads,
        so reader.lag would under-report)."""
        with self._lock:
            if self.durable:
                return int(self._pending_rows.get(owner, 0))
            return len(self._buffers.get(owner, ()))

    def pending_rows(self) -> int:
        with self._lock:
            if self.durable:
                return sum(self._pending_rows.get(o, 0)
                           for o in self._spool_readers)
            return sum(len(v) for v in self._buffers.values())

    def _pending_owners(self, only_expired: bool) -> List[int]:
        now = time.monotonic()
        with self._lock:
            if self.durable:
                since = self._spool_since
                owners = [o for o, r in self._spool_readers.items()
                          if r.lag > 0]
            else:
                since = self._buffer_since
                owners = list(self._buffers)
            if only_expired:
                owners = [o for o in owners
                          if now - since.get(o, 0.0) >= self.deadline_s]
        # parked peers whose probe slot hasn't come up yet are skipped
        # OUTSIDE the lock (health's lock is a leaf): no sender thread
        # is spawned just to park — the flusher tick stays O(healthy)
        return [o for o in owners
                if self.health.can_drain(o) or self.health.probe_ready(o)]

    def flush(self, only_expired: bool = False, wait: bool = False) -> None:
        for owner in self._pending_owners(only_expired):
            self._send_async(owner)
        if wait:
            with self._lock:
                threads = list(self._senders)
            for t in threads:
                t.join(timeout=self.max_retries * 5.0 + 5.0)

    def start(self) -> None:
        self._stop.clear()
        self._flusher = threading.Thread(
            target=self._flush_loop, name=f"{self.name}-flush", daemon=True)
        self._flusher.start()
        if self.heartbeat_interval_s > 0 and any(
                d is not None for d in self.peers.values()):
            self._heartbeater = threading.Thread(
                target=self._heartbeat_loop,
                name=f"{self.name}-heartbeat", daemon=True)
            self._heartbeater.start()
        # crash recovery: anything spooled-but-uncommitted from a prior
        # run ships now (replay-from-offset, MicroserviceKafkaConsumer
        # semantics applied to the producer side)
        if self.durable:
            self.flush()
        super().start()

    def stop(self) -> None:
        self._stop.set()
        if self._flusher is not None:
            self._flusher.join(timeout=5)
            self._flusher = None
        if self._heartbeater is not None:
            self._heartbeater.join(timeout=5)
            self._heartbeater = None
        self.flush(wait=True)
        if not self.durable:
            # fire-and-forget mode: rows still buffered for parked /
            # shedding peers die with the process — audit them as
            # replayable forward-shed records instead of vanishing
            with self._lock:
                leftovers = [o for o, buf in self._buffers.items() if buf]
            for owner in leftovers:
                with self._lock:
                    payload = self._drain_memory_locked(owner)
                if payload:
                    self._dead_letter(
                        owner, payload,
                        f"rows retained for parked peer {owner} at stop "
                        "(memory mode)", kind="forward-shed")
        for spool in self._spools.values():
            spool.close()
        super().stop()

    def apply_membership(
            self, peer_demuxes: Dict[int, Optional[RpcDemux]],
            process_id: Optional[int] = None) -> int:
        """Adopt a NEW peers map (count may change) and requeue every
        pending row under the new ownership — the consumer-rebalance
        analog: a departed peer's spooled rows go to their new owners
        (or the local intake) instead of waiting for a host that will
        never return.  Returns rows requeued.

        The caller (Instance.apply_membership_change) is responsible for
        record handoff (:mod:`sitewhere_tpu.rpc.migration`); this method
        only moves the in-flight forwarding state.
        """
        # Drain-stop current senders: swap under a quiet fabric so no
        # sender is mid-poll on a spool we are about to requeue.
        with self._lock:
            old_locks = list(self._owner_locks.values())
        for lock in old_locks:
            lock.acquire()
        old_tails: List[tuple] = []  # (reader, journal, end_position)
        try:
            with self._lock:
                pending: List[bytes] = []
                # memory buffers
                for owner in list(self._buffers):
                    payload = self._drain_memory_locked(owner)
                    if payload:
                        pending.append(payload)
                # durable spools: read (but do NOT commit yet) every
                # uncommitted tail — the old offsets advance only after
                # the rows are durably re-placed, so a crash mid-requeue
                # replays them (at-least-once), never loses them
                for owner, reader in list(self._spool_readers.items()):
                    while True:
                        records = reader.poll(SPOOL_POLL_RECORDS)
                        if not records:
                            break
                        pending.extend(r for _, r in records)
                    old_tails.append(
                        (reader, self._spools[owner], reader.position))
                    self._spool_since.pop(owner, None)
                # every spool tail is in `pending` now: the row counts
                # rebuild as the re-ingest below re-routes them
                self._pending_rows = {}

                if process_id is not None:
                    self.process_id = process_id
                self.peers = dict(peer_demuxes)
                self.n_processes = len(peer_demuxes)
                # any split computed under the old map must recompute
                # (see _route_remote's generation check)
                self._member_gen += 1
                # spools/locks for the new peer set (existing Journal
                # objects are reused so their files stay continuous)
                new_spools: Dict[int, Journal] = {}
                new_readers: Dict[int, JournalReader] = {}
                new_locks: Dict[int, threading.Lock] = {}
                durable_root = self._data_dir
                for p, demux in peer_demuxes.items():
                    if demux is None:
                        continue
                    new_locks[p] = self._owner_locks.get(
                        p, threading.Lock())
                    if p in self._spools:
                        new_spools[p] = self._spools[p]
                        new_readers[p] = self._spool_readers[p]
                    elif durable_root is not None:
                        spool = Journal(durable_root, name=f"forward-{p}",
                                        fsync_every=64,
                                        segment_bytes=4 << 20)
                        new_spools[p] = spool
                        new_readers[p] = JournalReader(spool, "sender")
                # departed peers' spools close in the finalize phase
                # below, after their rows are durably re-placed
                self._spools = new_spools
                self._spool_readers = new_readers
                self._owner_locks = new_locks
        finally:
            for lock in old_locks:
                lock.release()

        # health plane follows the membership: departed peers drop out
        # of the table, joiners start optimistic; piggyback taps rebind
        self.health.set_peers(
            [p for p, d in peer_demuxes.items() if d is not None])
        self._bind_piggyback(peer_demuxes)

        # Re-ingest outside every lock: rows route freshly under the new
        # map (local rows journal in the dispatcher, remote rows spool
        # for their new owners) — durably re-placed BEFORE the old
        # offsets commit below.
        requeued = 0
        for payload in pending:
            requeued += payload.count(b"\n") + 1
            self.ingest_payload(payload, source_id="membership-requeue")
        for reader, journal, end in old_tails:
            try:
                if end > reader.committed:
                    reader.commit(end)
                journal.prune(reader.committed)
                if journal not in self._spools.values():
                    journal.close()  # departed peer's spool, fully drained
            except Exception:
                logger.exception("old spool finalize failed (harmless: "
                                 "its rows replay as duplicates)")
        if requeued:
            logger.info("membership change: requeued %d pending rows "
                        "under the new ownership", requeued)
        self.flush()
        return requeued

    def metrics(self) -> Dict[str, object]:
        """Topology/admin view.  The canonical observable surface is the
        registered ``forward.*`` metric family (counters, the pending
        gauge, per-peer health-state gauges) — this dict is a snapshot
        of the same numbers plus the health table."""
        with self._lock:
            if self.durable:
                pending = sum(self._pending_rows.get(o, 0)
                              for o in self._spool_readers)
            else:
                pending = sum(len(v) for v in self._buffers.values())
            out = {
                "local_rows": self.local_rows,
                "forwarded_rows": self.forwarded_rows,
                "dead_lettered": self.dead_lettered,
                "pending": pending,
                "durable": self.durable,
            }
        self._m_pending.set(pending)
        out["send_attempts"] = int(self._m_attempts.value)
        out["probe_sends"] = int(self._m_probes.value)
        out["edge_refusals"] = int(self._m_edge.value)
        out["peers"] = self.health.snapshot()
        return out
