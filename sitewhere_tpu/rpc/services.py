"""Domain services over the RPC fabric + cached remote facades.

Reference: each domain microservice exposes its L0 SPI over gRPC
(``DeviceManagementImpl.java``, ``EventManagementImpl.java:109-584``) and
clients consume it through per-domain ApiChannels, with device/assignment
lookups near-cached (``CachedDeviceManagementApiChannel.java`` +
``cache/CacheProvider.java``).  Here :func:`bind_instance` publishes the
instance's already-composed services over one :class:`~.server.RpcServer`
(in-process composition made cross-host reachable at the boundary), and
:class:`RemoteDeviceManagement` is the near-cached client facade.

The event intake method ``events.ingest`` carries the columnar NDJSON
wire payload in the binary attachment lane and lands directly on
``PipelineDispatcher.ingest_wire_lines`` — so a forwarded cross-host
batch takes the exact same journaled, columnar path as local wire
traffic (Kafka's "the pipeline bus IS the intake" property).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Tuple

from sitewhere_tpu.rpc.channel import RpcDemux, RpcError
from sitewhere_tpu.rpc.server import CallContext, RpcServer
from sitewhere_tpu.services.common import EntityNotFound, SearchCriteria
from sitewhere_tpu.web.http import jsonable, page_response


def _criteria(body: dict) -> SearchCriteria:
    return SearchCriteria(
        page=int(body.get("page", 1)),
        page_size=int(body.get("pageSize", 100)),
        start_s=body.get("start"),
        end_s=body.get("end"),
    )


def bind_instance(server: RpcServer, inst) -> None:
    """Register the instance's domain surface on ``server``.

    Method names mirror the reference's per-domain gRPC services
    (SURVEY.md §2.3); the surface is the cross-host subset — what the
    reference's web-rest and pipeline services actually call over the
    fabric, not every SPI method (in-process callers keep the direct
    Python SPI).
    """
    dm = inst.device_management

    def reg(method, fn, authority=None, auth_required=True):
        server.register(method, fn, authority=authority,
                        auth_required=auth_required)

    # ---- device management (DeviceManagementImpl analog) -------------------
    reg("device.get", lambda c, b: jsonable(dm.get_device(b["token"])))
    reg("device.create", lambda c, b: jsonable(dm.create_device(**b)),
        authority="ROLE_ADMIN")
    reg("device.update",
        lambda c, b: jsonable(dm.update_device(b.pop("token"), **b)),
        authority="ROLE_ADMIN")
    reg("device.delete", lambda c, b: jsonable(dm.delete_device(b["token"])),
        authority="ROLE_ADMIN")
    reg("device.list",
        lambda c, b: page_response(dm.list_devices(_criteria(b))))
    reg("assignment.get",
        lambda c, b: jsonable(dm.get_device_assignment(b["token"])))
    reg("assignment.active",
        lambda c, b: jsonable(_active_assignment(dm, b["deviceToken"])))
    reg("assignment.create",
        lambda c, b: jsonable(dm.create_device_assignment(**b)),
        authority="ROLE_ADMIN")
    reg("devicetype.get",
        lambda c, b: jsonable(dm.get_device_type(b["token"])))
    reg("devicetype.create",
        lambda c, b: jsonable(dm.create_device_type(**b)),
        authority="ROLE_ADMIN")

    # ---- events (EventManagementImpl + intake boundary) --------------------
    def events_ingest(ctx: CallContext, body):
        if not ctx.attachment:
            return {"accepted": 0}
        n = inst.dispatcher.ingest_wire_lines(
            ctx.attachment,
            source_id=(body or {}).get("sourceId", f"rpc:{ctx.peer}"))
        # replicated-ack: the SENDER commits its spool cursor (and later
        # prunes the spool) on this reply, so the ack must mean durably
        # journaled — fsync before answering, or a kill of both hosts
        # in the ack window loses the batch from both sides
        # (crashrec_bench crash.mid_forward pins this)
        inst.ingest_journal.flush()
        return {"accepted": int(n)}

    reg("events.ingest", events_ingest)

    def events_query(ctx: CallContext, body):
        # Unknown tokens return an EMPTY page, not an error: in a
        # sharded topology most hosts don't know most tokens, and a
        # federated fan-out must be able to tell "not here" (normal)
        # from a peer actually failing.
        body = body or {}
        kwargs = {}
        token = body.get("deviceToken")
        if token is not None:
            dense = inst.identity.device.lookup(token)
            if dense < 0:
                return {"numResults": 0, "results": []}
            kwargs["device_id"] = int(dense)
        token = body.get("assignmentToken")
        if token is not None:
            handle = dm.handle_for("assignment", token)
            if handle < 0:
                return {"numResults": 0, "results": []}
            kwargs["assignment_id"] = int(handle)
        if body.get("eventType") is not None:
            kwargs["event_type"] = int(body["eventType"])
        inst.event_store.flush()
        results = inst.event_store.query(_criteria(body), **kwargs)
        return page_response(results)

    reg("events.query", events_query)

    # ---- state / topology (DeviceStateImpl + TopologyStateAggregator) ------
    reg("state.get", lambda c, b: jsonable(
        inst.device_state.get_device_state(b["deviceToken"])))

    # ---- command delivery (federated invocation; SURVEY.md §3.4) ----------
    # Deliberately create_command_invocation, NOT invoke_command: the
    # owner must answer not_found for an assignment it doesn't hold, or
    # two peers would ping-pong an unknown token forever.  The caller's
    # initiator rides through so audit data doesn't depend on placement.
    reg("command.invoke", lambda c, b: inst.create_command_invocation(
        b["assignmentToken"],
        command_token=str(b["commandToken"]),
        parameter_values=dict(b.get("parameterValues") or {}),
        initiator=str(b.get("initiator") or "RPC"),
        initiator_id=b.get("initiatorId"),
        ts_s=b.get("ts")))
    reg("instance.topology", lambda c, b: inst.topology())
    reg("instance.ping", lambda c, b: {"instance": inst.instance_id,
                                       "ts": time.time()},
        auth_required=False)

    # ---- fleet health plane (rpc/health.py) --------------------------------
    def fleet_heartbeat(ctx: CallContext, body):
        """One heartbeat exchange teaches both directions: the request
        body is the SENDER's health record (fed into our table), the
        response body is OURS — overload state, Retry-After hint,
        pending spool lag toward the sender, incarnation."""
        body = body if isinstance(body, dict) else {}
        fwd = inst.forwarder
        try:
            sender = int(body.get("processId"))
        except (TypeError, ValueError):
            # malformed beats are ignored, never an 'internal' error —
            # a buggy/fuzzing peer must not flood logs at beat rate
            sender = None
        if fwd is not None and sender is not None:
            fwd.observe_peer_heartbeat(sender, body)
        if fwd is not None:
            return fwd.heartbeat_body(sender if sender is not None else -1)
        ov = inst.overload
        return {
            "processId": -1, "incarnation": 0,
            "state": int(ov.state) if ov is not None else 0,
            "retryAfterS": (round(float(ov.retry_after()), 3)
                            if ov is not None else 0.0),
            "spoolLag": 0,
        }

    reg("fleet.heartbeat", fleet_heartbeat)

    # ---- the remaining management domains (per-domain ApiDemux analog) -----
    from sitewhere_tpu.rpc.domains import bind_domains

    bind_domains(server, inst)

    # ---- ownership migration (membership-change handoff target) -----------
    from sitewhere_tpu.rpc.migration import bind_migration

    bind_migration(server, inst)


def _active_assignment(dm, device_token: str):
    assignment = dm.get_active_assignment(device_token)
    if assignment is None:
        raise EntityNotFound(f"no active assignment for {device_token}")
    return assignment


class _CacheEntry:
    __slots__ = ("value", "expires_at")

    def __init__(self, value, expires_at: float):
        self.value = value
        self.expires_at = expires_at


class RemoteDeviceManagement:
    """Near-cached device-management client facade.

    Reference: ``CachedDeviceManagementApiChannel.java`` wraps the gRPC
    channel with TTL near-caches for device and assignment lookups so the
    inbound hot path (``InboundPayloadProcessingLogic.java:285-288``)
    pays a network hop only on cold tokens.  Mutations through this
    facade invalidate their own token's entry; remote writers are covered
    by the TTL, as in the reference.
    """

    def __init__(self, demux: RpcDemux, cache_ttl_s: float = 30.0,
                 max_entries: int = 10000):
        self._demux = demux
        self._ttl = cache_ttl_s
        self._max = max_entries
        self._cache: Dict[Tuple[str, str], _CacheEntry] = {}
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    # -- cache plumbing ------------------------------------------------------

    def _get_cached(self, kind: str, token: str):
        key = (kind, token)
        with self._lock:
            entry = self._cache.get(key)
            if entry is not None and entry.expires_at > time.monotonic():
                self.hits += 1
                return entry.value
            if entry is not None:
                del self._cache[key]
            self.misses += 1
        return None

    def _put(self, kind: str, token: str, value) -> None:
        with self._lock:
            if len(self._cache) >= self._max:
                # drop the stalest ~10% (bounded cache, no LRU bookkeeping
                # on the hot path — the reference cache is size-capped too)
                for key in sorted(self._cache,
                                  key=lambda k: self._cache[k].expires_at)[
                                      : max(1, self._max // 10)]:
                    del self._cache[key]
            self._cache[(kind, token)] = _CacheEntry(
                value, time.monotonic() + self._ttl)

    def _invalidate(self, kind: str, token: str) -> None:
        with self._lock:
            self._cache.pop((kind, token), None)

    # -- lookups (cached) ----------------------------------------------------

    def get_device(self, token: str) -> dict:
        cached = self._get_cached("device", token)
        if cached is not None:
            return cached
        body, _ = self._demux.call("device.get", {"token": token})
        self._put("device", token, body)
        return body

    def get_active_assignment(self, token: str) -> dict:
        cached = self._get_cached("assignment", token)
        if cached is not None:
            return cached
        body, _ = self._demux.call("assignment.active",
                                   {"deviceToken": token})
        self._put("assignment", token, body)
        return body

    # -- mutations (write-through invalidation) ------------------------------

    def create_device(self, **fields) -> dict:
        body, _ = self._demux.call("device.create", fields)
        return body

    def update_device(self, token: str, **fields) -> dict:
        body, _ = self._demux.call("device.update",
                                   {"token": token, **fields})
        self._invalidate("device", token)
        self._invalidate("assignment", token)
        return body

    def delete_device(self, token: str) -> dict:
        body, _ = self._demux.call("device.delete", {"token": token})
        self._invalidate("device", token)
        self._invalidate("assignment", token)
        return body
