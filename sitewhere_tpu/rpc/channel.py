"""Client side of the RPC fabric: channels + replica demux.

Reference: ``ApiDemux.java:42-110`` keeps one ``ApiChannel`` per
discovered replica hostname, routes calls round-robin
(``RoundRobinDemuxRoutingStrategy.java``), re-resolves topology every 5s,
and ``waitForApiChannel`` backs off 100ms→60s until a replica is
reachable.  ``MultitenantGrpcChannel`` stamps JWT + tenant tokens onto
every call (``JwtClientInterceptor.java``,
``TenantTokenClientInterceptor.java:53-57``).

This module keeps those *semantics* — per-replica channels, round-robin
with failover, exponential reconnect backoff, header stamping — over the
plain framed-TCP wire (`wire.py`) instead of gRPC/HTTP2.  One channel
multiplexes concurrent calls by request id (a reader thread correlates
responses), so callers never queue behind each other's round trips.
"""

from __future__ import annotations

import itertools
import logging
import socket
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from sitewhere_tpu.rpc import wire
from sitewhere_tpu.runtime import faults
from sitewhere_tpu.runtime.metrics import global_registry
from sitewhere_tpu.runtime.resilience import Backoff, RetryPolicy
from sitewhere_tpu.runtime.tracing import _NOOP_TRACE

logger = logging.getLogger("sitewhere_tpu.rpc")

BACKOFF_INITIAL_S = 0.1
BACKOFF_MAX_S = 60.0   # ApiDemux.java:47-52

# The reconnect schedule every channel follows (100ms → 60s, ApiDemux
# semantics).  No jitter: replica reconnects are per-endpoint, not a
# thundering herd, and deterministic schedules keep the tests exact.
RECONNECT_POLICY = RetryPolicy(
    initial_s=BACKOFF_INITIAL_S, max_s=BACKOFF_MAX_S, factor=2.0)


class RpcError(Exception):
    """Server-side failure surfaced to the caller.

    ``headers`` carries the error response's metadata lane (notably the
    overload piggyback ``x-overload``/``x-retry-after``) so callers can
    learn the peer's pressure even from a refusal."""

    def __init__(self, error: str, message: str,
                 headers: Optional[Dict[str, str]] = None):
        super().__init__(f"{error}: {message}")
        self.error = error
        self.message = message
        self.headers = headers or {}


class DeadlineExpired(RpcError):
    """The call's propagated deadline lapsed — client-side before the
    send, or server-side before the handler ran (no work was executed).

    RETRYABLE and deliberately distinct from :class:`ChannelUnavailable`:
    the peer is healthy, the *budget* died (usually behind a slow
    fabric).  Callers retry with a fresh budget; failure detectors must
    NOT count it as peer death."""

    def __init__(self, message: str,
                 headers: Optional[Dict[str, str]] = None):
        super().__init__("deadline_expired", message, headers)


# error code the server answers for pre-dispatch deadline rejections;
# the client re-raises it as DeadlineExpired (kept in one place so the
# two sides cannot drift)
DEADLINE_ERROR_CODE = "deadline_expired"
HEADER_DEADLINE = "deadline-ms"     # absolute unix epoch milliseconds


def deadline_header(budget_s: float) -> str:
    """Absolute-epoch encoding of a remaining budget.  Wall clock, not
    monotonic: the value must be comparable on the RECEIVING host."""
    return str(int((time.time() + budget_s) * 1000.0))


def deadline_remaining_s(headers: Dict[str, str]) -> Optional[float]:
    """Remaining budget encoded in ``headers`` (negative = expired);
    None when the call carries no deadline."""
    raw = headers.get(HEADER_DEADLINE)
    if raw is None:
        return None
    try:
        return int(raw) / 1000.0 - time.time()
    except (TypeError, ValueError):
        return None


class ChannelUnavailable(Exception):
    """No connection could be established / the connection died mid-call."""


class _Pending:
    __slots__ = ("event", "frame", "sock")

    def __init__(self, sock=None):
        self.event = threading.Event()
        self.frame: Optional[wire.Frame] = None
        self.sock = sock   # the connection this call went out on


class RpcChannel:
    """One connection to one replica, multiplexing concurrent calls.

    ``token_provider`` supplies the JWT stamped into the
    ``authorization`` header per call (the ``JwtClientInterceptor``
    analog — a provider, not a fixed string, so token refresh needs no
    channel restart); ``tenant`` rides the ``tenant`` header
    (``TenantTokenClientInterceptor`` analog).
    """

    def __init__(self, endpoint: str,
                 token_provider: Optional[Callable[[], str]] = None,
                 tenant: Optional[str] = None,
                 connect_timeout_s: float = 5.0,
                 header_listener: Optional[
                     Callable[[Dict[str, str]], None]] = None):
        self.endpoint = endpoint
        self._addr = wire.parse_endpoint(endpoint)
        self._token_provider = token_provider
        self._tenant = tenant
        self._connect_timeout_s = connect_timeout_s
        # response-header tap: the health table's piggyback intake (a
        # listener crash must never fail the call it rode on)
        self.header_listener = header_listener
        self._sock: Optional[socket.socket] = None
        self._reader: Optional[threading.Thread] = None
        self._lock = threading.Lock()          # connection state transitions
        self._write_lock = threading.Lock()    # frame sendall only
        self._pending: Dict[int, _Pending] = {}
        self._pending_lock = threading.Lock()
        self._next_id = itertools.count(1)
        self._closed = False
        # reconnect backoff (exponential, 100ms → 60s) — the shared
        # resilience primitive; retries tick resilience.retries.rpc.connect
        self._backoff = Backoff(RECONNECT_POLICY, name="rpc.connect")

    # -- connection management ---------------------------------------------

    @property
    def connected(self) -> bool:
        return self._sock is not None

    def in_backoff(self) -> bool:
        return not self.connected and not self._backoff.due()

    def _connect_locked(self, timeout_s: Optional[float] = None) -> None:
        if self._sock is not None or self._closed:
            return
        if not self._backoff.due():
            raise ChannelUnavailable(
                f"{self.endpoint} in backoff for "
                f"{self._backoff.remaining():.1f}s")
        try:
            faults.fire("rpc.connect")
            if faults.net_drops(self.endpoint, "connect"):
                # injected partition: unreachable exactly like a refused
                # connect (backoff advances, caller fails over)
                raise OSError("injected network partition")
            sock = socket.create_connection(
                self._addr, timeout=(timeout_s if timeout_s is not None
                                     else self._connect_timeout_s))
        except OSError as e:
            self._backoff.defer()
            raise ChannelUnavailable(f"{self.endpoint}: {e}") from e
        sock.settimeout(None)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = sock
        self._backoff.reset()
        self._reader = threading.Thread(
            target=self._read_loop, args=(sock,),
            name=f"rpc-reader-{self.endpoint}", daemon=True)
        self._reader.start()

    def ensure_connected(self) -> None:
        with self._lock:
            self._connect_locked()

    def _read_loop(self, sock: socket.socket) -> None:
        try:
            while True:
                frame = wire.read_frame(sock)
                if not frame.is_response:
                    logger.warning("%s: unexpected request frame from server",
                                   self.endpoint)
                    continue
                with self._pending_lock:
                    pending = self._pending.pop(frame.request_id, None)
                if pending is not None:
                    pending.frame = frame
                    pending.event.set()
        except (ConnectionError, OSError, wire.WireError) as e:
            self._drop(sock, e)

    def _drop(self, sock: socket.socket, exc: Exception) -> None:
        """Connection died: fail the in-flight calls THAT WENT OUT ON IT
        so their callers fail over — calls already riding a newer
        reconnected socket are untouched (they are still answerable)."""
        with self._lock:
            if self._sock is sock:
                self._sock = None
        try:
            sock.close()
        except OSError:
            pass
        with self._pending_lock:
            stranded = {rid: p for rid, p in self._pending.items()
                        if p.sock is sock}
            for rid in stranded:
                del self._pending[rid]
        for p in stranded.values():
            p.event.set()   # frame stays None → ChannelUnavailable
        if stranded and not self._closed:
            logger.info("%s: connection dropped (%s); %d calls failed over",
                        self.endpoint, exc, len(stranded))

    # -- calls ---------------------------------------------------------------

    def call(self, method: str, body: object = None,
             attachment: bytes = b"",
             headers: Optional[Dict[str, str]] = None,
             timeout_s: float = 30.0, trace=None,
             deadline_s: Optional[float] = None) -> Tuple[object, bytes]:
        """One request/reply round trip.  Returns ``(body, attachment)``.

        ``trace`` (a :class:`~sitewhere_tpu.runtime.tracing.Trace`) wraps
        the round trip in an ``rpc.client.<method>`` span and stamps the
        trace context into the frame headers so the server continues the
        SAME trace — the client tracing interceptor analog.

        ``deadline_s`` is the call's remaining BUDGET in seconds: it is
        stamped into the ``deadline-ms`` header (absolute epoch ms, the
        grpc-timeout analog), the client wait timeout derives from it
        (never longer than the budget), and a server receiving it
        already expired rejects the call before executing the handler —
        no wasted work behind a slow fabric.

        Raises :class:`RpcError` for server-reported failures
        (:class:`DeadlineExpired` for a lapsed budget — retryable,
        distinct from peer-down), :class:`ChannelUnavailable` for
        transport failures (the demux catches the latter and fails
        over).
        """
        trace = trace or _NOOP_TRACE
        with trace.span(f"rpc.client.{method}") as span:
            span.tag("endpoint", self.endpoint)
            hdrs = trace.propagate(dict(headers or {}), parent=span)
            return self._call(method, body, attachment, hdrs, timeout_s,
                              deadline_s)

    def _call(self, method: str, body: object, attachment: bytes,
              hdrs: Dict[str, str], timeout_s: float,
              deadline_s: Optional[float] = None) -> Tuple[object, bytes]:
        if deadline_s is not None:
            if deadline_s <= 0:
                # budget already burned (an upstream hop ate it): fail
                # here, client-side — the wire would only spread the lapse
                raise DeadlineExpired(
                    f"{self.endpoint}: budget exhausted before {method}")
            hdrs.setdefault(HEADER_DEADLINE, deadline_header(deadline_s))
            timeout_s = min(timeout_s, deadline_s)
        if self._token_provider is not None and "authorization" not in hdrs:
            hdrs["authorization"] = self._token_provider()
        if self._tenant is not None and "tenant" not in hdrs:
            hdrs["tenant"] = self._tenant
        # injected network faults (runtime/faults.py net plane): latency
        # delays the send (consuming real deadline budget, exactly like
        # a slow fabric); a request-direction drop is a transport fault
        drop, delay = faults.net_shape(self.endpoint, "request")
        if drop:
            raise ChannelUnavailable(
                f"{self.endpoint}: injected partition on {method}")
        if delay > 0.0:
            time.sleep(delay)
        # Encode BEFORE taking any lock, and connect under the state lock
        # only (bounded by connect_timeout — itself capped by the call's
        # remaining budget, so a blackholed peer cannot overrun the
        # deadline by a 5s SYN timeout); the write lock serializes just
        # the sendall so a slow large-attachment writer never stalls
        # other callers' connect/registration — their own timeout_s
        # governs.
        with self._lock:
            self._connect_locked(
                min(self._connect_timeout_s, deadline_s)
                if deadline_s is not None else None)
            sock = self._sock
        if sock is None:
            raise ChannelUnavailable(f"{self.endpoint}: not connected")
        pending = _Pending(sock)
        request_id = next(self._next_id)
        frame_bytes = wire.encode(wire.request_frame(
            request_id, method, body, hdrs, attachment))
        with self._pending_lock:
            self._pending[request_id] = pending
        try:
            with self._write_lock:
                sock.sendall(frame_bytes)
        except OSError as e:
            with self._pending_lock:
                self._pending.pop(request_id, None)
            self._drop(sock, e)
            raise ChannelUnavailable(f"{self.endpoint}: {e}") from e
        if faults.net_drops(self.endpoint, "response"):
            # one-way partition: the request REACHED the server (it may
            # execute!) but the reply is lost — drop the pending slot so
            # the read loop discards the response and the caller times
            # out, exactly the ambiguity a real half-open link produces
            with self._pending_lock:
                self._pending.pop(request_id, None)
        if not pending.event.wait(timeout_s):
            with self._pending_lock:
                self._pending.pop(request_id, None)
            raise ChannelUnavailable(
                f"{self.endpoint}: timeout after {timeout_s}s on {method}")
        frame = pending.frame
        if frame is None:
            raise ChannelUnavailable(f"{self.endpoint}: connection lost")
        if frame.headers and self.header_listener is not None:
            try:
                self.header_listener(frame.headers)
            except Exception:   # noqa: BLE001 — a tap must not fail the call
                logger.exception("%s: response header listener failed",
                                 self.endpoint)
        if frame.is_error:
            err = frame.body if isinstance(frame.body, dict) else {}
            code = err.get("error", "internal")
            message = err.get("message", "unknown error")
            if code == DEADLINE_ERROR_CODE:
                raise DeadlineExpired(message, frame.headers)
            raise RpcError(code, message, frame.headers)
        return frame.body, frame.attachment

    def close(self) -> None:
        self._closed = True
        with self._lock:
            sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        with self._pending_lock:
            stranded, self._pending = self._pending, {}
        for p in stranded.values():
            p.event.set()


class RpcDemux:
    """Round-robin demux over replica channels with failover.

    Reference semantics preserved from ``ApiDemux.java``: a channel per
    replica, round-robin routing, calls fail over to the next replica on
    transport errors, ``wait_for_channel`` blocks until any replica is
    reachable, and ``set_endpoints`` is the discovery-update hook (the
    Consul watch analog — topology is pushed in, not polled here).
    """

    def __init__(self, endpoints: List[str],
                 token_provider: Optional[Callable[[], str]] = None,
                 tenant: Optional[str] = None,
                 connect_timeout_s: float = 5.0,
                 header_listener: Optional[
                     Callable[[Dict[str, str]], None]] = None):
        self._token_provider = token_provider
        self._tenant = tenant
        self._connect_timeout_s = connect_timeout_s
        self._header_listener = header_listener
        self._lock = threading.Lock()
        self._channels: Dict[str, RpcChannel] = {}
        self._rr = 0
        self.set_endpoints(endpoints)

    def _make_channel(self, endpoint: str) -> RpcChannel:
        return RpcChannel(endpoint, token_provider=self._token_provider,
                          tenant=self._tenant,
                          connect_timeout_s=self._connect_timeout_s,
                          header_listener=self._header_listener)

    def set_header_listener(
            self, listener: Optional[Callable[[Dict[str, str]], None]],
    ) -> None:
        """Install the response-header tap on every current and future
        channel (the forwarder's health table registers its piggyback
        intake here)."""
        with self._lock:
            self._header_listener = listener
            for chan in self._channels.values():
                chan.header_listener = listener

    def set_endpoints(self, endpoints: List[str]) -> None:
        """Reconcile the channel set against a new replica list
        (add/remove, existing connections kept — ApiDemux discovery
        monitor semantics)."""
        with self._lock:
            for ep in endpoints:
                if ep not in self._channels:
                    self._channels[ep] = self._make_channel(ep)
            for ep in list(self._channels):
                if ep not in endpoints:
                    self._channels.pop(ep).close()

    @property
    def endpoints(self) -> List[str]:
        with self._lock:
            return list(self._channels)

    def _rotation(self) -> List[RpcChannel]:
        with self._lock:
            chans = list(self._channels.values())
            if not chans:
                return []
            start = self._rr % len(chans)
            self._rr += 1
        return chans[start:] + chans[:start]

    def call(self, method: str, body: object = None,
             attachment: bytes = b"",
             headers: Optional[Dict[str, str]] = None,
             timeout_s: float = 30.0, trace=None,
             deadline_s: Optional[float] = None) -> Tuple[object, bytes]:
        """Round-robin call with failover: transport failures rotate to
        the next replica; server-reported errors (RpcError) do NOT fail
        over — the reference likewise retries only channel faults, not
        application faults.  ``trace`` propagates per attempt, so a
        failed-over call shows one client span per replica tried.

        ``deadline_s`` is ONE budget for the whole rotation: each
        failover attempt gets only what the previous attempts left, so
        k dead replicas cannot multiply the caller's wait."""
        rotation = self._rotation()
        if not rotation:
            raise ChannelUnavailable("no endpoints configured")
        deadline_at = (time.monotonic() + deadline_s
                       if deadline_s is not None else None)
        last: Optional[Exception] = None
        for chan in rotation:
            if chan.in_backoff() and len(rotation) > 1:
                last = last or ChannelUnavailable(
                    f"{chan.endpoint} in backoff")
                continue
            remaining = (deadline_at - time.monotonic()
                         if deadline_at is not None else None)
            if remaining is not None and remaining <= 0:
                if isinstance(last, ChannelUnavailable):
                    # transport failures ate the budget: surface THEM —
                    # a caller's failure detector must count this
                    # toward peer death, not file it as a benign
                    # budget lapse
                    raise last
                raise DeadlineExpired(
                    f"budget exhausted during failover on {method}")
            try:
                return chan.call(method, body, attachment, headers, timeout_s,
                                 trace=trace, deadline_s=remaining)
            except ChannelUnavailable as e:
                last = e
                global_registry().counter(
                    "resilience.retries.rpc.failover").inc()
        raise last if last is not None else ChannelUnavailable("no replicas")

    def wait_for_channel(self, timeout_s: float = 60.0) -> RpcChannel:
        """Block until any replica is connectable
        (``ApiDemux.waitForApiChannel`` — backoff handled per-channel)."""
        deadline = time.monotonic() + timeout_s
        attempt = 0
        while True:
            for chan in self._rotation():
                try:
                    chan.ensure_connected()
                    return chan
                except ChannelUnavailable:
                    continue
            if time.monotonic() >= deadline:
                raise ChannelUnavailable(
                    f"no replica reachable within {timeout_s}s")
            sleep = RECONNECT_POLICY.delay(attempt)
            attempt += 1
            time.sleep(min(sleep, max(0.0, deadline - time.monotonic())))

    def close(self) -> None:
        with self._lock:
            chans = list(self._channels.values())
            self._channels.clear()
        for chan in chans:
            chan.close()
