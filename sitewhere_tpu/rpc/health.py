"""Fleet health plane: the per-host peer health table.

The reference platform never detected failures itself — it leaned on
Consul health checks for service liveness and Kafka consumer-group
rebalances for partition liveness (SURVEY.md §1), and the stream-broker
comparisons our benchmarks cite (PAPERS.md 1807.07724) treat broker
failure semantics as table stakes.  The TPU-first framework removed
both coordinators, which left :class:`~.forward.HostForwarder`
discovering a dead or SHEDDING peer only by burning per-batch connect
timeouts and retry backoffs, forever.  This module reconstitutes the
health plane over the fabric we already have:

- a lightweight ``fleet.heartbeat`` RPC every ``heartbeat_interval_s``
  carrying the sender's overload state, Retry-After hint, pending
  spool lag toward the receiver, and an **incarnation** number (a
  restart bumps it, so a rebooted peer's stale state is replaced, not
  merged);
- the same overload state **piggybacked on every RPC response header**
  (``x-overload`` / ``x-retry-after``, stamped by the server for free)
  so a busy fabric learns about pressure at call rate, faster than the
  heartbeat period;
- an interval-based failure detector per peer::

      ALIVE --(silence >= suspect_after_s, or a send-failure streak)-->
      SUSPECT --(silence >= down_after_s)--> DOWN --(heartbeat)--> ALIVE

  with **hysteresis**: after any state change the table refuses further
  changes for ``hysteresis_s`` — a peer flapping at exactly the
  heartbeat period cannot oscillate the table (and therefore cannot
  trigger park/resume/requeue storms) faster than the configured
  dwell.

Consumers read three questions off the table:

- :meth:`PeerHealthTable.can_drain` — may the forwarder run a full
  spool drain against this peer?  (ALIVE and not advertising
  SHEDDING+.)
- :meth:`PeerHealthTable.probe_due` — a parked peer gets ONE paced
  probe batch per probe interval (stretched by the peer's own
  Retry-After hint while it sheds) instead of a retry storm.
- :meth:`PeerHealthTable.owner_pressure` — the device-facing edge maps
  a remote owner's advertised overload into protocol-native
  backpressure (HTTP 429 / CoAP 5.03 / MQTT pause) so fleet-wide
  pressure reaches the device that can act on it.

Determinism: the table takes an injectable ``clock`` and is driven by
explicit ``observe_*``/``tick`` calls, so the hysteresis and detector
contracts are asserted with a fake clock — no sleeps.
"""

from __future__ import annotations

import enum
import logging
import threading
import time
from typing import Callable, Dict, Iterable, Optional

logger = logging.getLogger("sitewhere_tpu.rpc")

__all__ = ["PeerState", "PeerHealthTable", "HEADER_OVERLOAD",
           "HEADER_RETRY_AFTER"]

# piggyback headers every RPC response carries (server.py stamps them,
# channel.py surfaces them to the registered header listener)
HEADER_OVERLOAD = "x-overload"
HEADER_RETRY_AFTER = "x-retry-after"

# OverloadState names by int value — kept local so the health table can
# render snapshots without importing the (numpy-bearing) overload module
_OVERLOAD_NAMES = ("NORMAL", "DEGRADED", "SHEDDING", "EMERGENCY")
_SHED_THRESHOLD = 2     # OverloadState.SHEDDING


class PeerState(enum.IntEnum):
    """Failure-detector verdict for one peer, ordered by severity."""

    ALIVE = 0
    SUSPECT = 1    # missed heartbeats / send failures: probe, don't drain
    DOWN = 2       # sustained silence: probe at the paced interval only


class _Peer:
    __slots__ = ("state", "last_heard", "last_transition", "incarnation",
                 "overload_state", "retry_after_s", "spool_lag",
                 "fail_streak", "next_probe_at", "transitions",
                 "suppressed", "device_unhealthy", "unhealthy_shards")

    def __init__(self, now: float):
        self.state = PeerState.ALIVE        # optimistic boot (grace)
        self.last_heard = now
        self.last_transition = now
        self.incarnation = 0
        self.overload_state = 0
        self.retry_after_s = 0.0
        self.spool_lag = 0                  # rows the PEER holds for us
        self.fail_streak = 0
        self.next_probe_at = now
        self.transitions = 0
        self.suppressed = 0                 # hysteresis-refused changes
        self.device_unhealthy = False       # peer's hung-step watchdog flag
        self.unhealthy_shards = ()          # mesh shards the wedge names
                                            # (empty = whole tier)


class PeerHealthTable:
    """Per-host view of every peer's liveness + overload state.

    Thread-safe; the internal lock is a LEAF — no method calls out of
    this module while holding it, so callers may consult the table from
    sender threads, the heartbeat loop, and RPC reader threads freely.
    """

    def __init__(self, peers: Iterable[int], *,
                 heartbeat_interval_s: float = 0.5,
                 suspect_after_s: Optional[float] = None,
                 down_after_s: Optional[float] = None,
                 hysteresis_s: Optional[float] = None,
                 probe_interval_s: Optional[float] = None,
                 suspect_failures: int = 3,
                 clock: Callable[[], float] = time.monotonic,
                 metrics=None):
        hb = float(heartbeat_interval_s) if heartbeat_interval_s > 0 else 0.5
        self.heartbeat_interval_s = hb
        # defaults scale with the heartbeat period: suspicion needs ~3
        # missed beats, death ~8; one dwell covers two periods so a
        # peer flapping at exactly the period cannot flap the table
        self.suspect_after_s = float(suspect_after_s
                                     if suspect_after_s is not None
                                     else 3.0 * hb)
        self.down_after_s = float(down_after_s if down_after_s is not None
                                  else 8.0 * hb)
        self.hysteresis_s = float(hysteresis_s if hysteresis_s is not None
                                  else 2.0 * hb)
        self.probe_interval_s = float(probe_interval_s
                                      if probe_interval_s is not None
                                      else 2.0 * hb)
        self.suspect_failures = max(1, int(suspect_failures))
        self._clock = clock
        self._metrics = metrics
        self._lock = threading.Lock()
        now = clock()
        self._peers: Dict[int, _Peer] = {int(p): _Peer(now) for p in peers}
        self._gauges: Dict[int, tuple] = {}
        if metrics is not None:
            for p in self._peers:
                self._gauges[p] = (
                    metrics.gauge(f"forward.peer_state.{p}"),
                    metrics.gauge(f"forward.peer_overload.{p}"),
                )

    # -- internals -----------------------------------------------------------

    def _now(self, now: Optional[float]) -> float:
        return self._clock() if now is None else now

    def _transition_locked(self, peer: int, rec: _Peer, new: PeerState,
                           now: float, why: str) -> None:
        """Apply a detector verdict, subject to the hysteresis dwell:
        after any change the table holds its verdict for
        ``hysteresis_s`` — flap damping IS the anti-storm contract."""
        if new == rec.state:
            return
        if now - rec.last_transition < self.hysteresis_s:
            rec.suppressed += 1
            return
        old, rec.state = rec.state, new
        rec.last_transition = now
        rec.transitions += 1
        gauges = self._gauges.get(peer)
        if gauges is not None:
            gauges[0].set(int(new))
        logger.log(
            logging.WARNING if new != PeerState.ALIVE else logging.INFO,
            "peer %d health %s -> %s (%s)", peer, old.name, new.name, why)

    def _overload_locked(self, peer: int, rec: _Peer, state: int,
                         retry_after_s: float) -> None:
        rec.overload_state = max(0, int(state))
        rec.retry_after_s = max(0.0, float(retry_after_s))
        gauges = self._gauges.get(peer)
        if gauges is not None:
            gauges[1].set(rec.overload_state)

    # -- observations --------------------------------------------------------

    def observe_heartbeat(self, peer: int, incarnation: int = 0,
                          overload_state: int = 0,
                          retry_after_s: float = 0.0,
                          spool_lag: int = 0,
                          device_unhealthy: bool = False,
                          unhealthy_shards: tuple = (),
                          now: Optional[float] = None) -> None:
        """A full heartbeat (request or response body) from ``peer``."""
        now = self._now(now)
        with self._lock:
            rec = self._peers.get(peer)
            if rec is None:
                return
            rec.last_heard = now
            # deliberately NOT clearing fail_streak: an INCOMING beat
            # proves the peer is up, not that WE can reach it — under a
            # one-way partition the streak must keep the peer parked
            # (only an answered outbound call clears it: observe_alive /
            # observe_piggyback)
            if incarnation and incarnation != rec.incarnation:
                if rec.incarnation:
                    logger.info("peer %d restarted (incarnation %d -> %d)",
                                peer, rec.incarnation, incarnation)
                rec.incarnation = incarnation
            self._overload_locked(peer, rec, overload_state, retry_after_s)
            rec.spool_lag = max(0, int(spool_lag))
            if bool(device_unhealthy) != rec.device_unhealthy:
                logger.warning("peer %d device tier %s", peer,
                               ("unhealthy (hung dispatch, shards "
                                f"{list(unhealthy_shards) or 'ALL'})")
                               if device_unhealthy else "recovered")
            rec.device_unhealthy = bool(device_unhealthy)
            # shard-scoped refinement: which mesh shards the peer's
            # wedge attributes to (empty = whole tier).  Tracked even
            # without a flag edge — attribution can sharpen mid-episode.
            rec.unhealthy_shards = (tuple(unhealthy_shards)
                                    if device_unhealthy else ())
            if rec.fail_streak < self.suspect_failures:
                self._transition_locked(peer, rec, PeerState.ALIVE, now,
                                        "heartbeat")

    def observe_alive(self, peer: int, now: Optional[float] = None) -> None:
        """Liveness-only evidence: a delivered batch, any answered RPC
        (even an application error — the peer computed a reply)."""
        now = self._now(now)
        with self._lock:
            rec = self._peers.get(peer)
            if rec is None:
                return
            rec.last_heard = now
            rec.fail_streak = 0
            self._transition_locked(peer, rec, PeerState.ALIVE, now,
                                    "answered")

    def observe_failure(self, peer: int, now: Optional[float] = None) -> None:
        """A transport failure toward ``peer`` (connect refused, timeout,
        dropped mid-call).  A streak escalates without waiting for
        heartbeat silence — the sender learns from its own traffic."""
        now = self._now(now)
        with self._lock:
            rec = self._peers.get(peer)
            if rec is None:
                return
            rec.fail_streak += 1
            if rec.fail_streak >= 3 * self.suspect_failures:
                self._transition_locked(peer, rec, PeerState.DOWN, now,
                                        f"{rec.fail_streak} send failures")
            elif rec.fail_streak >= self.suspect_failures:
                self._transition_locked(peer, rec, PeerState.SUSPECT, now,
                                        f"{rec.fail_streak} send failures")

    def observe_piggyback(self, peer: int, headers: Dict[str, str],
                          now: Optional[float] = None) -> None:
        """Overload state riding an ordinary response's headers — the
        fast path that beats the heartbeat period on a busy fabric."""
        raw = headers.get(HEADER_OVERLOAD)
        if raw is None:
            return
        try:
            state = int(raw)
            retry = float(headers.get(HEADER_RETRY_AFTER, 0.0))
        except (TypeError, ValueError):
            return
        now = self._now(now)
        with self._lock:
            rec = self._peers.get(peer)
            if rec is None:
                return
            rec.last_heard = now
            rec.fail_streak = 0
            self._overload_locked(peer, rec, state, retry)
            self._transition_locked(peer, rec, PeerState.ALIVE, now,
                                    "piggyback")

    def tick(self, now: Optional[float] = None) -> None:
        """Interval detector: silence since ``last_heard`` votes the
        state up; the hysteresis dwell in ``_transition_locked`` keeps
        the verdict stable."""
        now = self._now(now)
        with self._lock:
            for peer, rec in self._peers.items():
                silent = now - rec.last_heard
                if silent >= self.down_after_s:
                    by_silence = PeerState.DOWN
                elif silent >= self.suspect_after_s:
                    by_silence = PeerState.SUSPECT
                else:
                    by_silence = PeerState.ALIVE
                if rec.fail_streak >= 3 * self.suspect_failures:
                    by_streak = PeerState.DOWN
                elif rec.fail_streak >= self.suspect_failures:
                    by_streak = PeerState.SUSPECT
                else:
                    by_streak = PeerState.ALIVE
                desired = max(by_silence, by_streak)
                self._transition_locked(peer, rec, PeerState(desired), now,
                                        f"silent {silent:.2f}s")

    # -- consumer queries ----------------------------------------------------

    def state(self, peer: int) -> PeerState:
        with self._lock:
            rec = self._peers.get(peer)
            return rec.state if rec is not None else PeerState.ALIVE

    def overload_state(self, peer: int) -> int:
        with self._lock:
            rec = self._peers.get(peer)
            return rec.overload_state if rec is not None else 0

    def retry_after(self, peer: int) -> float:
        with self._lock:
            rec = self._peers.get(peer)
            return rec.retry_after_s if rec is not None else 0.0

    def can_drain(self, peer: int) -> bool:
        """Full-drain eligibility: ALIVE and not advertising SHEDDING+.
        Unknown peers drain (the table only restrains known trouble)."""
        with self._lock:
            rec = self._peers.get(peer)
            if rec is None:
                return True
            return (rec.state == PeerState.ALIVE
                    and rec.overload_state < _SHED_THRESHOLD
                    # the peer's RPC plane answers but its device tier
                    # is wedged (hung-step watchdog): forwarded rows
                    # would pile into a queue nothing drains — park
                    # them in the spool until the flag clears
                    and not rec.device_unhealthy)

    def probe_ready(self, peer: int, now: Optional[float] = None) -> bool:
        """Non-stamping peek: is a probe currently allowed?  (The flush
        loop uses this to avoid spawning a sender that would park.)"""
        now = self._now(now)
        with self._lock:
            rec = self._peers.get(peer)
            return rec is None or now >= rec.next_probe_at

    def probe_due(self, peer: int, now: Optional[float] = None) -> bool:
        """Claim the next probe slot for a PARKED peer: True at most
        once per probe interval — the interval stretches to the peer's
        own Retry-After hint while it sheds, honoring its backpressure."""
        now = self._now(now)
        with self._lock:
            rec = self._peers.get(peer)
            if rec is None:
                return True
            if now < rec.next_probe_at:
                return False
            interval = self.probe_interval_s
            if rec.overload_state >= _SHED_THRESHOLD:
                interval = max(interval, rec.retry_after_s)
            rec.next_probe_at = now + interval
            return True

    def owner_pressure(self, peer: int) -> Optional[tuple]:
        """``(overload_state, retry_after_s)`` when ``peer`` advertises
        SHEDDING+ — the device-facing edge turns this into 429 / 5.03 /
        pause hints; None when the owner can take traffic."""
        with self._lock:
            rec = self._peers.get(peer)
            if rec is None or rec.overload_state < _SHED_THRESHOLD:
                return None
            return rec.overload_state, max(rec.retry_after_s, 1.0)

    # -- membership / introspection ------------------------------------------

    def set_peers(self, peers: Iterable[int]) -> None:
        """Reconcile the tracked peer set after a membership change —
        existing records (and their dwell state) are kept."""
        wanted = {int(p) for p in peers}
        now = self._clock()
        with self._lock:
            for p in wanted - set(self._peers):
                self._peers[p] = _Peer(now)
                if self._metrics is not None:
                    self._gauges[p] = (
                        self._metrics.gauge(f"forward.peer_state.{p}"),
                        self._metrics.gauge(f"forward.peer_overload.{p}"),
                    )
            for p in set(self._peers) - wanted:
                del self._peers[p]
                gauges = self._gauges.pop(p, None)
                if gauges is not None:
                    # zero first (holders of the popped Gauge see a
                    # quiet value, dashboards stop alerting on a frozen
                    # DOWN), then unregister so a long-lived fleet that
                    # churns membership doesn't accrete one gauge pair
                    # per peer that ever existed
                    gauges[0].set(0)
                    gauges[1].set(0)
                    remove = getattr(self._metrics, "remove", None)
                    if remove is not None:
                        remove(f"forward.peer_state.{p}",
                               f"forward.peer_overload.{p}")

    def transitions(self, peer: int) -> int:
        with self._lock:
            rec = self._peers.get(peer)
            return rec.transitions if rec is not None else 0

    def snapshot(self) -> Dict[str, dict]:
        """Admin-surface view (instance topology folds this in)."""
        now = self._clock()
        with self._lock:
            out = {}
            for peer, rec in sorted(self._peers.items()):
                ov = rec.overload_state
                out[str(peer)] = {
                    "state": rec.state.name,
                    "overload": (_OVERLOAD_NAMES[ov]
                                 if 0 <= ov < len(_OVERLOAD_NAMES)
                                 else str(ov)),
                    "retry_after_s": round(rec.retry_after_s, 3),
                    "silent_s": round(max(0.0, now - rec.last_heard), 3),
                    "incarnation": rec.incarnation,
                    "spool_lag": rec.spool_lag,
                    "fail_streak": rec.fail_streak,
                    "transitions": rec.transitions,
                    "suppressed_flaps": rec.suppressed,
                    "device_unhealthy": rec.device_unhealthy,
                    "unhealthy_shards": list(rec.unhealthy_shards),
                }
            return out
