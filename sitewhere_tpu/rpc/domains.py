"""Per-domain remote facades over the RPC fabric.

Reference: the client fabric exposes EVERY management domain remotely —
per-domain ApiChannel/ApiDemux/converters for asset/batch/device/
devicestate/event/label/schedule/tenant/user
(``sitewhere-grpc-client/.../ApiDemux.java:42-110`` + the ten per-domain
packages) — so the web-rest gateway can run on a host that owns none of
the stores.  Round 3 remoted only device-management/search/topology/
commands; this module completes the surface: a declarative per-domain
method table is bound onto the RpcServer (reusing its JWT/authority
machinery), and :class:`RemoteDomain` is the duck-typed client facade a
gateway instance swaps in for the local service object.

Marshalling: entities cross as ``jsonable`` dicts (the same wire shape
the REST layer emits), re-wrapped client-side in :class:`DotDict` so
attribute-style consumers (``user.username``) keep working;
``SearchResults`` pages cross as ``numResults``/``results`` and come
back as real ``SearchResults`` so ``page_response`` composes.  A
leading :class:`SearchCriteria` argument is carried structurally.
``EntityNotFound`` round-trips (server maps it to the ``not_found``
error frame; the facade re-raises it) so REST 404s survive remoting.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

from sitewhere_tpu.rpc.channel import RpcDemux, RpcError
from sitewhere_tpu.services.common import (
    AuthError,
    DuplicateToken,
    EntityNotFound,
    ForbiddenError,
    InvalidReference,
    SearchCriteria,
    SearchResults,
    ValidationError,
)

# Typed error frames re-raise as the service exception the REST error
# mapper already understands — remoting must not change status codes.
_RAISE_BY_CODE = {
    "not_found": EntityNotFound,
    "validation": ValidationError,
    "duplicate": DuplicateToken,
    "invalid_reference": InvalidReference,
    "unauthorized": AuthError,
    "forbidden": ForbiddenError,
}
from sitewhere_tpu.web.http import jsonable

_A = "ROLE_ADMIN"

# domain -> (Instance attribute, {method: required authority or None}).
# The surface is what the REST gateway and pipeline services actually
# call — the cross-host subset, mirroring the reference's per-domain
# gRPC services (SURVEY.md §2.3), not every SPI method.
DOMAIN_SURFACE: Dict[str, tuple] = {
    "assets": ("assets", {
        "create_asset_type": _A, "get_asset_type": None,
        "update_asset_type": _A, "list_asset_types": None,
        "delete_asset_type": _A,
        "create_asset": _A, "get_asset": None, "update_asset": _A,
        "list_assets": None, "delete_asset": _A,
    }),
    "schedules": ("schedules", {
        "create_schedule": _A, "get_schedule": None, "list_schedules": None,
        "delete_schedule": _A,
        "create_job": _A, "get_job": None, "list_jobs": None,
        "delete_job": _A, "fire": _A,
    }),
    "batch": ("batch_ops", {
        "create_batch_command_invocation": _A, "get_operation": None,
        "list_operations": None, "list_elements": None, "process_now": _A,
    }),
    "users": ("users", {
        "create_user": _A, "get_user": None, "update_user": _A,
        "delete_user": _A, "list_users": None, "authenticate": None,
        "create_granted_authority": _A, "get_granted_authority": None,
        "list_granted_authorities": None, "authorities_for": None,
    }),
    "tenants": ("tenants", {
        "create_tenant": _A, "get_tenant": None, "update_tenant": _A,
        "delete_tenant": _A, "list_tenants": None, "authorized_for": None,
        "list_tenant_templates": None, "list_dataset_templates": None,
    }),
    # Token-form methods only: dense device ids are meaningful solely
    # inside their minting host's identity map and must not cross hosts.
    "devicestate": ("device_state", {
        "get_device_state": None, "missing_device_tokens": None,
        "seen_since_tokens": None, "summary": None,
    }),
}

# Credential material must never cross a marshalling boundary — neither
# REST nor the fabric (the reference's REST marshalers drop it too).
_SCRUB_KEYS = frozenset({"hashed_password"})


def scrub(doc):
    """Drop credential fields from a marshalled entity (recursive)."""
    if isinstance(doc, dict):
        return {k: scrub(v) for k, v in doc.items() if k not in _SCRUB_KEYS}
    if isinstance(doc, list):
        return [scrub(v) for v in doc]
    return doc


def bind_domains(server, inst) -> None:
    """Register every DOMAIN_SURFACE method as ``{domain}.{method}``."""
    for domain, (attr, methods) in DOMAIN_SURFACE.items():
        svc = getattr(inst, attr, None)
        if svc is None:
            continue
        for method, authority in methods.items():
            server.register(
                f"{domain}.{method}",
                _make_handler(svc, method),
                authority=authority,
            )


def _make_handler(svc, method):
    import inspect

    fn = getattr(svc, method)
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        sig = None

    def handler(ctx, body):
        body = body or {}
        args = list(body.get("args") or [])
        kwargs = dict(body.get("kwargs") or {})
        if body.get("_criteria") is not None:
            args.insert(0, SearchCriteria(**body["_criteria"]))
        if sig is not None:
            # Bad remote ARGUMENTS answer a typed validation frame; a
            # TypeError raised inside the service stays an internal
            # fault (logged server-side) — binding first separates them.
            try:
                sig.bind(*args, **kwargs)
            except TypeError as e:
                raise ValidationError(str(e)) from e
        out = fn(*args, **kwargs)
        if isinstance(out, SearchResults):
            return {"_page": {"numResults": out.total,
                              "results": scrub(jsonable(out.results))}}
        return {"_value": scrub(jsonable(out))}

    return handler


class DotDict(dict):
    """A dict whose keys read as attributes (marshalled entities keep
    working for attribute-style consumers like ``user.username``)."""

    def __getattr__(self, name):
        try:
            return self[name]
        except KeyError:
            raise AttributeError(name) from None


def _revive(value):
    if isinstance(value, dict):
        return DotDict({k: _revive(v) for k, v in value.items()})
    if isinstance(value, list):
        return [_revive(v) for v in value]
    return value


class RemoteDomain:
    """Client facade for one domain: ``facade.method(...)`` becomes an
    RPC to the owning host, with criteria/page/entity marshalling and
    ``EntityNotFound`` re-raised for REST 404 parity."""

    # Consumed by e.g. the checkpointer: a facade holds no store to
    # snapshot/restore — the owning host does that.
    _remote_facade_ = True

    def __init__(self, demux: RpcDemux, domain: str,
                 methods: Optional[frozenset] = None):
        self._demux = demux
        self._domain = domain
        self._methods = frozenset(
            methods if methods is not None
            else DOMAIN_SURFACE[domain][1].keys())

    def __getattr__(self, name: str):
        if name.startswith("_") or name not in self._methods:
            raise AttributeError(f"{self._domain}.{name} is not remoted")

        def call(*args, **kwargs):
            body: dict = {}
            args_l = list(args)
            if args_l and isinstance(args_l[0], SearchCriteria):
                body["_criteria"] = dataclasses.asdict(args_l.pop(0))
            elif args_l and args_l[0] is None and name.startswith("list_"):
                args_l.pop(0)  # list_x(None) — the default-criteria idiom
            if args_l:
                body["args"] = jsonable(args_l)
            if kwargs:
                body["kwargs"] = jsonable(kwargs)
            try:
                resp, _ = self._demux.call(f"{self._domain}.{name}", body)
            except RpcError as e:
                exc = _RAISE_BY_CODE.get(e.error)
                if exc is not None:
                    raise exc(e.message) from None
                raise
            if "_page" in resp:
                page = resp["_page"]
                return SearchResults(
                    results=_revive(page.get("results") or []),
                    total=int(page.get("numResults", 0)))
            return _revive(resp.get("_value"))

        return call


def remote_domains(demux: RpcDemux) -> Dict[str, RemoteDomain]:
    """Facades for every domain in DOMAIN_SURFACE, keyed by domain."""
    return {d: RemoteDomain(demux, d) for d in DOMAIN_SURFACE}


def attach_remote_domains(inst, demux: RpcDemux,
                          domains: Optional[list] = None) -> None:
    """Turn ``inst`` into a gateway for the given domains: its service
    attributes are swapped for remote facades over ``demux``, so every
    REST route (late-bound ``inst.<attr>``) transparently serves against
    the owning host's stores.  Reference: web-rest consuming every
    domain through ApiDemux channels instead of local persistence."""
    for domain in domains or list(DOMAIN_SURFACE):
        attr, _ = DOMAIN_SURFACE[domain]
        setattr(inst, attr, RemoteDomain(demux, domain))
