"""Ownership migration on cluster membership change.

Reference: when Kafka consumer-group membership changes, partitions
rebalance to the surviving/new members and each consumer resumes from
the committed offset (``MicroserviceKafkaConsumer.java:116-139``); the
gRPC demux tracks replica add/remove through its DiscoveryMonitor
(``ApiDemux.java:42-110``).  Here device placement is the rendezvous
hash over the peers list (``rpc/forward.py``), so changing the peer
COUNT remaps ~1/(P+1) of devices — and the rows behind them must move:

1. **Spool requeue** (:meth:`HostForwarder.apply_membership`): every
   spooled-but-unsent batch re-splits line-by-line under the NEW
   ownership — rows for a departed peer land on their new owner (or the
   local intake) instead of waiting for a host that will never return.
2. **Record handoff** (:func:`migrate_out`): each host exports the
   devices it owns whose new owner is elsewhere — device type, device,
   active assignment, and the full DeviceState row — to
   ``migration.import`` on the new owner, which creates missing records
   idempotently and merges state newest-wins.  The exporter KEEPS its
   rows (historical events stay queryable locally and through federated
   search); new traffic routes by the new ownership.

A device whose old owner died unmigrated is not lost: its spooled
events replay to the new owner, whose auto-registration re-mints the
device (``service-device-registration`` semantics) — state rebuilds
from the stream, which is the Kafka-rebalance story exactly.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional

from sitewhere_tpu.rpc.forward import owning_process
from sitewhere_tpu.services.common import (
    DuplicateToken,
    EntityNotFound,
    InvalidReference,
    ValidationError,
)

logger = logging.getLogger("sitewhere_tpu.migration")


def plan_outgoing(inst, old_n: int, new_n: int,
                  process_id: int) -> Dict[int, List[str]]:
    """Tokens THIS host owns (old map) that move elsewhere (new map),
    grouped by their new owner."""
    from sitewhere_tpu.services.common import SearchCriteria

    out: Dict[int, List[str]] = {}
    everything = SearchCriteria(page_size=0)  # unlimited sentinel
    for device in inst.device_management.list_devices(everything):
        token = device.token
        if owning_process(token, old_n) != process_id:
            continue  # not ours to hand off
        new_owner = owning_process(token, new_n)
        if new_owner != process_id:
            out.setdefault(new_owner, []).append(token)
    return out


def export_devices(inst, tokens: List[str]) -> List[dict]:
    """Marshal the movable records for ``tokens`` (tokens-only entity
    references, so the import side resolves against ITS stores)."""
    dm = inst.device_management
    docs: List[dict] = []
    for token in tokens:
        device = dm.get_device(token)
        dtype = dm.get_device_type(device.device_type)
        assignment = dm.get_active_assignment(token)
        doc: dict = {
            "token": token,
            "deviceType": {"token": dtype.token, "name": dtype.name},
            "device": {"comments": device.comments,
                       "status": device.status},
        }
        if assignment is not None:
            doc["assignment"] = {
                "token": assignment.token,
                "customer": assignment.customer,
                "area": assignment.area,
                "asset": assignment.asset,
                "status": assignment.status,
                "active_date_s": assignment.active_date_s,
            }
        dense = inst.identity.device.lookup(token)
        if dense >= 0:
            try:
                doc["state"] = inst.device_state.export_row(int(dense))
            except Exception:
                logger.exception("state export failed for %s", token)
        docs.append(doc)
    return docs


def import_devices(inst, docs: List[dict]) -> dict:
    """Idempotently adopt exported records (the ``migration.import``
    handler): create what is absent, merge state newest-wins, never
    fail the whole batch for one bad doc."""
    created = 0
    states = 0
    errors = 0
    dm = inst.device_management
    for doc in docs or []:
        try:
            token = str(doc["token"])
            dt = doc.get("deviceType") or {}
            dt_token = str(dt.get("token") or "migrated")
            try:
                dm.get_device_type(dt_token)
            except EntityNotFound:
                dm.create_device_type(token=dt_token,
                                      name=str(dt.get("name") or dt_token))
            try:
                dm.get_device(token)
            except EntityNotFound:
                dev = doc.get("device") or {}
                dm.create_device(token=token, device_type=dt_token,
                                 comments=str(dev.get("comments") or ""),
                                 status=str(dev.get("status") or ""))
                created += 1
            a = doc.get("assignment")
            if a and dm.get_active_assignment(token) is None:
                # container references resolve against THIS host's
                # stores — drop any the importer does not hold rather
                # than fail the device handoff
                for ref, get in (("customer", dm.get_customer),
                                 ("area", dm.get_area)):
                    tok = a.get(ref)
                    if not tok:
                        continue
                    try:
                        get(tok)
                    except EntityNotFound:
                        a[ref] = None
                try:
                    dm.create_device_assignment(
                        token=(str(a["token"]) if a.get("token") else None),
                        device=token,
                        customer=a.get("customer"),
                        area=a.get("area"),
                        asset=a.get("asset"),
                        status=str(a.get("status") or "Active"))
                except (DuplicateToken, ValidationError, InvalidReference):
                    dm.create_device_assignment(device=token)
            state = doc.get("state")
            if state is not None:
                dense = inst.identity.device.lookup(token)
                if dense >= 0:
                    # under the step barrier: an in-flight pipeline step
                    # computed from the pre-import epoch would otherwise
                    # clobber this row at its commit
                    with inst.dispatcher.step_barrier():
                        applied = inst.device_state.import_row(
                            int(dense), state)
                    if applied:
                        states += 1
        except Exception:
            errors += 1
            logger.exception("migration import failed for %r",
                             doc.get("token"))
    return {"created": created, "states": states, "errors": errors}


def bind_migration(server, inst) -> None:
    server.register(
        "migration.import",
        lambda ctx, body: import_devices(inst, (body or {}).get("docs")),
        authority="ROLE_ADMIN")


def migrate_out(inst, old_n: int, new_n: int, process_id: int,
                demuxes: Dict[int, Optional[object]],
                batch: int = 256) -> dict:
    """Hand off every locally-owned device whose new owner is elsewhere.
    Unreachable owners are logged and skipped — their devices re-mint
    from the event stream via auto-registration (module docstring)."""
    plan = plan_outgoing(inst, old_n, new_n, process_id)
    moved = 0
    failed = 0
    for owner, tokens in sorted(plan.items()):
        demux = demuxes.get(owner)
        if demux is None:
            failed += len(tokens)
            logger.warning("no demux for new owner %d; %d devices not "
                           "handed off", owner, len(tokens))
            continue
        for lo in range(0, len(tokens), batch):
            part = tokens[lo:lo + batch]
            try:
                # export inside the try: a device deleted between plan
                # and export must not abort the remaining handoff
                docs = export_devices(inst, part)
                body, _ = demux.call("migration.import", {"docs": docs})
                moved += int(body.get("created", 0))
            except Exception:
                failed += len(part)
                logger.exception("handoff to %d failed (%d devices)",
                                 owner, len(part))
    return {"planned": sum(len(v) for v in plan.values()),
            "moved": moved, "failed": failed}
