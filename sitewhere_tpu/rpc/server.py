"""Server side of the RPC fabric: framed-TCP dispatch with interceptors.

Reference: ``GrpcServer.java:70-78`` builds a Netty server with a JWT
interceptor, a tenant-token interceptor, and tracing interceptors;
``EventManagementRouter.java:62`` then routes each call to the right
tenant engine off the tenant header.  Here the same three concerns —
authn, tenant scoping, span tracing — wrap every registered handler, and
routing stays a dict lookup because one process hosts every domain
service (SURVEY.md §1 L2: the 19 boot shells collapse into one
composition root).

Handlers receive ``(ctx, body)`` and return ``result`` or
``(result, attachment_bytes)``; service-layer exceptions map onto typed
error frames the client re-raises as :class:`~.channel.RpcError`.
"""

from __future__ import annotations

import logging
import socket
import socketserver
import threading
from typing import Callable, Dict, Optional, Tuple

from sitewhere_tpu.rpc import wire
from sitewhere_tpu.rpc.channel import (
    DEADLINE_ERROR_CODE,
    deadline_remaining_s,
)
from sitewhere_tpu.rpc.health import HEADER_OVERLOAD, HEADER_RETRY_AFTER
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent
from sitewhere_tpu.runtime.metrics import global_registry
from sitewhere_tpu.runtime.overload import OverloadShed
from sitewhere_tpu.services.common import (
    AuthError,
    DuplicateToken,
    EntityNotFound,
    ForbiddenError,
    InvalidReference,
    ServiceError,
    ValidationError,
)

logger = logging.getLogger("sitewhere_tpu.rpc")

_ERROR_CODES = (
    # an overloaded host's admission refusal is RETRYABLE backpressure
    # for the forwarding peer (its spool redelivers), never "internal"
    (OverloadShed, "overloaded"),
    (EntityNotFound, "not_found"),
    (DuplicateToken, "duplicate"),
    (InvalidReference, "invalid_reference"),
    (ValidationError, "validation"),
    (ForbiddenError, "forbidden"),
    (AuthError, "unauthorized"),
    (ServiceError, "service_error"),
)


class CallContext:
    """Per-call context handed to handlers (the interceptor outputs)."""

    __slots__ = ("method", "headers", "username", "authorities", "tenant",
                 "attachment", "peer")

    def __init__(self, method: str, headers: Dict[str, str],
                 username: Optional[str], authorities: Tuple[str, ...],
                 tenant: Optional[str], attachment: bytes, peer: str):
        self.method = method
        self.headers = headers
        self.username = username
        self.authorities = authorities
        self.tenant = tenant
        self.attachment = attachment
        self.peer = peer


class _Handler:
    __slots__ = ("fn", "authority", "auth_required")

    def __init__(self, fn, authority: Optional[str], auth_required: bool):
        self.fn = fn
        self.authority = authority
        self.auth_required = auth_required


class RpcServer(LifecycleComponent):
    """Framed-TCP RPC endpoint as a lifecycle component.

    ``tokens`` (a :class:`~sitewhere_tpu.security.jwt.TokenManagement`)
    enables the JWT interceptor; when set, every handler registered with
    ``auth_required=True`` (the default) rejects calls without a valid
    ``authorization`` header — matching ``JwtServerInterceptor`` fail-
    closed semantics.  ``tracer`` records a span per dispatched call.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 tokens=None, tracer=None, name: str = "rpc-server",
                 max_inflight_per_conn: int = 32, metrics=None):
        super().__init__(name)
        self._host = host
        self._port = port
        self._tokens = tokens
        self._tracer = tracer
        # instance-scoped registry when provided (co-resident instances
        # must not share counters); process-global otherwise
        self._metrics = metrics if metrics is not None else global_registry()
        self.max_inflight_per_conn = max_inflight_per_conn
        # overload piggyback source: a callable returning
        # ``(overload_state_int, retry_after_s)`` stamped into EVERY
        # response's headers (success, error, even deadline rejections)
        # so callers' health tables learn pressure at call rate — set by
        # the instance when an OverloadController exists
        self.overload_provider: Optional[
            Callable[[], Tuple[int, float]]] = None
        self._handlers: Dict[str, _Handler] = {}
        self._server: Optional[socketserver.ThreadingTCPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._conns: set = set()
        self._conns_lock = threading.Lock()

    # -- registry ------------------------------------------------------------

    def register(self, method: str, fn: Callable,
                 authority: Optional[str] = None,
                 auth_required: bool = True) -> None:
        if method in self._handlers:
            raise ValueError(f"method already registered: {method}")
        self._handlers[method] = _Handler(fn, authority, auth_required)

    @property
    def port(self) -> int:
        if self._server is None:
            raise RuntimeError("server not started")
        return self._server.server_address[1]

    @property
    def endpoint(self) -> str:
        return f"{self._host}:{self.port}"

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        outer = self

        class ConnectionHandler(socketserver.BaseRequestHandler):
            def handle(self):
                peer = "%s:%d" % self.client_address[:2]
                self.request.setsockopt(
                    socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                with outer._conns_lock:
                    outer._conns.add(self.request)
                # Clients multiplex concurrent calls on one connection
                # (channel.py correlates by request id) — so each frame
                # dispatches on its own worker and only the response
                # sendall serializes; a slow events.query never blocks a
                # state.get behind it on the same socket.  The semaphore
                # bounds in-flight dispatches per connection: when a
                # client outruns the handlers, the read loop stalls
                # (TCP backpressure) instead of spawning unboundedly.
                send_lock = threading.Lock()
                slots = threading.Semaphore(outer.max_inflight_per_conn)
                workers = []

                def dispatch_one(frame):
                    try:
                        outer._dispatch(self.request, frame, peer,
                                        send_lock)
                    finally:
                        slots.release()

                try:
                    while True:
                        frame = wire.read_frame(self.request)
                        slots.acquire()
                        w = threading.Thread(
                            target=dispatch_one, args=(frame,),
                            name=f"rpc-call-{frame.method}", daemon=True)
                        workers.append(w)
                        w.start()
                        workers = [t for t in workers if t.is_alive()]
                except ConnectionError:
                    pass   # client went away — normal
                except wire.WireError as e:
                    logger.warning("rpc %s: protocol violation: %s", peer, e)
                except OSError:
                    pass
                finally:
                    for w in workers:
                        w.join(timeout=5)
                    with outer._conns_lock:
                        outer._conns.discard(self.request)

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((self._host, self._port), ConnectionHandler)
        self._thread = threading.Thread(
            target=self._server.serve_forever, kwargs={"poll_interval": 0.1},
            name=f"{self.name}-accept", daemon=True)
        self._thread.start()
        super().start()

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        # drop established connections too — a stopped replica must not
        # keep answering (clients fail over, ApiDemux semantics)
        with self._conns_lock:
            conns, self._conns = set(self._conns), set()
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        super().stop()

    # -- dispatch ------------------------------------------------------------

    def _authenticate(self, handler: _Handler, headers: Dict[str, str]):
        """JWT interceptor: returns (username, authorities) or raises."""
        if self._tokens is None or not handler.auth_required:
            return None, ()
        token = headers.get("authorization", "")
        if token.startswith("Bearer "):
            token = token[7:]
        if not token:
            raise AuthError("authorization header required")
        username = self._tokens.username(token)          # raises on bad/expired
        authorities = tuple(self._tokens.authorities(token))
        if handler.authority and handler.authority not in authorities:
            raise ForbiddenError(
                f"{handler.authority} required for {username}")
        return username, authorities

    def _piggyback_headers(self) -> Dict[str, str]:
        """Overload state for the response metadata lane (empty when no
        provider is wired — single-host instances pay nothing)."""
        provider = self.overload_provider
        if provider is None:
            return {}
        try:
            state, retry_after = provider()
        except Exception:   # noqa: BLE001 — telemetry must not fail replies
            logger.exception("overload provider failed")
            return {}
        return {HEADER_OVERLOAD: str(int(state)),
                HEADER_RETRY_AFTER: f"{float(retry_after):.3f}"}

    def _dispatch(self, sock, frame: wire.Frame, peer: str,
                  send_lock: Optional[threading.Lock] = None) -> None:
        send_lock = send_lock or threading.Lock()
        if frame.is_response:
            logger.warning("rpc %s: response frame on server side", peer)
            return
        # Deadline gate BEFORE any work (auth included): a call whose
        # propagated budget lapsed in flight is answered with the
        # retryable deadline_expired code without executing the handler
        # — work a slow fabric already made useless is refused, not run.
        remaining = deadline_remaining_s(frame.headers)
        if remaining is not None and remaining <= 0:
            self._metrics.counter("rpc.deadline_rejected").inc()
            try:
                payload = wire.encode(wire.response_frame(
                    frame.request_id,
                    {"error": DEADLINE_ERROR_CODE,
                     "message": (f"{frame.method}: deadline expired "
                                 f"{-remaining:.3f}s before dispatch")},
                    error=True, headers=self._piggyback_headers()))
                with send_lock:
                    sock.sendall(payload)
            except OSError:
                pass
            return
        try:
            handler = self._handlers.get(frame.method)
            if handler is None:
                raise EntityNotFound(f"no such method: {frame.method}")
            username, authorities = self._authenticate(handler, frame.headers)
            ctx = CallContext(frame.method, frame.headers, username,
                              authorities, frame.headers.get("tenant"),
                              frame.attachment, peer)
            if self._tracer is not None:
                # Continue the CALLER's trace when the headers carry one
                # (the reference's server tracing interceptor reads the
                # propagated gRPC metadata) — same trace_id on both sides
                # of the boundary; start a fresh trace otherwise.
                trace = self._tracer.join(frame.headers)
                if trace is None:
                    trace = self._tracer.trace(f"rpc.{frame.method}")
                try:
                    with trace.span(f"rpc.server.{frame.method}") as span:
                        span.tag("peer", peer)
                        result = handler.fn(ctx, frame.body)
                finally:
                    # the server owns its side's retention decision: an
                    # error HERE retains these spans even when the caller
                    # drops its own (tail sampling is per-side)
                    trace.end()
            else:
                result = handler.fn(ctx, frame.body)
            attachment = b""
            if isinstance(result, tuple):
                result, attachment = result
            payload = wire.encode(wire.response_frame(
                frame.request_id, result, attachment,
                headers=self._piggyback_headers()))
            with send_lock:
                sock.sendall(payload)
        except Exception as e:     # noqa: BLE001 — every fault must answer
            code = "internal"
            for exc_type, exc_code in _ERROR_CODES:
                if isinstance(e, exc_type):
                    code = exc_code
                    break
            if code == "internal":
                logger.exception("rpc %s: %s failed", peer, frame.method)
            try:
                payload = wire.encode(wire.response_frame(
                    frame.request_id,
                    {"error": code, "message": str(e)}, error=True,
                    headers=self._piggyback_headers()))
                with send_lock:
                    sock.sendall(payload)
            except OSError:
                pass
