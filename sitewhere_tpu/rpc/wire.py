"""Binary frame protocol for the cross-host RPC fabric.

Reference: the gRPC fabric (``sitewhere-grpc-client``, SURVEY.md §1 L3)
moves protobuf request/reply frames over HTTP/2 with JWT + tenant-token
metadata headers (``JwtClientInterceptor.java``,
``TenantTokenClientInterceptor.java``).  The TPU-first redesign keeps RPC
strictly at the host boundary (SURVEY.md §2.4: in-slice lookups are
tensor gathers; "out-of-pod: plain RPC only at the boundary"), so the
fabric here is deliberately small: one length-delimited frame layout on a
plain TCP stream, no HTTP/2 machinery, no generated stubs.

Frame layout (big-endian)::

    magic     4s   b"SWR1"
    flags     u8   bit0 = response, bit1 = error response
    reserved  u8
    request_id u64 correlates a response to its request on one connection
    method    u16-prefixed utf-8   (request frames; empty on responses)
    headers   u32-prefixed JSON    (authorization / tenant / trace ids)
    body      u32-prefixed JSON    (the structured payload)
    attach    u32-prefixed bytes   (bulk lane: columnar event payloads,
                                    checkpoint blobs — kept OUT of JSON so
                                    forwarding a 1M-row NDJSON batch never
                                    round-trips through text encoding)

The separate binary attachment lane is the design point: the reference
ships Kafka payloads as protobuf ``bytes`` next to its gRPC metadata for
the same reason (``EventModelMarshaler.java``).
"""

from __future__ import annotations

import json
import socket
import struct
from typing import Dict, Optional, Tuple

# Trace context rides the JSON headers lane next to authorization/tenant
# (the reference's tracing interceptors stamp gRPC metadata the same
# way): ``trace-id`` + ``parent-id`` + ``trace-sampled`` headers —
# written by Trace.propagate, read by Tracer.join (runtime/tracing.py);
# the wire layer itself treats them as opaque headers.  The same lane
# carries the call's deadline budget (``deadline-ms``, absolute unix
# epoch milliseconds — the gRPC grpc-timeout analog; channel.py writes
# it, server.py rejects already-expired work before dispatch) and the
# response-side overload piggyback (``x-overload``/``x-retry-after``).

MAGIC = b"SWR1"
FLAG_RESPONSE = 0x01
FLAG_ERROR = 0x02

_HEADER = struct.Struct(">4sBBQ")  # magic, flags, reserved, request_id

MAX_METHOD = 256
MAX_HEADERS = 1 << 16
MAX_BODY = 1 << 24          # 16 MiB structured payload
MAX_ATTACH = 1 << 26        # 64 MiB bulk lane


class WireError(Exception):
    """Malformed frame on the stream (protocol violation — fatal for
    the connection, like an HTTP/2 GOAWAY)."""


class Frame:
    __slots__ = ("flags", "request_id", "method", "headers", "body", "attachment")

    def __init__(self, flags: int, request_id: int, method: str,
                 headers: Dict[str, str], body: object,
                 attachment: bytes = b""):
        self.flags = flags
        self.request_id = request_id
        self.method = method
        self.headers = headers
        self.body = body
        self.attachment = attachment

    @property
    def is_response(self) -> bool:
        return bool(self.flags & FLAG_RESPONSE)

    @property
    def is_error(self) -> bool:
        return bool(self.flags & FLAG_ERROR)


def request_frame(request_id: int, method: str, body: object,
                  headers: Optional[Dict[str, str]] = None,
                  attachment: bytes = b"") -> Frame:
    return Frame(0, request_id, method, headers or {}, body, attachment)


def response_frame(request_id: int, body: object,
                   attachment: bytes = b"", error: bool = False,
                   headers: Optional[Dict[str, str]] = None) -> Frame:
    """``headers`` is the response metadata lane: the server piggybacks
    its overload state (``x-overload`` / ``x-retry-after``) on every
    reply so clients learn fleet pressure at call rate — see
    ``rpc/health.py``."""
    flags = FLAG_RESPONSE | (FLAG_ERROR if error else 0)
    return Frame(flags, request_id, "", headers or {}, body, attachment)


def encode(frame: Frame) -> bytes:
    method = frame.method.encode("utf-8")
    headers = json.dumps(frame.headers, separators=(",", ":")).encode("utf-8")
    body = json.dumps(frame.body, separators=(",", ":")).encode("utf-8")
    if len(method) > MAX_METHOD:
        raise WireError(f"method too long: {len(method)}")
    if len(headers) > MAX_HEADERS:
        raise WireError(f"headers too large: {len(headers)}")
    if len(body) > MAX_BODY:
        raise WireError(f"body too large: {len(body)}")
    if len(frame.attachment) > MAX_ATTACH:
        raise WireError(f"attachment too large: {len(frame.attachment)}")
    return b"".join((
        _HEADER.pack(MAGIC, frame.flags, 0, frame.request_id),
        struct.pack(">H", len(method)), method,
        struct.pack(">I", len(headers)), headers,
        struct.pack(">I", len(body)), body,
        struct.pack(">I", len(frame.attachment)), frame.attachment,
    ))


def _read_exact(sock: socket.socket, n: int) -> bytes:
    """Read exactly ``n`` bytes or raise ConnectionError on EOF."""
    parts = []
    remaining = n
    while remaining:
        chunk = sock.recv(min(remaining, 1 << 20))
        if not chunk:
            raise ConnectionError("connection closed mid-frame"
                                  if parts or remaining != n else
                                  "connection closed")
        parts.append(chunk)
        remaining -= len(chunk)
    return b"".join(parts)


def read_frame(sock: socket.socket) -> Frame:
    """Read one frame off ``sock``; raises ConnectionError on clean or
    mid-frame EOF, WireError on protocol violations."""
    head = _read_exact(sock, _HEADER.size)
    magic, flags, _reserved, request_id = _HEADER.unpack(head)
    if magic != MAGIC:
        raise WireError(f"bad magic {magic!r}")
    (method_len,) = struct.unpack(">H", _read_exact(sock, 2))
    if method_len > MAX_METHOD:
        raise WireError(f"method too long: {method_len}")
    try:
        method = (_read_exact(sock, method_len).decode("utf-8")
                  if method_len else "")
    except UnicodeDecodeError as e:
        raise WireError(f"undecodable method name: {e}") from e
    (headers_len,) = struct.unpack(">I", _read_exact(sock, 4))
    if headers_len > MAX_HEADERS:
        raise WireError(f"headers too large: {headers_len}")
    headers_raw = _read_exact(sock, headers_len) if headers_len else b"{}"
    (body_len,) = struct.unpack(">I", _read_exact(sock, 4))
    if body_len > MAX_BODY:
        raise WireError(f"body too large: {body_len}")
    body_raw = _read_exact(sock, body_len) if body_len else b""
    try:
        headers = json.loads(headers_raw)
        body = json.loads(body_raw) if body_raw else None
    except (ValueError, UnicodeDecodeError) as e:
        # version-skewed or buggy peer: surface as a protocol violation so
        # readers drop the connection instead of dying un-handled
        raise WireError(f"undecodable frame payload: {e}") from e
    (attach_len,) = struct.unpack(">I", _read_exact(sock, 4))
    if attach_len > MAX_ATTACH:
        raise WireError(f"attachment too large: {attach_len}")
    attachment = _read_exact(sock, attach_len) if attach_len else b""
    if not isinstance(headers, dict):
        raise WireError("headers must be a JSON object")
    return Frame(flags, request_id, method, headers, body, attachment)


def write_frame(sock: socket.socket, frame: Frame) -> None:
    sock.sendall(encode(frame))


def parse_endpoint(endpoint: str) -> Tuple[str, int]:
    """``host:port`` → tuple; the static-topology discovery format
    (Consul replaced by explicit endpoint lists, SURVEY.md §2.4)."""
    host, _, port = endpoint.rpartition(":")
    if not host or not port.isdigit():
        raise ValueError(f"bad endpoint {endpoint!r} (want host:port)")
    return host, int(port)
