"""Label generation service: QR symbology for platform entities.

Reference: ``service-label-generation`` exposes named label generators
(``labels/symbology/LabelGeneratorManager.java``) and a QR generator
(``labels/symbology/QrCodeGenerator.java``) that renders an entity URL into
a PNG served over gRPC/REST.  Here a generator is a URL template + render
options; the symbology itself is :mod:`sitewhere_tpu.labels.qr` and batched
rendering is a vectorized upscale so large label runs (bench config 5) are
one array op instead of a per-label image pipeline.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

import numpy as np

from sitewhere_tpu.labels import png, qr
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent
from sitewhere_tpu.services.common import EntityNotFound, require

# Entity kinds the reference builds label URLs for (device, assignment,
# area, customer, asset — cf. the label REST surface in service-web-rest).
ENTITY_KINDS = ("device", "assignment", "area", "customer", "asset", "tenant")


@dataclasses.dataclass
class LabelGenerator:
    """A named QR label generator (reference ``ILabelGenerator``)."""

    generator_id: str
    name: str
    url_template: str = "https://sitewhere-tpu.local/{kind}/{token}"
    scale: int = 4          # pixels per module
    border: int = 4         # quiet-zone modules (spec minimum)
    ec_level: str = "M"

    def url_for(self, kind: str, token: str) -> str:
        require(kind in ENTITY_KINDS, EntityNotFound(f"unknown entity kind {kind!r}"))
        return self.url_template.format(kind=kind, token=token)


def render_modules(matrix: np.ndarray, scale: int = 4, border: int = 4) -> np.ndarray:
    """Upscale a module matrix to a grayscale image (0=dark ink, 255=light)."""
    bordered = np.pad(matrix, border, constant_values=0)
    img = np.where(bordered > 0, 0, 255).astype(np.uint8)
    return np.kron(img, np.ones((scale, scale), dtype=np.uint8))


def render_batch(matrices: Sequence[np.ndarray], scale: int = 4,
                 border: int = 4) -> np.ndarray:
    """Render many same-version QR matrices in one vectorized op.

    Returns ``uint8[B, H, W]``.  All matrices must share one size (encode
    with an explicit ``version`` to guarantee this); the upscale is a single
    broadcasted kron over the batch, the array-friendly path the mixed
    label/media benchmark exercises.
    """
    sizes = {m.shape[0] for m in matrices}
    if len(sizes) != 1:
        raise ValueError(f"mixed matrix sizes {sorted(sizes)}; pin a version")
    stack = np.stack(matrices)
    bordered = np.pad(stack, ((0, 0), (border, border), (border, border)),
                      constant_values=0)
    img = np.where(bordered > 0, 0, 255).astype(np.uint8)
    return np.kron(img, np.ones((1, scale, scale), dtype=np.uint8))


class LabelGeneratorManager(LifecycleComponent):
    """Registry of label generators (reference ``LabelGeneratorManager``)."""

    def __init__(self, generators: Optional[List[LabelGenerator]] = None):
        super().__init__("label-generation")
        self._generators: Dict[str, LabelGenerator] = {}
        # Degradation ladder (runtime/overload.py): when wired, label
        # rendering — optional, CPU-bound work — refuses with 503 from
        # DEGRADED up so its cycles go to the event path instead.
        self.load_gate = None   # Callable[[str], bool] | None
        self.refused_under_load = 0
        for gen in generators or [LabelGenerator("default", "Default QR")]:
            self.register(gen)

    def register(self, generator: LabelGenerator) -> LabelGenerator:
        self._generators[generator.generator_id] = generator
        return generator

    def _check_capacity(self) -> None:
        if self.load_gate is not None and not self.load_gate("labels"):
            from sitewhere_tpu.services.common import ServiceUnavailable

            self.refused_under_load += 1
            raise ServiceUnavailable(
                "label generation is switched off while the instance "
                "is overloaded; retry after it recovers")

    def get_generator(self, generator_id: str) -> LabelGenerator:
        gen = self._generators.get(generator_id)
        require(gen is not None, EntityNotFound(f"no label generator {generator_id!r}"))
        return gen

    def list_generators(self) -> List[LabelGenerator]:
        return list(self._generators.values())

    def generate_matrix(self, generator_id: str, kind: str, token: str) -> np.ndarray:
        self._check_capacity()
        gen = self.get_generator(generator_id)
        return qr.encode(gen.url_for(kind, token), level=gen.ec_level)

    def generate_png(self, generator_id: str, kind: str, token: str) -> bytes:
        """Entity label as PNG bytes — the REST/gRPC payload of the reference
        (``service-label-generation/.../grpc/LabelGenerationImpl.java``)."""
        self._check_capacity()
        gen = self.get_generator(generator_id)
        matrix = self.generate_matrix(generator_id, kind, token)
        return png.write_png(render_modules(matrix, gen.scale, gen.border))

    def generate_png_batch(self, generator_id: str, kind: str,
                           tokens: Sequence[str]) -> List[bytes]:
        """Batch label run: encode each token, render all in one upscale."""
        self._check_capacity()
        gen = self.get_generator(generator_id)
        payloads = [gen.url_for(kind, t) for t in tokens]
        version = max(
            qr.pick_version(len(p.encode("utf-8")), gen.ec_level) for p in payloads
        )
        matrices = [qr.encode(p, level=gen.ec_level, version=version)
                    for p in payloads]
        images = render_batch(matrices, gen.scale, gen.border)
        return [png.write_png(img) for img in images]
