"""Label generation: QR symbology + PNG rendering for platform entities.

TPU-new implementation of the reference ``service-label-generation``
(``labels/symbology/QrCodeGenerator.java``, ``LabelGeneratorManager.java``).
"""

from sitewhere_tpu.labels.manager import (  # noqa: F401
    LabelGenerator,
    LabelGeneratorManager,
    render_batch,
    render_modules,
)
from sitewhere_tpu.labels.png import read_png_size, write_png  # noqa: F401
from sitewhere_tpu.labels.qr import decode_matrix, encode  # noqa: F401
