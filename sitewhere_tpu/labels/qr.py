"""QR code encoder (ISO/IEC 18004, byte mode, versions 1-10, EC levels L/M).

Reference: ``service-label-generation`` renders entity QR labels via an
external JVM library (``service-label-generation/src/main/java/com/sitewhere/
labels/symbology/QrCodeGenerator.java``).  No QR library is baked into this
image, so the symbology is implemented here from the spec: byte-mode
segment encoding, Reed-Solomon ECC over GF(256), block interleaving, module
placement, all 8 mask patterns with penalty-based selection, and BCH-encoded
format/version info.

The output is a numpy ``uint8[N, N]`` module matrix (1 = dark).  Rendering
to PNG lives in :mod:`sitewhere_tpu.labels.png`; batched rendering for the
mixed-workload benchmark in :mod:`sitewhere_tpu.labels.png`.

A structural decoder (:func:`decode_matrix`) is included so tests can
round-trip: it re-extracts codewords from the matrix, verifies the
Reed-Solomon syndromes are zero, and returns the original payload.
"""

from __future__ import annotations

import functools
from typing import List, Optional, Tuple

import numpy as np

# --------------------------------------------------------------------------
# GF(256) arithmetic (primitive polynomial x^8+x^4+x^3+x^2+1 = 0x11d)

_EXP = np.zeros(512, dtype=np.int32)
_LOG = np.zeros(256, dtype=np.int32)
_x = 1
for _i in range(255):
    _EXP[_i] = _x
    _LOG[_x] = _i
    _x <<= 1
    if _x & 0x100:
        _x ^= 0x11D
for _i in range(255, 512):
    _EXP[_i] = _EXP[_i - 255]

# Plain-list twins of the GF tables: python-int indexing of a list is
# several times faster than extracting numpy scalars, and the RS inner
# loop is pure scalar work.
_EXP_L: List[int] = _EXP.tolist()
_LOG_L: List[int] = _LOG.tolist()


def _gf_mul(a: int, b: int) -> int:
    if a == 0 or b == 0:
        return 0
    return _EXP_L[_LOG_L[a] + _LOG_L[b]]


@functools.lru_cache(maxsize=None)
def _rs_generator(n_ec: int) -> Tuple[int, ...]:
    """Generator polynomial coefficients (descending powers), monic.

    Cached: there is one polynomial per EC-codeword count, and computing
    it cost more than the per-block division it feeds.
    """
    gen = [1]
    for i in range(n_ec):
        nxt = [0] * (len(gen) + 1)
        for j, c in enumerate(gen):
            nxt[j] ^= _gf_mul(c, 1)
            nxt[j + 1] ^= _gf_mul(c, _EXP_L[i])
        gen = nxt
    return tuple(gen)


@functools.lru_cache(maxsize=None)
def _rs_generator_logs(n_ec: int) -> Tuple[int, ...]:
    """log of each non-leading generator coefficient (-1 for zero)."""
    return tuple(_LOG_L[g] if g else -1 for g in _rs_generator(n_ec)[1:])


def rs_ecc(data: bytes, n_ec: int) -> bytes:
    """Reed-Solomon error-correction codewords for ``data``."""
    glog = _rs_generator_logs(n_ec)
    rem = [0] * n_ec
    exp, rng = _EXP_L, range(n_ec)
    for byte in data:
        factor = byte ^ rem[0]
        rem = rem[1:] + [0]
        if factor:
            lf = _LOG_L[factor]
            for i in rng:
                lg = glog[i]
                if lg >= 0:
                    rem[i] ^= exp[lf + lg]
    return bytes(rem)


def rs_syndromes_zero(codewords: bytes, n_ec: int) -> bool:
    """True iff the RS syndromes of data+ecc are all zero (no corruption)."""
    for i in range(n_ec):
        s = 0
        for byte in codewords:
            s = _gf_mul(s, int(_EXP[i])) ^ byte
        if s != 0:
            return False
    return True


# --------------------------------------------------------------------------
# Version tables (ISO 18004 table 9), byte mode, EC levels L and M.
# Per (version, level): list of (count, total_codewords, data_codewords);
# ec codewords per block = total - data (same for every block of a version).

_BLOCKS = {
    ("L", 1): [(1, 26, 19)],
    ("L", 2): [(1, 44, 34)],
    ("L", 3): [(1, 70, 55)],
    ("L", 4): [(1, 100, 80)],
    ("L", 5): [(1, 134, 108)],
    ("L", 6): [(2, 86, 68)],
    ("L", 7): [(2, 98, 78)],
    ("L", 8): [(2, 121, 97)],
    ("L", 9): [(2, 146, 116)],
    ("L", 10): [(2, 86, 68), (2, 87, 69)],
    ("M", 1): [(1, 26, 16)],
    ("M", 2): [(1, 44, 28)],
    ("M", 3): [(1, 70, 44)],
    ("M", 4): [(2, 50, 32)],
    ("M", 5): [(2, 67, 43)],
    ("M", 6): [(4, 43, 27)],
    ("M", 7): [(4, 49, 31)],
    ("M", 8): [(2, 60, 38), (2, 61, 39)],
    ("M", 9): [(3, 58, 36), (2, 59, 37)],
    ("M", 10): [(4, 69, 43), (1, 70, 44)],
}

# Alignment pattern center coordinates per version.
_ALIGN = {
    1: [],
    2: [6, 18],
    3: [6, 22],
    4: [6, 26],
    5: [6, 30],
    6: [6, 34],
    7: [6, 22, 38],
    8: [6, 24, 42],
    9: [6, 26, 46],
    10: [6, 28, 50],
}

_EC_BITS = {"L": 0b01, "M": 0b00}  # format-info EC level indicator

MAX_VERSION = 10


def _data_capacity(level: str, version: int) -> int:
    return sum(count * data for count, _, data in _BLOCKS[(level, version)])


def data_capacity_bytes(level: str, version: int) -> int:
    """Max byte-mode payload length for a version/level (header removed)."""
    # mode (4 bits) + length (8 bits for v1-9, 16 for v10+) → 12 or 20 bits
    header_bits = 12 if version <= 9 else 20
    return (8 * _data_capacity(level, version) - header_bits) // 8


def pick_version(payload_len: int, level: str) -> int:
    for version in range(1, MAX_VERSION + 1):
        if data_capacity_bytes(level, version) >= payload_len:
            return version
    raise ValueError(
        f"payload of {payload_len} bytes exceeds version-{MAX_VERSION} "
        f"level-{level} capacity"
    )


# --------------------------------------------------------------------------
# Bit assembly


def _encode_codewords(payload: bytes, level: str, version: int) -> bytes:
    """Byte-mode segment → padded data codewords (pre-ECC)."""
    n_data = _data_capacity(level, version)
    bits: List[int] = []

    def put(value: int, width: int) -> None:
        for i in range(width - 1, -1, -1):
            bits.append((value >> i) & 1)

    put(0b0100, 4)  # byte mode
    put(len(payload), 8 if version <= 9 else 16)
    for byte in payload:
        put(byte, 8)
    # terminator (up to 4 zero bits), then pad to byte boundary
    put(0, min(4, 8 * n_data - len(bits)))
    while len(bits) % 8:
        bits.append(0)
    out = bytearray()
    for i in range(0, len(bits), 8):
        byte = 0
        for b in bits[i : i + 8]:
            byte = (byte << 1) | b
        out.append(byte)
    # pad codewords 0xEC / 0x11 alternating
    pads = (0xEC, 0x11)
    i = 0
    while len(out) < n_data:
        out.append(pads[i & 1])
        i += 1
    return bytes(out)


def _interleave(data: bytes, level: str, version: int) -> bytes:
    """Split into blocks, compute per-block ECC, interleave (spec §8.6)."""
    blocks: List[bytes] = []
    eccs: List[bytes] = []
    pos = 0
    for count, total, n_data in _BLOCKS[(level, version)]:
        n_ec = total - n_data
        for _ in range(count):
            block = data[pos : pos + n_data]
            pos += n_data
            blocks.append(block)
            eccs.append(rs_ecc(block, n_ec))
    out = bytearray()
    for i in range(max(len(b) for b in blocks)):
        for b in blocks:
            if i < len(b):
                out.append(b[i])
    for i in range(max(len(e) for e in eccs)):
        for e in eccs:
            if i < len(e):
                out.append(e[i])
    return bytes(out)


# --------------------------------------------------------------------------
# Matrix construction

_FINDER = np.array(
    [
        [1, 1, 1, 1, 1, 1, 1],
        [1, 0, 0, 0, 0, 0, 1],
        [1, 0, 1, 1, 1, 0, 1],
        [1, 0, 1, 1, 1, 0, 1],
        [1, 0, 1, 1, 1, 0, 1],
        [1, 0, 0, 0, 0, 0, 1],
        [1, 1, 1, 1, 1, 1, 1],
    ],
    dtype=np.uint8,
)

_ALIGN_PAT = np.array(
    [
        [1, 1, 1, 1, 1],
        [1, 0, 0, 0, 1],
        [1, 0, 1, 0, 1],
        [1, 0, 0, 0, 1],
        [1, 1, 1, 1, 1],
    ],
    dtype=np.uint8,
)


def matrix_size(version: int) -> int:
    return 17 + 4 * version


def _function_patterns(version: int) -> Tuple[np.ndarray, np.ndarray]:
    """Return (matrix, reserved) with finder/timing/alignment/format areas
    stamped; ``reserved`` marks every non-data module."""
    n = matrix_size(version)
    mat = np.zeros((n, n), dtype=np.uint8)
    res = np.zeros((n, n), dtype=bool)

    def stamp(r: int, c: int, pat: np.ndarray) -> None:
        h, w = pat.shape
        mat[r : r + h, c : c + w] = pat
        res[r : r + h, c : c + w] = True

    # finders + separators (separators are light: leave 0, just reserve)
    stamp(0, 0, _FINDER)
    stamp(0, n - 7, _FINDER)
    stamp(n - 7, 0, _FINDER)
    res[0:8, 0:8] = True
    res[0:8, n - 8 : n] = True
    res[n - 8 : n, 0:8] = True

    # timing patterns
    for i in range(8, n - 8):
        mat[6, i] = mat[i, 6] = (i + 1) % 2
        res[6, i] = res[i, 6] = True

    # alignment patterns (skip any overlapping a finder)
    centers = _ALIGN[version]
    for r in centers:
        for c in centers:
            if (r < 9 and c < 9) or (r < 9 and c > n - 10) or (r > n - 10 and c < 9):
                continue
            stamp(r - 2, c - 2, _ALIGN_PAT)

    # format info areas (filled later) + dark module
    res[8, 0:9] = True
    res[0:9, 8] = True
    res[8, n - 8 : n] = True
    res[n - 8 : n, 8] = True
    mat[n - 8, 8] = 1  # dark module

    # version info areas (v >= 7)
    if version >= 7:
        res[0:6, n - 11 : n - 8] = True
        res[n - 11 : n - 8, 0:6] = True

    return mat, res


@functools.lru_cache(maxsize=None)
def _placement_order(version: int) -> Tuple[np.ndarray, np.ndarray]:
    """Data-cell coordinates in zigzag placement order (two columns at a
    time, right→left, skipping timing col 6) — a pure function of the
    version's reserved grid, so computed once."""
    _, res = _function_patterns(version)
    n = res.shape[0]
    rr: List[int] = []
    cc: List[int] = []
    col = n - 1
    upward = True
    while col > 0:
        if col == 6:  # vertical timing column
            col -= 1
        rows = range(n - 1, -1, -1) if upward else range(n)
        for r in rows:
            for c in (col, col - 1):
                if not res[r, c]:
                    rr.append(r)
                    cc.append(c)
        upward = not upward
        col -= 2
    r_arr, c_arr = np.asarray(rr, np.intp), np.asarray(cc, np.intp)
    # frozen: these are shared cache singletons — a caller mutating one
    # would silently corrupt every later encode/decode for this version
    r_arr.flags.writeable = False
    c_arr.flags.writeable = False
    return r_arr, c_arr


def _place_data(mat: np.ndarray, res: np.ndarray, codewords: bytes) -> None:
    """Zigzag placement via the cached per-version order; cells past the
    codeword bits are the spec's remainder bits (zero)."""
    del res  # the cached order already encodes the reserved grid
    r_idx, c_idx = _placement_order((mat.shape[0] - 17) // 4)
    bits = np.unpackbits(np.frombuffer(codewords, np.uint8))
    k = min(len(bits), len(r_idx))
    mat[r_idx[:k], c_idx[:k]] = bits[:k]
    mat[r_idx[k:], c_idx[k:]] = 0


_MASKS = [
    lambda r, c: (r + c) % 2 == 0,
    lambda r, c: r % 2 == 0,
    lambda r, c: c % 3 == 0,
    lambda r, c: (r + c) % 3 == 0,
    lambda r, c: (r // 2 + c // 3) % 2 == 0,
    lambda r, c: (r * c) % 2 + (r * c) % 3 == 0,
    lambda r, c: ((r * c) % 2 + (r * c) % 3) % 2 == 0,
    lambda r, c: ((r + c) % 2 + (r * c) % 3) % 2 == 0,
]


def _mask_grid(mask: int, n: int) -> np.ndarray:
    r, c = np.indices((n, n))
    return _MASKS[mask](r, c)


@functools.lru_cache(maxsize=None)
def _mask_stack(n: int) -> np.ndarray:
    """All 8 mask grids for symbol size ``n`` as one [8, n, n] stack
    (cached: mask patterns depend only on coordinates; frozen because
    the cache entry is shared by every encode at this size)."""
    r, c = np.indices((n, n))
    stack = np.stack([_MASKS[m](r, c) for m in range(8)])
    stack.flags.writeable = False
    return stack


def _run_penalty(grid: np.ndarray) -> int:
    """Rule 1 over rows: sum of (3 + len - 5) for same-color runs >= 5."""
    rows, n = grid.shape
    change = np.ones((rows, n), bool)
    change[:, 1:] = grid[:, 1:] != grid[:, :-1]
    # run id per cell, disambiguated across rows; bincount = run lengths
    ids = np.cumsum(change, axis=1) + (
        np.arange(rows)[:, None] * (n + 1))
    lengths = np.bincount(ids.ravel())
    runs = lengths[lengths >= 5]
    return int((runs - 2).sum())  # 3 + len - 5


def _finder_penalty(grid: np.ndarray) -> int:
    """Rule 3 over rows: 40 per 1011101 core with 4 light modules on a
    side (truncated border windows do not count, matching the spec)."""
    from numpy.lib.stride_tricks import sliding_window_view

    # border sentinel 2: never equal to light (0), so a flank that runs
    # off the symbol edge cannot satisfy the 4-light requirement
    pad = np.pad(grid.astype(np.int8), ((0, 0), (4, 4)),
                 constant_values=2)
    win = sliding_window_view(pad, 15, axis=1)  # [rows, n - 6, 15]
    pat = np.array([1, 0, 1, 1, 1, 0, 1], np.int8)
    core = (win[:, :, 4:11] == pat).all(axis=2)
    before = (win[:, :, 0:4] == 0).all(axis=2)
    after = (win[:, :, 11:15] == 0).all(axis=2)
    return 40 * int((core & (before | after)).sum())


def _penalty(mat: np.ndarray) -> int:
    """The four penalty rules of spec §8.8.2 (vectorized)."""
    n = mat.shape[0]
    score = 0
    # rule 1: runs of >= 5 same-color modules, rows and columns
    score += _run_penalty(mat) + _run_penalty(mat.T)
    # rule 2: 2x2 blocks of same color
    same = (
        (mat[:-1, :-1] == mat[:-1, 1:])
        & (mat[:-1, :-1] == mat[1:, :-1])
        & (mat[:-1, :-1] == mat[1:, 1:])
    )
    score += 3 * int(same.sum())
    # rule 3: finder-like 1011101 pattern with 4 light modules on either side
    score += _finder_penalty(mat) + _finder_penalty(mat.T)
    # rule 4: dark-module proportion deviation from 50%
    dark_pct = 100.0 * mat.sum() / (n * n)
    score += 10 * int(abs(dark_pct - 50) // 5)
    return score


def _run_penalty_all(grids: np.ndarray) -> np.ndarray:
    """Rule 1 over rows for a [m, R, n] stack → per-matrix totals [m]."""
    m, rows, n = grids.shape
    g = grids.reshape(m * rows, n)
    change = np.ones((m * rows, n), bool)
    change[:, 1:] = g[:, 1:] != g[:, :-1]
    ids = np.cumsum(change, axis=1) + (
        np.arange(m * rows)[:, None] * (n + 1))
    lengths = np.bincount(ids.ravel(), minlength=m * rows * (n + 1) + 1)
    contrib = np.where(lengths >= 5, lengths - 2, 0)
    # id space is strided (n+1) per row: fold back to per-row, then per-mask
    per_row = contrib[: m * rows * (n + 1)].reshape(m * rows, n + 1).sum(1)
    return per_row.reshape(m, rows).sum(1)


def _finder_penalty_all(grids: np.ndarray) -> np.ndarray:
    """Rule 3 over rows for a [m, R, n] stack → per-matrix totals [m].

    Slice algebra instead of a 15-wide window view: the core 1011101 is
    seven shifted slices ANDed, the 4-light flanks are prefix-sum range
    queries — no [.., 15]-materialized comparison arrays.  Window i
    (i in [0, n-6)) covers padded columns [i, i+15); border sentinel 2
    keeps a flank that runs off the symbol edge from counting as light,
    matching the truncated-window rule of the spec."""
    m, rows, n = grids.shape
    w = n - 6  # window positions per row
    g = np.pad(grids.astype(np.int8), ((0, 0), (0, 0), (4, 4)),
               constant_values=2)
    eq1 = g == 1
    eq0 = g == 0

    def s(a: np.ndarray, off: int) -> np.ndarray:
        # padded column (i+4)+off for every window position i
        return a[:, :, 4 + off: 4 + off + w]

    core = (s(eq1, 0) & s(eq0, 1) & s(eq1, 2) & s(eq1, 3)
            & s(eq1, 4) & s(eq0, 5) & s(eq1, 6))
    # exclusive prefix sums of light cells: range [a, b) light-count is
    # cp[b] - cp[a]; flanks are [i, i+4) and [i+11, i+15)
    cp = np.zeros((m, rows, g.shape[2] + 1), np.int32)
    np.cumsum(eq0, axis=2, out=cp[:, :, 1:])
    before = (cp[:, :, 4: 4 + w] - cp[:, :, 0: w]) == 4
    after = (cp[:, :, 15: 15 + w] - cp[:, :, 11: 11 + w]) == 4
    return 40 * (core & (before | after)).sum(axis=(1, 2))


def _penalty_all(mats: np.ndarray) -> np.ndarray:
    """§8.8.2 penalties for a [m, n, n] stack of candidate matrices at
    once — one set of numpy calls instead of m of them (mask selection
    evaluates all 8 masks; the per-call overhead dominated at n≤57).
    Pinned equal to per-matrix :func:`_penalty` by tests."""
    m, n, _ = mats.shape
    score = _run_penalty_all(mats) + _run_penalty_all(
        mats.transpose(0, 2, 1))
    same = (
        (mats[:, :-1, :-1] == mats[:, :-1, 1:])
        & (mats[:, :-1, :-1] == mats[:, 1:, :-1])
        & (mats[:, :-1, :-1] == mats[:, 1:, 1:])
    )
    score = score + 3 * same.sum(axis=(1, 2))
    score = score + _finder_penalty_all(mats) + _finder_penalty_all(
        mats.transpose(0, 2, 1))
    dark_pct = 100.0 * mats.sum(axis=(1, 2)) / (n * n)
    score = score + 10 * (np.abs(dark_pct - 50) // 5).astype(np.int64)
    return score


def _bch(value: int, poly: int, total_bits: int, data_bits: int) -> int:
    """Append BCH remainder bits: value << (total-data), mod poly."""
    rem = value << (total_bits - data_bits)
    poly_deg = poly.bit_length() - 1
    for i in range(total_bits - 1, poly_deg - 1, -1):
        if rem & (1 << i):
            rem ^= poly << (i - poly_deg)
    return (value << (total_bits - data_bits)) | rem


def _format_bits(level: str, mask: int) -> int:
    value = (_EC_BITS[level] << 3) | mask
    return _bch(value, 0b10100110111, 15, 5) ^ 0b101010000010010


def _version_bits(version: int) -> int:
    return _bch(version, 0b1111100100101, 18, 6)


def _write_format(mat: np.ndarray, level: str, mask: int) -> None:
    n = mat.shape[0]
    f = _format_bits(level, mask)
    bits = [(f >> i) & 1 for i in range(14, -1, -1)]  # MSB first: bit 14..0
    # copy 1 around top-left finder: bits 0..14
    coords1 = (
        [(8, c) for c in range(6)] + [(8, 7), (8, 8), (7, 8)]
        + [(r, 8) for r in range(5, -1, -1)]
    )
    # copy 2: down the right of top-right finder + left of bottom-left finder
    coords2 = [(n - 1 - r, 8) for r in range(7)] + [(8, n - 8 + c) for c in range(8)]
    for (r, c), bit in zip(coords1, bits):
        mat[r, c] = bit
    for (r, c), bit in zip(coords2, bits):
        mat[r, c] = bit


def _write_version(mat: np.ndarray, version: int) -> None:
    if version < 7:
        return
    n = mat.shape[0]
    v = _version_bits(version)
    for i in range(18):
        bit = (v >> i) & 1
        mat[i // 3, n - 11 + i % 3] = bit
        mat[n - 11 + i % 3, i // 3] = bit


def encode(payload: bytes | str, level: str = "M",
           version: Optional[int] = None, mask: Optional[int] = None) -> np.ndarray:
    """Encode ``payload`` into a QR module matrix (``uint8[N, N]``, 1=dark)."""
    if isinstance(payload, str):
        payload = payload.encode("utf-8")
    if level not in _EC_BITS:
        raise ValueError(f"EC level must be L or M, got {level!r}")
    if version is None:
        version = pick_version(len(payload), level)
    elif not 1 <= version <= MAX_VERSION:
        raise ValueError(f"version must be 1..{MAX_VERSION}")
    elif data_capacity_bytes(level, version) < len(payload):
        raise ValueError("payload too long for requested version")

    codewords = _interleave(_encode_codewords(payload, level, version), level, version)
    base, res = _function_patterns(version)
    _place_data(base, res, codewords)

    n = base.shape[0]
    if mask is not None:
        mat = base.copy()
        flip = _mask_grid(mask, n) & ~res
        mat[flip] ^= 1
        _write_format(mat, level, mask)
        _write_version(mat, version)
        return mat
    # all 8 candidates as one stack; penalties vectorized across the
    # mask axis (_penalty_all) — selection was the encoder's hot loop
    stack = np.where(_mask_stack(n) & ~res, base ^ 1, base)
    for m in range(8):
        _write_format(stack[m], level, m)
        _write_version(stack[m], version)
    return stack[int(np.argmin(_penalty_all(stack)))]


# --------------------------------------------------------------------------
# Structural decoder (for round-trip tests and journal audits)


def read_format(mat: np.ndarray) -> Tuple[str, int]:
    """Read (ec_level, mask) back from the format info around the TL finder."""
    coords = (
        [(8, c) for c in range(6)] + [(8, 7), (8, 8), (7, 8)]
        + [(r, 8) for r in range(5, -1, -1)]
    )
    f = 0
    for r, c in coords:
        f = (f << 1) | int(mat[r, c])
    f ^= 0b101010000010010
    value = f >> 10
    level_bits, mask = value >> 3, value & 0b111
    for name, bits in _EC_BITS.items():
        if bits == level_bits:
            return name, mask
    raise ValueError(f"unknown EC level bits {level_bits:#b}")


def decode_matrix(mat: np.ndarray) -> bytes:
    """Recover the payload from a module matrix produced by :func:`encode`.

    Verifies Reed-Solomon syndromes per block; raises on corruption.  Not a
    camera-image decoder — it assumes an axis-aligned, unscaled matrix.
    """
    n = mat.shape[0]
    version = (n - 17) // 4
    level, mask = read_format(mat)
    _, res = _function_patterns(version)
    unmasked = mat.copy()
    flip = _mask_grid(mask, n) & ~res
    unmasked[flip] ^= 1

    # extract bits in placement order — the SAME cached order encode
    # placed them in, so the two sides cannot drift
    r_idx, c_idx = _placement_order(version)
    bits = unmasked[r_idx, c_idx].tolist()
    total = sum(count * tot for count, tot, _ in _BLOCKS[(level, version)])
    codewords = bytearray()
    for i in range(total):
        byte = 0
        for b in bits[8 * i : 8 * i + 8]:
            byte = (byte << 1) | b
        codewords.append(byte)

    # de-interleave
    shapes: List[Tuple[int, int]] = []  # (n_data, n_ec) per block
    for count, tot, n_data in _BLOCKS[(level, version)]:
        shapes += [(n_data, tot - n_data)] * count
    data_blocks: List[bytearray] = [bytearray() for _ in shapes]
    ecc_blocks: List[bytearray] = [bytearray() for _ in shapes]
    pos = 0
    for i in range(max(d for d, _ in shapes)):
        for bi, (d, _) in enumerate(shapes):
            if i < d:
                data_blocks[bi].append(codewords[pos])
                pos += 1
    for i in range(max(e for _, e in shapes)):
        for bi, (_, e) in enumerate(shapes):
            if i < e:
                ecc_blocks[bi].append(codewords[pos])
                pos += 1
    for bi, (d, e) in enumerate(shapes):
        if not rs_syndromes_zero(bytes(data_blocks[bi]) + bytes(ecc_blocks[bi]), e):
            raise ValueError(f"RS syndrome check failed for block {bi}")

    stream = b"".join(bytes(b) for b in data_blocks)
    # parse byte-mode segment
    def get_bits(byte_stream: bytes, start: int, width: int) -> int:
        v = 0
        for i in range(start, start + width):
            v = (v << 1) | ((byte_stream[i // 8] >> (7 - i % 8)) & 1)
        return v

    mode = get_bits(stream, 0, 4)
    if mode != 0b0100:
        raise ValueError(f"expected byte mode, got {mode:#06b}")
    len_width = 8 if version <= 9 else 16
    length = get_bits(stream, 4, len_width)
    start = 4 + len_width
    payload = bytearray(
        get_bits(stream, start + 8 * i, 8) for i in range(length)
    )
    return bytes(payload)
