"""Minimal PNG writer (stdlib only) for label images.

The reference emits label PNGs through AWT/ImageIO inside
``service-label-generation``; here a grayscale or RGB ``uint8`` array is
serialized directly: IHDR + IDAT (zlib, filter 0) + IEND.
"""

from __future__ import annotations

import struct
import zlib

import numpy as np


def _chunk(tag: bytes, body: bytes) -> bytes:
    return (
        struct.pack(">I", len(body))
        + tag
        + body
        + struct.pack(">I", zlib.crc32(tag + body) & 0xFFFFFFFF)
    )


def write_png(img: np.ndarray) -> bytes:
    """Serialize ``uint8[H, W]`` (grayscale) or ``uint8[H, W, 3]`` (RGB)."""
    img = np.asarray(img, dtype=np.uint8)
    if img.ndim == 2:
        color_type, channels = 0, 1
    elif img.ndim == 3 and img.shape[2] == 3:
        color_type, channels = 2, 3
    else:
        raise ValueError(f"expected [H,W] or [H,W,3], got {img.shape}")
    h, w = img.shape[:2]
    raw = img.reshape(h, w * channels)
    # prepend filter byte 0 to each scanline
    scanlines = np.concatenate(
        [np.zeros((h, 1), dtype=np.uint8), raw], axis=1
    ).tobytes()
    ihdr = struct.pack(">IIBBBBB", w, h, 8, color_type, 0, 0, 0)
    return (
        b"\x89PNG\r\n\x1a\n"
        + _chunk(b"IHDR", ihdr)
        + _chunk(b"IDAT", zlib.compress(scanlines, 6))
        + _chunk(b"IEND", b"")
    )


def read_png_size(data: bytes) -> tuple:
    """Parse (width, height) from a PNG header (test helper)."""
    if data[:8] != b"\x89PNG\r\n\x1a\n":
        raise ValueError("not a PNG")
    w, h = struct.unpack(">II", data[16:24])
    return w, h
