"""Outbound connectors: deliver filtered event batches to external systems.

Reference: ``service-outbound-connectors`` — ``IOutboundConnector``
processes event batches (``spi/IOutboundConnector.java:45-54``), wrapped by
``FilteredOutboundConnector``; implementations publish to MQTT (with
Groovy multicast + route building), RabbitMQ, SQS, EventHub, InitialState,
dweet.io, Solr, or a user Groovy script.  Image constraints (no external
broker/SaaS clients) map those onto:

- :class:`MqttOutboundConnector` — MQTT publish with pluggable multicaster
  + route builder (the ``AllWithSpecificationMulticaster`` shape).
- :class:`FileConnector` — durable JSONL export (the external-indexer
  analog; doubles as the Solr-connector seam for a real indexer).
- :class:`CallbackConnector` — arbitrary Python callable (Groovy analog).

All connectors receive *column batches* + a surviving-row mask and marshal
rows only after filtering, so the host cost scales with delivered events,
not stream volume.
"""

from __future__ import annotations

import json
import logging
import os
import time
import threading
from typing import Callable, Dict, List, Optional

import numpy as np

from sitewhere_tpu.outbound.filters import apply_filters
from sitewhere_tpu.runtime import faults
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent
from sitewhere_tpu.runtime.resilience import (
    Backoff,
    CircuitBreaker,
    RetriesExhausted,
    RetryPolicy,
    call_with_retry,
    dead_letter,
)
from sitewhere_tpu.schema import EventType

# One immediate retry on a fresh connection: a keep-alive socket the
# server already closed fails the first write/read, not the request.
_RECONNECT_RETRY = RetryPolicy(initial_s=0.0, max_s=0.0, max_attempts=1)

logger = logging.getLogger("sitewhere_tpu.outbound")

Columns = Dict[str, np.ndarray]

def _camel(snake: str) -> str:
    head, *rest = snake.lower().split("_")
    return head + "".join(p.capitalize() for p in rest)


# camelCase display names derived from the schema enum — the single source
# of event-type codes stays sitewhere_tpu.schema.EventType.
_EVENT_TYPE_NAMES = {int(et): _camel(et.name) for et in EventType}


def marshal_row(cols: Columns, row: int, identity=None) -> dict:
    """One event row → JSON-able dict (REST/export marshaling).

    With an :class:`~sitewhere_tpu.ids.IdentityMap`, dense handles resolve
    back to tokens (host-side only — the reverse of the ingest edge).
    """
    etype = int(cols["event_type"][row])
    doc = {
        "eventType": _EVENT_TYPE_NAMES.get(etype, etype),
        "deviceId": int(cols["device_id"][row]),
        "tenantId": int(cols["tenant_id"][row]),
        "ts_s": int(cols["ts_s"][row]),
        "ts_ns": int(cols["ts_ns"][row]),
    }
    if identity is not None:
        token = identity.device.token_of(doc["deviceId"])
        if token is not None:
            doc["device"] = token
    if etype == EventType.MEASUREMENT:
        doc["mtypeId"] = int(cols["mtype_id"][row])
        doc["value"] = float(cols["value"][row])
    elif etype == EventType.LOCATION:
        doc.update(
            lat=float(cols["lat"][row]),
            lon=float(cols["lon"][row]),
            elevation=float(cols["elevation"][row]),
        )
    elif etype == EventType.ALERT:
        doc.update(
            alertCode=int(cols["alert_code"][row]),
            alertLevel=int(cols["alert_level"][row]),
        )
    elif etype in (EventType.COMMAND_INVOCATION, EventType.COMMAND_RESPONSE):
        doc["commandId"] = int(cols["command_id"][row])
    for name in ("area_id", "customer_id", "asset_id", "assignment_id", "device_type_id"):
        if name in cols:
            doc[_camel(name)] = int(cols[name][row])
    return doc


class OutboundConnector(LifecycleComponent):
    """Base: filter chain + batch delivery + failure counters.

    Reference: ``FilteredOutboundConnector`` + the per-connector metrics of
    ``OutboundConnector.java``.

    With a :class:`~sitewhere_tpu.runtime.resilience.CircuitBreaker`
    attached, a connector whose ``deliver`` keeps RAISING trips the
    breaker and subsequent batches are SHED (counted in ``shed``,
    summarized to ``dead_letters``) instead of queueing behind a dead
    sink — the worker queue stays drained and the half-open probe
    re-admits traffic once the sink recovers.  Only exceptions that
    escape ``deliver`` count as failures: connectors that swallow their
    own errors keep their existing semantics.
    """

    def __init__(self, connector_id: str, filters=None,
                 breaker: Optional[CircuitBreaker] = None,
                 dead_letters=None, priority: bool = False):
        super().__init__(f"connector-{connector_id}")
        self.connector_id = connector_id
        self.filters = list(filters or [])
        self.breaker = breaker
        self.dead_letters = dead_letters
        # Overload ladder contract: priority connectors (alert
        # notifiers, command bridges) keep receiving batches in
        # SHEDDING/EMERGENCY; non-priority fan-out (search indexers,
        # bulk exporters, analytics taps) sheds first.
        self.priority = bool(priority)
        self._lock = threading.Lock()
        self.processed = 0
        self.errors = 0
        self.shed = 0

    def process_batch(self, cols: Columns, mask: np.ndarray) -> int:
        """Filter and deliver one column batch; returns rows delivered."""
        try:
            surviving = apply_filters(self.filters, cols, mask)
        except Exception:
            # a crashing filter is a connector error too (the manager
            # only logs); it says nothing about the SINK, so the
            # breaker's outcome window is left alone
            with self._lock:
                self.errors += 1
            raise
        n = int(surviving.sum())
        if not n:
            return 0
        if self.breaker is not None and not self.breaker.allow():
            with self._lock:
                self.shed += n
            dead_letter(self.dead_letters, {
                "kind": "connector-shed",
                "connector": self.connector_id,
                "rows": n,
            })
            return 0
        try:
            faults.fire("outbound.deliver")
            self.deliver(cols, surviving)
        except Exception:
            # the connector owns its error count (the manager only
            # isolates + logs); the breaker sees every escaped failure
            with self._lock:
                self.errors += 1
            if self.breaker is not None:
                self.breaker.record_failure()
            raise
        if self.breaker is not None:
            self.breaker.record_success()
        with self._lock:
            self.processed += n
        return n

    def deliver(self, cols: Columns, mask: np.ndarray) -> None:  # override
        raise NotImplementedError


class CallbackConnector(OutboundConnector):
    """Deliver through any callable (the Groovy-connector analog)."""

    def __init__(self, connector_id: str, fn: Callable[[Columns, np.ndarray], None],
                 filters=None, **kw):
        super().__init__(connector_id, filters, **kw)
        self.fn = fn

    def deliver(self, cols: Columns, mask: np.ndarray) -> None:
        self.fn(cols, mask)


class FileConnector(OutboundConnector):
    """Append surviving events as JSON lines (external-indexer analog)."""

    def __init__(self, connector_id: str, path: str, identity=None,
                 filters=None, **kw):
        super().__init__(connector_id, filters, **kw)
        self.path = path
        self.identity = identity
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)

    def deliver(self, cols: Columns, mask: np.ndarray) -> None:
        rows = np.nonzero(mask)[0]
        with open(self.path, "a") as f:
            for row in rows:
                f.write(json.dumps(marshal_row(cols, int(row), self.identity)) + "\n")


class HttpConnector(OutboundConnector):
    """POST surviving events as a JSON array to a webhook URL.

    Reference: the SaaS push connectors — ``InitialStateEventsConnector``
    and ``DweetConnector`` (``service-outbound-connectors/.../initialstate``,
    ``.../dweetio``) are HTTPS POSTs of marshaled events to a per-account
    endpoint.  One generic webhook connector covers the shape; per-service
    envelopes are a ``transform`` away.  Delivery is batched (one request
    per surviving batch, not per event) and reuses the connection
    (keep-alive) until an error forces a reconnect.
    """

    def __init__(
        self,
        connector_id: str,
        url: str,
        identity=None,
        headers: Optional[Dict[str, str]] = None,
        transform: Optional[Callable[[List[dict]], bytes]] = None,
        timeout_s: float = 10.0,
        filters=None,
        **kw,
    ):
        super().__init__(connector_id, filters, **kw)
        from urllib.parse import urlsplit

        parts = urlsplit(url)
        if parts.scheme not in ("http", "https"):
            raise ValueError(f"unsupported webhook scheme: {parts.scheme!r}")
        self._scheme = parts.scheme
        self._netloc = parts.netloc
        self._path = parts.path or "/"
        if parts.query:
            self._path += "?" + parts.query
        self.identity = identity
        self.headers = dict(headers or {})
        self.transform = transform
        self.timeout_s = timeout_s
        self._conn = None

    def _connect(self):
        import http.client

        cls = (http.client.HTTPSConnection if self._scheme == "https"
               else http.client.HTTPConnection)
        return cls(self._netloc, timeout=self.timeout_s)

    def stop(self) -> None:
        if self._conn is not None:
            try:
                self._conn.close()
            except Exception:
                pass
            self._conn = None
        super().stop()

    def _post(self, body: bytes, headers: Dict[str, str]) -> int:
        """One POST exchange, returning the status; transport failures
        drop the keep-alive connection and raise (retryable)."""
        if self._conn is None:
            self._conn = self._connect()
        try:
            self._conn.request("POST", self._path, body=body,
                               headers=headers)
            resp = self._conn.getresponse()
            resp.read()
            return resp.status
        except Exception:
            try:
                self._conn.close()
            except Exception:
                pass
            self._conn = None
            raise

    def deliver(self, cols: Columns, mask: np.ndarray) -> None:
        rows = np.nonzero(mask)[0]
        docs = [marshal_row(cols, int(r), self.identity) for r in rows]
        body = (self.transform(docs) if self.transform is not None
                else json.dumps(docs).encode("utf-8"))
        headers = {"Content-Type": "application/json", **self.headers}
        try:
            status = call_with_retry(
                lambda: self._post(body, headers), _RECONNECT_RETRY,
                retry_on=(Exception,),
                name=f"outbound.{self.connector_id}.post")
        except RetriesExhausted as e:
            logger.exception("%s POST %s failed", self.name, self._path)
            # raise so process_batch counts the error and the breaker
            # sees the dead sink (the manager isolates it from siblings)
            raise DeliveryFailed(
                f"POST {self._path} failed: {e.__cause__}") from e.__cause__
        # only 2xx is delivery: http.client does not follow redirects,
        # so a 3xx means the events never arrived — an answered error is
        # NOT retried (the reference webhook connectors likewise treat a
        # rejection as final)
        if not 200 <= status < 300:
            logger.error("%s POST %s rejected (%d)", self.name, self._path,
                         status)
            raise DeliveryFailed(f"webhook returned {status}")


class DeliveryFailed(Exception):
    """Webhook answered with an error status (no reconnect needed)."""


class MqttOutboundConnector(OutboundConnector):
    """Publish surviving events to MQTT topics via multicast routing.

    Reference: ``mqtt/MqttOutboundConnector.java`` with
    ``AllWithSpecificationMulticaster`` (route per matching device-type) and
    a route builder computing the topic.  ``multicaster`` maps an event dict
    → list of route strings; ``route_builder`` maps (route, event) → topic.
    """

    def __init__(
        self,
        connector_id: str,
        client,
        topic: str = "sitewhere/output",
        identity=None,
        multicaster: Optional[Callable[[dict], List[str]]] = None,
        route_builder: Optional[Callable[[str, dict], str]] = None,
        qos: int = 0,
        filters=None,
        **kw,
    ):
        super().__init__(connector_id, filters, **kw)
        self.client = client
        self.topic = topic
        self.identity = identity
        self.multicaster = multicaster
        self.route_builder = route_builder
        self.qos = qos

    def deliver(self, cols: Columns, mask: np.ndarray) -> None:
        rows = np.nonzero(mask)[0]
        for row in rows:
            doc = marshal_row(cols, int(row), self.identity)
            payload = json.dumps(doc).encode("utf-8")
            if self.multicaster is not None:
                routes = self.multicaster(doc)
            else:
                routes = [self.topic]
            for route in routes:
                topic = (
                    self.route_builder(route, doc)
                    if self.route_builder is not None
                    else route
                )
                try:
                    self.client.publish(topic, payload, qos=self.qos)
                except Exception:
                    with self._lock:
                        self.errors += 1
                    logger.exception("%s publish to %s failed", self.name, topic)


class IndexPushConnector(HttpConnector):
    """Push enriched events to an external search index in bulk.

    Reference: ``SolrOutboundConnector``
    (``service-outbound-connectors/.../solr/SolrOutboundConnector.java``)
    indexes every surviving event into an external Solr core — the
    write side of the federated-search story (the repo's own providers
    are query-side over its own store).  This is the batched variant of
    :class:`HttpConnector`:

    - events ACCUMULATE across pipeline batches and flush as ONE bulk
      request when ``bulk_rows`` is reached or ``bulk_interval_s``
      elapses (the Solr client's buffered-add semantics);
    - a failed bulk is RETAINED and retried with exponential backoff —
      backpressure is a bounded buffer (``max_buffer_rows``); beyond it
      the OLDEST docs drop and are counted (``dropped``), never the
      pipeline blocked;
    - the default wire shape is a JSON array POSTed to the URL
      (Solr ``/update`` accepts exactly that); ``bulk_format`` swaps in
      e.g. an Elasticsearch ``_bulk`` NDJSON builder.
    """

    def __init__(
        self,
        connector_id: str,
        url: str,
        identity=None,
        headers: Optional[Dict[str, str]] = None,
        bulk_rows: int = 500,
        bulk_interval_s: float = 1.0,
        max_buffer_rows: int = 50_000,
        backoff_s: float = 0.5,
        max_backoff_s: float = 30.0,
        bulk_format: Optional[Callable[[List[dict]], bytes]] = None,
        timeout_s: float = 10.0,
        filters=None,
        **kw,
    ):
        super().__init__(connector_id, url, identity=identity,
                         headers=headers, timeout_s=timeout_s,
                         filters=filters, **kw)
        self.bulk_rows = bulk_rows
        self.bulk_interval_s = bulk_interval_s
        self.max_buffer_rows = max_buffer_rows
        self.backoff_s = backoff_s
        self.max_backoff_s = max_backoff_s
        self.bulk_format = bulk_format or (
            lambda docs: json.dumps(docs).encode("utf-8"))
        self._pending: List[dict] = []
        self._inflight: set = set()
        self._last_flush = time.monotonic()
        # failed-bulk retry schedule (was ad-hoc _retry_at/_cur_backoff)
        self._backoff = Backoff(
            RetryPolicy(initial_s=backoff_s, max_s=max_backoff_s),
            name=f"outbound.{connector_id}.bulk")
        self.indexed = 0
        self.dropped = 0
        # serializes whole flushes: the interval timer and a delivery
        # thread passing the due-check together must not post the same
        # docs twice (also guards _conn, which is not thread-safe)
        self._flush_lock = threading.Lock()
        self._timer: Optional[threading.Thread] = None
        self._timer_stop = threading.Event()

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        super().start()
        self._timer_stop.clear()
        self._timer = threading.Thread(
            target=self._tick, name=f"{self.name}-flusher", daemon=True)
        self._timer.start()

    def stop(self) -> None:
        self._timer_stop.set()
        if self._timer is not None:
            self._timer.join(timeout=5)
            self._timer = None
        self._flush(force=True)  # best-effort final push
        super().stop()

    def _tick(self) -> None:
        while not self._timer_stop.wait(max(0.05, self.bulk_interval_s / 2)):
            try:
                self._flush()
            except Exception:
                logger.exception("%s interval flush failed", self.name)

    # -- delivery ------------------------------------------------------------

    def deliver(self, cols: Columns, mask: np.ndarray) -> None:
        rows = np.nonzero(mask)[0]
        docs = [marshal_row(cols, int(r), self.identity) for r in rows]
        with self._lock:
            self._pending.extend(docs)
            overflow = len(self._pending) - self.max_buffer_rows
            if overflow > 0:
                # drop OLDEST (the index is a derived view; newest data
                # wins when the sink cannot keep up) — but never a doc
                # an in-flight bulk is carrying: it is being indexed,
                # not dropped, and the post-send identity delete must
                # find it in place
                keep: List[dict] = []
                dropped = 0
                for d in self._pending:
                    if dropped < overflow and id(d) not in self._inflight:
                        dropped += 1
                        continue
                    keep.append(d)
                self._pending = keep
                self.dropped += dropped
        self._flush()

    def _flush(self, force: bool = False) -> None:
        with self._flush_lock:
            self._flush_locked(force)

    def _flush_locked(self, force: bool) -> None:
        now = time.monotonic()
        with self._lock:
            n = len(self._pending)
            due = force or n >= self.bulk_rows or (
                n > 0 and now - self._last_flush >= self.bulk_interval_s)
            if not due or n == 0 or (not force
                                     and not self._backoff.due(now)):
                return
            batch = self._pending[:]
            self._inflight = {id(d) for d in batch}
        ok = False
        try:
            body = self.bulk_format(batch)
            ok = self._post_bulk(body)
        finally:
            if ok:
                with self._lock:
                    # remove exactly the docs this flush sent, BY
                    # IDENTITY: deliveries that landed mid-request stay
                    # pending (a head-count delete would eat them)
                    sent = self._inflight
                    self._pending = [d for d in self._pending
                                     if id(d) not in sent]
                    self._inflight = set()
                    self.indexed += len(batch)
                    self._last_flush = now
                    self._backoff.reset()
            else:
                with self._lock:
                    self._inflight = set()
                    self.errors += 1
                    self._backoff.defer(now)

    def _post_bulk(self, body: bytes) -> bool:
        headers = {"Content-Type": "application/json", **self.headers}
        try:
            status = call_with_retry(
                lambda: self._post(body, headers), _RECONNECT_RETRY,
                retry_on=(Exception,),
                name=f"outbound.{self.connector_id}.bulk-post")
        except RetriesExhausted:
            logger.exception("%s bulk POST %s failed", self.name,
                             self._path)
            return False
        if not 200 <= status < 300:
            logger.error("%s bulk POST %s rejected (%d)",
                         self.name, self._path, status)
            return False
        return True
