"""Event search providers — federated search over indexed events.

Reference: ``service-event-search`` manages named ``ISearchProvider``s
(Solr impl) queried through the REST ``ExternalSearch`` controller
(SURVEY.md §2.2).  Here the built-in provider searches the columnar
:class:`~sitewhere_tpu.services.event_store.EventStore` directly (the
store *is* the index — chunk pruning + vectorized masks), and the manager
keeps the named-provider SPI so an external indexer can be plugged in.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from sitewhere_tpu.services.common import (
    EntityNotFound,
    SearchCriteria,
    SearchResults,
)
from sitewhere_tpu.services.event_store import EventRecord, EventStore


class EventSearchProvider:
    """Search the event store (reference: ``SolrSearchProvider``)."""

    def __init__(self, provider_id: str, store: EventStore, name: str = ""):
        self.provider_id = provider_id
        self.name = name or provider_id
        self.store = store

    def search(self, criteria: Optional[SearchCriteria] = None, **filters) -> SearchResults[EventRecord]:
        return self.store.query(criteria, **filters)


class SearchProvidersManager:
    """Named provider registry (reference: ``SearchProviderManager``)."""

    def __init__(self, providers: Optional[List[EventSearchProvider]] = None):
        self._providers: Dict[str, EventSearchProvider] = {
            p.provider_id: p for p in providers or []
        }

    def add_provider(self, provider: EventSearchProvider) -> None:
        self._providers[provider.provider_id] = provider

    def get_provider(self, provider_id: str) -> EventSearchProvider:
        p = self._providers.get(provider_id)
        if p is None:
            raise EntityNotFound(f"search provider {provider_id}")
        return p

    def list_providers(self) -> List[EventSearchProvider]:
        return list(self._providers.values())
