"""Event search providers — federated search over indexed events.

Reference: ``service-event-search`` manages named ``ISearchProvider``s
(Solr impl) queried through the REST ``ExternalSearch`` controller
(SURVEY.md §2.2).  Here the built-in provider searches the columnar
:class:`~sitewhere_tpu.services.event_store.EventStore` directly (the
store *is* the index — chunk pruning + vectorized masks), and the manager
keeps the named-provider SPI so an external indexer can be plugged in.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from sitewhere_tpu.services.common import (
    EntityNotFound,
    SearchCriteria,
    SearchResults,
)
from sitewhere_tpu.services.event_store import EventRecord, EventStore


class EventSearchProvider:
    """Search the event store (reference: ``SolrSearchProvider``)."""

    def __init__(self, provider_id: str, store: EventStore, name: str = ""):
        self.provider_id = provider_id
        self.name = name or provider_id
        self.store = store

    def search(self, criteria: Optional[SearchCriteria] = None, **filters) -> SearchResults[EventRecord]:
        return self.store.query(criteria, **filters)


class TokenSearchAdapter:
    """Token-level filters onto the local store's dense-id query.

    Federated queries carry tokens (hosts don't share dense handles);
    the local leg resolves them against this host's identity map — an
    unknown token simply matches nothing here (it may be another
    host's device)."""

    def __init__(self, provider_id: str, store: EventStore, identity,
                 device_management, name: str = ""):
        self.provider_id = provider_id
        self.name = name or provider_id
        self.store = store
        self.identity = identity
        self.device_management = device_management

    def search(self, criteria: Optional[SearchCriteria] = None,
               **filters) -> SearchResults[EventRecord]:
        resolved = {}
        token = filters.pop("device_token", None)
        if token is not None:
            dense = self.identity.device.lookup(token)
            if dense < 0:
                return SearchResults(results=[], total=0)
            resolved["device_id"] = int(dense)
        token = filters.pop("assignment_token", None)
        if token is not None:
            handle = self.device_management.handle_for("assignment", token)
            if handle < 0:
                return SearchResults(results=[], total=0)
            resolved["assignment_id"] = int(handle)
        resolved.update(filters)
        self.store.flush()
        return self.store.query(criteria, **resolved)


class RemoteSearchProvider:
    """Search a PEER instance's event store over the RPC fabric.

    Reference: external search providers query a remote index over the
    network (``SolrSearchProvider``).  In a multi-host topology each
    host's store indexes its own shards' events (keyed forwarding,
    ``rpc/forward.py``), so a peer's store is exactly such a remote
    index — reached through ``events.query`` on the fabric.  Results are
    the wire dicts (already marshaled by the peer)."""

    def __init__(self, provider_id: str, demux, name: str = ""):
        self.provider_id = provider_id
        self.name = name or provider_id
        self.demux = demux

    def search(self, criteria: Optional[SearchCriteria] = None,
               **filters) -> SearchResults[dict]:
        criteria = criteria or SearchCriteria()
        body = {"page": criteria.page, "pageSize": criteria.page_size}
        if criteria.start_s is not None:
            body["start"] = criteria.start_s
        if criteria.end_s is not None:
            body["end"] = criteria.end_s
        for key, wire_key in (("device_token", "deviceToken"),
                              ("assignment_token", "assignmentToken"),
                              ("event_type", "eventType")):
            if filters.get(key) is not None:
                body[wire_key] = filters[key]
        page, _ = self.demux.call("events.query", body)
        return SearchResults(results=list(page.get("results", [])),
                             total=int(page.get("numResults", 0)))


def _record_ts(record) -> tuple:
    """Newest-first merge key for local EventRecords and remote dicts."""
    if isinstance(record, dict):
        return (record.get("ts_s", 0), record.get("ts_ns", 0))
    return (getattr(record, "ts_s", 0), getattr(record, "ts_ns", 0))


class FederatedSearchProvider:
    """Cluster-wide search: fan a query out to several providers (the
    local store + every peer) and merge newest-first.

    This is the multi-host completion of the reference's federation
    idea: one logical search surface over per-host indexes.  Each
    backend is over-fetched to ``page × page_size`` so the merged page
    is exact regardless of how rows distribute across hosts; a peer
    that fails mid-query is skipped (federated search degrades, it
    does not fail whole — the reference's provider surface has the
    same isolation)."""

    def __init__(self, provider_id: str, providers: List, name: str = ""):
        self.provider_id = provider_id
        self.name = name or provider_id
        self.providers = list(providers)

    def search(self, criteria: Optional[SearchCriteria] = None,
               **filters) -> SearchResults:
        criteria = criteria or SearchCriteria()
        # page_size <= 0 is the "unlimited" sentinel every provider
        # honors (SearchCriteria.slice) — propagate it, don't slice to []
        unlimited = criteria.page_size <= 0
        fetch = SearchCriteria(
            page=1,
            page_size=0 if unlimited else criteria.page * criteria.page_size,
            start_s=criteria.start_s, end_s=criteria.end_s)
        merged: List = []
        total = 0
        for provider in self.providers:
            try:
                page = provider.search(fetch, **filters)
            except Exception:   # noqa: BLE001 — degrade, don't fail whole
                import logging

                logging.getLogger("sitewhere_tpu.search").warning(
                    "federated search: provider %s failed; skipping",
                    provider.provider_id, exc_info=True)
                continue
            merged.extend(page.results)
            total += page.total
        merged.sort(key=_record_ts, reverse=True)
        if unlimited:
            return SearchResults(results=merged, total=total)
        lo = (criteria.page - 1) * criteria.page_size
        return SearchResults(results=merged[lo:lo + criteria.page_size],
                             total=total)


class SearchProvidersManager:
    """Named provider registry (reference: ``SearchProviderManager``)."""

    def __init__(self, providers: Optional[List[EventSearchProvider]] = None):
        self._providers: Dict[str, EventSearchProvider] = {
            p.provider_id: p for p in providers or []
        }

    def add_provider(self, provider: EventSearchProvider) -> None:
        self._providers[provider.provider_id] = provider

    def get_provider(self, provider_id: str) -> EventSearchProvider:
        p = self._providers.get(provider_id)
        if p is None:
            raise EntityNotFound(f"search provider {provider_id}")
        return p

    def list_providers(self) -> List[EventSearchProvider]:
        return list(self._providers.values())
