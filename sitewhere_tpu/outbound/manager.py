"""Outbound connectors manager: fan each enriched batch to every connector.

Reference: ``KafkaOutboundConnectorHost.java:44-89`` runs one Kafka
consumer (own consumer group = own offset cursor) per connector, so a slow
or failing connector never blocks the others.  Here each connector
processes each batch on its own worker thread with error isolation; a
connector exception is counted and logged, never propagated to the
dispatcher (the pipeline equivalent of a consumer group falling behind is
the connector's queue depth).
"""

from __future__ import annotations

import logging
import queue
import threading
from typing import Dict, List, Optional

import numpy as np

from sitewhere_tpu.outbound.connectors import OutboundConnector
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent

logger = logging.getLogger("sitewhere_tpu.outbound")


class OutboundConnectorsManager(LifecycleComponent):
    """Owns the connector set; dispatches batches to per-connector queues."""

    def __init__(self, connectors: Optional[List[OutboundConnector]] = None,
                 queue_depth: int = 64):
        super().__init__("outbound-connectors")
        self.queue_depth = queue_depth
        self._workers: Dict[str, "_Worker"] = {}
        for c in connectors or []:
            self.add_connector(c)

    def add_connector(self, connector: OutboundConnector) -> None:
        self.add_child(connector)
        worker = _Worker(connector, self.queue_depth)
        self._workers[connector.connector_id] = worker
        if self.state.name == "STARTED":
            worker.start()

    def start(self) -> None:
        super().start()
        for worker in self._workers.values():
            worker.start()

    def stop(self) -> None:
        for worker in self._workers.values():
            worker.shutdown()
        super().stop()

    def submit(self, cols: Dict[str, np.ndarray], mask: np.ndarray) -> None:
        """Offer one enriched batch to every connector (non-blocking; a
        full queue drops the batch for that connector and counts it —
        backpressure stays local, like an overwhelmed consumer group)."""
        for worker in self._workers.values():
            worker.offer(cols, mask)

    def drain(self, timeout: float = 10.0) -> None:
        """Block until all queued batches are processed (tests/shutdown)."""
        for worker in self._workers.values():
            worker.drain(timeout)

    def stats(self) -> Dict[str, dict]:
        return {
            cid: {
                "processed": w.connector.processed,
                "errors": w.connector.errors,
                "dropped": w.dropped,
                "queued": w.q.qsize(),
            }
            for cid, w in self._workers.items()
        }


class _Worker:
    def __init__(self, connector: OutboundConnector, depth: int):
        self.connector = connector
        self.q: "queue.Queue" = queue.Queue(maxsize=depth)
        self.dropped = 0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name=f"outbound-{self.connector.connector_id}", daemon=True
        )
        self._thread.start()

    def shutdown(self) -> None:
        self._stop.set()
        if self._thread is not None:
            try:
                self.q.put_nowait(None)  # wake; a full queue still wakes the
            except queue.Full:           # worker on its next get()
                pass
            self._thread.join(timeout=5)
            self._thread = None

    def offer(self, cols, mask) -> None:
        try:
            self.q.put_nowait((cols, mask))
        except queue.Full:
            self.dropped += 1

    def drain(self, timeout: float) -> None:
        import time

        # unfinished_tasks only reaches 0 after task_done() — i.e. after the
        # in-flight batch has fully processed, not merely been dequeued.
        deadline = time.monotonic() + timeout
        while self.q.unfinished_tasks and time.monotonic() < deadline:
            time.sleep(0.005)

    def _loop(self) -> None:
        while not self._stop.is_set():
            item = self.q.get()
            try:
                if item is None:
                    continue
                cols, mask = item
                try:
                    self.connector.process_batch(cols, mask)
                except Exception:
                    # isolation only: process_batch already counted the
                    # error and informed the connector's breaker
                    logger.exception("connector %s failed on batch",
                                     self.connector.connector_id)
            finally:
                self.q.task_done()
