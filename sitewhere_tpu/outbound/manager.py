"""Outbound connectors manager: fan each enriched batch to every connector.

Reference: ``KafkaOutboundConnectorHost.java:44-89`` runs one Kafka
consumer (own consumer group = own offset cursor) per connector, so a slow
or failing connector never blocks the others.  Here each connector
processes each batch on its own worker thread with error isolation; a
connector exception is counted and logged, never propagated to the
dispatcher (the pipeline equivalent of a consumer group falling behind is
the connector's queue depth).

Observability: ``submit`` carries the originating plan's trace (an
``outbound.deliver`` span per connector lands in the SAME trace, even
though delivery is asynchronous) and its ingest timestamp, so the
manager can fold per-stage lag into the metrics registry —
``outbound.queue_depth.<id>`` gauges, the ``outbound.ack_latency_s``
histogram (submit→successful process, with trace-id exemplars), and the
per-connector ``pipeline.ingest_to_outbound_ack_latency_s.<id>`` gauges
the watermark story needs (per-stage attribution localizes regressions;
arxiv 1807.07724 / 2307.14287).  Failed deliveries never record an ack.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from sitewhere_tpu.outbound.connectors import OutboundConnector
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent
from sitewhere_tpu.runtime.tracing import _NOOP_TRACE

logger = logging.getLogger("sitewhere_tpu.outbound")


class OutboundConnectorsManager(LifecycleComponent):
    """Owns the connector set; dispatches batches to per-connector queues."""

    def __init__(self, connectors: Optional[List[OutboundConnector]] = None,
                 queue_depth: int = 64, metrics=None, overload=None):
        super().__init__("outbound-connectors")
        self.queue_depth = queue_depth
        self.metrics = metrics
        # degradation ladder (runtime/overload.py): from SHEDDING up,
        # batches are offered only to PRIORITY connectors (alert
        # notifiers, command bridges); bulk fan-out (search indexers,
        # file sinks, analytics taps) sheds and is counted per worker
        self.overload = overload
        # tenant metering hook (instance-wired): rows offered to at
        # least one connector bill ``outbound_rows`` to their tenant
        self.usage_ledger = None
        self._workers: Dict[str, "_Worker"] = {}
        for c in connectors or []:
            self.add_connector(c)

    def add_connector(self, connector: OutboundConnector) -> None:
        self.add_child(connector)
        worker = _Worker(connector, self.queue_depth, self.metrics)
        self._workers[connector.connector_id] = worker
        if self.state.name == "STARTED":
            worker.start()

    def start(self) -> None:
        super().start()
        for worker in self._workers.values():
            worker.start()

    def stop(self) -> None:
        for worker in self._workers.values():
            worker.shutdown()
        super().stop()

    def submit(self, cols: Dict[str, np.ndarray], mask: np.ndarray,
               trace=None, ingest_t0: Optional[float] = None) -> None:
        """Offer one enriched batch to every connector (non-blocking; a
        full queue drops the batch for that connector and counts it —
        backpressure stays local, like an overwhelmed consumer group).

        ``trace`` is the originating plan's trace (delivery spans join
        it); ``ingest_t0`` is the monotonic receive time of the plan's
        oldest row, for the ingest→outbound-ack watermark gauge."""
        item = (cols, mask, trace or _NOOP_TRACE, ingest_t0,
                time.monotonic())
        offered = 0
        for worker in self._workers.values():
            if (self.overload is not None
                    and not self.overload.allow_fanout(
                        getattr(worker.connector, "priority", False))):
                worker.overload_shed += 1
                if worker._m_shed is not None:
                    worker._m_shed.inc()
                continue
            worker.offer(item)
            offered += 1
        if offered and self.usage_ledger is not None:
            # bill fan-out per ROW × connectors offered: tenant cost
            # scales with how much delivery work their rows fan into
            try:
                tenants = cols.get("tenant_id") if hasattr(cols, "get") \
                    else None
                if tenants is not None:
                    self.usage_ledger.charge_rows_host(
                        np.asarray(tenants)[np.asarray(mask)],
                        "outbound_rows",
                        weights=np.full(int(np.asarray(mask).sum()),
                                        float(offered)))
            except Exception:
                logger.exception("outbound usage charge failed")

    def drain(self, timeout: float = 10.0) -> None:
        """Block until all queued batches are processed (tests/shutdown)."""
        for worker in self._workers.values():
            worker.drain(timeout)

    def stats(self) -> Dict[str, dict]:
        return {
            cid: {
                "processed": w.connector.processed,
                "errors": w.connector.errors,
                "dropped": w.dropped,
                "queued": w.q.qsize(),
            }
            for cid, w in self._workers.items()
        }


class _Worker:
    def __init__(self, connector: OutboundConnector, depth: int,
                 metrics=None):
        self.connector = connector
        self.q: "queue.Queue" = queue.Queue(maxsize=depth)
        self.dropped = 0
        self.overload_shed = 0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        if metrics is not None:
            cid = connector.connector_id
            self._m_depth = metrics.gauge(f"outbound.queue_depth.{cid}")
            self._m_ack = metrics.histogram("outbound.ack_latency_s")
            # per connector: one shared gauge would be last-write-wins,
            # letting a fast connector mask a lagging one's watermark
            self._m_e2e = metrics.gauge(
                f"pipeline.ingest_to_outbound_ack_latency_s.{cid}")
            self._m_dropped = metrics.counter("outbound.batches_dropped")
            self._m_shed = metrics.counter(
                f"outbound.overload_shed.{cid}")
        else:
            self._m_depth = self._m_ack = self._m_e2e = None
            self._m_dropped = self._m_shed = None

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name=f"outbound-{self.connector.connector_id}", daemon=True
        )
        self._thread.start()

    def shutdown(self) -> None:
        self._stop.set()
        if self._thread is not None:
            try:
                self.q.put_nowait(None)  # wake; a full queue still wakes the
            except queue.Full:           # worker on its next get()
                pass
            self._thread.join(timeout=5)
            self._thread = None

    def offer(self, item) -> None:
        try:
            self.q.put_nowait(item)
        except queue.Full:
            self.dropped += 1
            if self._m_dropped is not None:
                self._m_dropped.inc()
        if self._m_depth is not None:
            self._m_depth.set(self.q.qsize())

    def drain(self, timeout: float) -> None:
        # unfinished_tasks only reaches 0 after task_done() — i.e. after the
        # in-flight batch has fully processed, not merely been dequeued.
        # Wait on the queue's all_tasks_done condition (what Queue.join
        # waits on) instead of polling: task_done() notifies it, so the
        # drain wakes exactly when work completes and the deadline is
        # honored precisely, with zero CPU burned in between.
        deadline = time.monotonic() + timeout
        with self.q.all_tasks_done:
            while self.q.unfinished_tasks:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return
                self.q.all_tasks_done.wait(remaining)

    def _loop(self) -> None:
        while not self._stop.is_set():
            item = self.q.get()
            try:
                if item is None:
                    continue
                cols, mask, trace, ingest_t0, t_submit = item
                delivered = False
                try:
                    with trace.span("outbound.deliver") as span:
                        span.tag("connector", self.connector.connector_id)
                        self.connector.process_batch(cols, mask)
                    delivered = True
                except Exception:
                    # isolation only: process_batch already counted the
                    # error and informed the connector's breaker
                    logger.exception("connector %s failed on batch",
                                     self.connector.connector_id)
                now = time.monotonic()
                if self._m_ack is not None:
                    if delivered:
                        # a failed batch is NOT an ack — recording it
                        # would make an outage read as healthy delivery.
                        # Exemplar is best-effort: a tail-candidate trace
                        # flips sampled at the dispatcher's end(), which
                        # an idle worker's fast ack can precede — such an
                        # ack carries no exemplar even when the trace is
                        # later retained (the e2e histogram's exemplar,
                        # recorded post-decision, is the authoritative
                        # bucket→trace link).
                        self._m_ack.observe(
                            now - t_submit,
                            trace_id=(trace.trace_id if trace.sampled
                                      else None))
                        if ingest_t0 is not None:
                            self._m_e2e.set(now - ingest_t0)
                    self._m_depth.set(self.q.qsize())
            finally:
                self.q.task_done()
