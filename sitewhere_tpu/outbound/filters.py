"""Vectorized outbound event filters.

Reference: ``service-outbound-connectors/.../filter/`` — ``AreaFilter``,
``DeviceTypeFilter`` (include/exclude one entity), and the Groovy script
filter, applied per event by ``FilteredOutboundConnector``.  Here a filter
maps a *column batch* to a boolean mask in one numpy expression, so
filtering N events costs one vector op instead of N callbacks; the script
filter takes a callable over the columns (the
:mod:`sitewhere_tpu.runtime.scripting` extension point).

Operation modes follow the reference: ``include=True`` passes only matching
events, ``include=False`` (exclude) blocks matching events.  A connector's
filter chain ANDs its filters (an event must survive every filter), same
as ``FilteredOutboundConnector.isFiltered``.
"""

from __future__ import annotations

from typing import Callable, Dict, Sequence

import numpy as np

Columns = Dict[str, np.ndarray]


class _IdColumnFilter:
    """Match rows whose ``column`` is one of ``ids``."""

    column: str

    def __init__(self, ids: Sequence[int], include: bool = False):
        self.ids = np.asarray(list(ids), np.int32)
        self.include = include

    def __call__(self, cols: Columns) -> np.ndarray:
        match = np.isin(cols[self.column], self.ids)
        return match if self.include else ~match


class AreaFilter(_IdColumnFilter):
    """Pass/block events by enriched area id (reference ``AreaFilter``)."""

    column = "area_id"


class DeviceTypeFilter(_IdColumnFilter):
    """Pass/block by device type id (reference ``DeviceTypeFilter``)."""

    column = "device_type_id"


class DeviceFilter(_IdColumnFilter):
    """Pass/block by device id."""

    column = "device_id"


class EventTypeFilter(_IdColumnFilter):
    """Pass/block by event type (connectors often want only alerts)."""

    column = "event_type"


class CallbackFilter:
    """Script filter: any callable columns → bool mask (Groovy analog)."""

    def __init__(self, fn: Callable[[Columns], np.ndarray]):
        self.fn = fn

    def __call__(self, cols: Columns) -> np.ndarray:
        return np.asarray(self.fn(cols), np.bool_)


def apply_filters(filters, cols: Columns, base_mask: np.ndarray) -> np.ndarray:
    """AND a filter chain over a column batch."""
    mask = base_mask.copy()
    for f in filters:
        if not mask.any():
            break
        mask &= f(cols)
    return mask
