"""Outbound delivery of enriched events to external systems.

Reference: ``service-outbound-connectors`` — one Kafka consumer per
connector over the enriched-events topic, each connector wrapped in
filters, some with multicast routing (SURVEY.md §2.2).  Here the
dispatcher hands every accepted (enriched) batch to the
:class:`~sitewhere_tpu.outbound.manager.OutboundConnectorsManager`;
filters are *vectorized column masks* rather than per-event predicates —
the TPU-shaped reformulation of ``FilteredOutboundConnector``.
"""

from sitewhere_tpu.outbound.filters import (
    AreaFilter,
    CallbackFilter,
    DeviceFilter,
    DeviceTypeFilter,
    EventTypeFilter,
)
from sitewhere_tpu.outbound.connectors import (
    CallbackConnector,
    FileConnector,
    HttpConnector,
    IndexPushConnector,
    MqttOutboundConnector,
    OutboundConnector,
)
from sitewhere_tpu.outbound.manager import OutboundConnectorsManager
from sitewhere_tpu.outbound.search import EventSearchProvider, SearchProvidersManager

__all__ = [
    "AreaFilter",
    "CallbackFilter",
    "DeviceFilter",
    "DeviceTypeFilter",
    "EventTypeFilter",
    "CallbackConnector",
    "FileConnector",
    "HttpConnector",
    "IndexPushConnector",
    "MqttOutboundConnector",
    "OutboundConnector",
    "OutboundConnectorsManager",
    "EventSearchProvider",
    "SearchProvidersManager",
]
