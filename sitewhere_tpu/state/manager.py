"""DeviceStateManager: owner + query surface of the DeviceState tensors.

Reference: ``service-device-state`` is the queryable materialized view of
last-known device state (``grpc/DeviceStateImpl.java`` + Mongo persistence
``MongoDeviceStateManagement``) fed by the enriched-events consumer.  Here
the view *is* the :class:`~sitewhere_tpu.schema.DeviceState` pytree the
pipeline step threads through every batch; this manager holds the current
epoch, applies step outputs, answers host queries (single-device reads,
missing/recent scans), and runs the presence sweep against it.

Device-resident by design: queries that scan all devices (missing list,
recently-seen) are vectorized reductions on device, with only the
resulting indices/rows copied back.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

import numpy as np

from sitewhere_tpu.ids import NULL_ID, IdentityMap
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent
from sitewhere_tpu.schema import DeviceState, EventBatch, EventType
from sitewhere_tpu.services.common import EntityNotFound, require
from sitewhere_tpu.state.presence import missing_state_changes, presence_sweep


class DeviceStateManager(LifecycleComponent):
    """Holds the authoritative :class:`DeviceState` epoch.

    The pipeline dispatcher calls :meth:`commit` with each step's
    ``new_state``; readers get consistent snapshots.  ``tenant_ids`` for
    presence StateChange emission come from the registry mirror columns
    (the enrichment source of truth).
    """

    def __init__(
        self,
        capacity: int,
        identity: IdentityMap,
        num_mtype_slots: int = 8,
        tenant_id_of_device=None,  # Callable[[np.ndarray], np.ndarray]
    ):
        super().__init__(name="device-state-manager")
        self.identity = identity
        self._lock = threading.RLock()
        self._state = DeviceState.empty(capacity, num_mtype_slots)
        self._tenant_id_of_device = tenant_id_of_device

    # -- epoch plumbing ----------------------------------------------------

    @property
    def current(self) -> DeviceState:
        with self._lock:
            return self._state

    def commit(self, new_state: DeviceState) -> None:
        """Adopt a pipeline step's output state (the merge already ran on
        device inside the step)."""
        with self._lock:
            self._state = new_state

    # -- presence ----------------------------------------------------------

    def apply_presence_sweep(
        self, now_s: int, missing_after_s: int
    ) -> Optional[EventBatch]:
        """Run the jitted sweep, adopt the flagged state, and build the
        STATE_CHANGE batch for newly-missing devices (None if none)."""
        import jax.numpy as jnp

        with self._lock:
            new_state, newly_missing = presence_sweep(
                self._state, jnp.int32(now_s), jnp.int32(missing_after_s)
            )
            self._state = new_state
        mask = np.asarray(newly_missing)
        if self._tenant_id_of_device is not None:
            tenant_ids = self._tenant_id_of_device(np.arange(mask.size))
        else:
            tenant_ids = np.zeros(mask.size, np.int32)
        return missing_state_changes(mask, tenant_ids, now_s)

    # -- queries (reference: DeviceStateImpl RPCs) --------------------------

    def get_device_state(self, device_token: str) -> Dict[str, object]:
        """Last-known state for one device, as a host dict."""
        device_id = self.identity.device.lookup(device_token)
        require(
            device_id != NULL_ID, EntityNotFound(f"no device {device_token!r}")
        )
        return self.get_device_state_by_id(int(device_id))

    def get_device_state_by_id(self, device_id: int) -> Dict[str, object]:
        with self._lock:
            s = self._state
        require(
            0 <= device_id < s.capacity, EntityNotFound(f"bad device id {device_id}")
        )
        row = {
            "device_id": device_id,
            "last_event_ts_s": int(np.asarray(s.last_event_ts_s[device_id])),
            "last_event_type": int(np.asarray(s.last_event_type[device_id])),
            "presence_missing": bool(np.asarray(s.presence_missing[device_id])),
            "last_location": {
                "lat": float(np.asarray(s.last_lat[device_id])),
                "lon": float(np.asarray(s.last_lon[device_id])),
                "elevation": float(np.asarray(s.last_elevation[device_id])),
                "ts_s": int(np.asarray(s.last_location_ts_s[device_id])),
            },
            "last_alert": {
                "code": int(np.asarray(s.last_alert_code[device_id])),
                "ts_s": int(np.asarray(s.last_alert_ts_s[device_id])),
            },
            "last_values": np.asarray(s.last_values[device_id]).tolist(),
            "last_value_ts_s": np.asarray(s.last_value_ts_s[device_id]).tolist(),
        }
        if row["last_event_type"] == NULL_ID:
            row["last_event_type"] = None
        return row

    def missing_device_ids(self) -> List[int]:
        """Devices currently flagged missing (vectorized scan + index copy)."""
        with self._lock:
            mask = np.asarray(self._state.presence_missing)
        return [int(i) for i in np.nonzero(mask)[0]]

    def seen_since(self, since_s: int) -> List[int]:
        """Devices with any event at/after ``since_s``."""
        with self._lock:
            s = self._state
            mask = np.asarray(
                (s.last_event_type != NULL_ID) & (s.last_event_ts_s >= since_s)
            )
        return [int(i) for i in np.nonzero(mask)[0]]

    def summary(self) -> Dict[str, int]:
        with self._lock:
            s = self._state
            has = np.asarray(s.last_event_type != NULL_ID)
            missing = np.asarray(s.presence_missing)
        return {
            "devices_with_state": int(has.sum()),
            "devices_missing": int(missing.sum()),
        }
