"""DeviceStateManager: owner + query surface of the DeviceState tensors.

Reference: ``service-device-state`` is the queryable materialized view of
last-known device state (``grpc/DeviceStateImpl.java`` + Mongo persistence
``MongoDeviceStateManagement``) fed by the enriched-events consumer.  Here
the view *is* the :class:`~sitewhere_tpu.schema.DeviceState` pytree the
pipeline step threads through every batch; this manager holds the current
epoch, applies step outputs, answers host queries (single-device reads,
missing/recent scans), and runs the presence sweep against it.

Device-resident by design: queries that scan all devices (missing list,
recently-seen) are vectorized reductions on device, with only the
resulting indices/rows copied back.
"""

from __future__ import annotations

import functools
import threading
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from sitewhere_tpu.ids import NULL_ID, IdentityMap
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent
from sitewhere_tpu.schema import DeviceState, EventBatch
from sitewhere_tpu.services.common import EntityNotFound, require
from sitewhere_tpu.state.presence import presence_sweep, state_changes_for


def _next_pow2(n: int) -> int:
    return 1 << max(0, int(n) - 1).bit_length()


@functools.lru_cache(maxsize=64)
def _partition_gather(rung: int):
    """Jitted padded gather for one partition rung size — compiled ONCE
    per pow2 rung and shared by every tenant sitting on that rung, the
    same bucketing guarantee the rules compiler gives program shapes.
    Padding rows gather device 0 and carry valid=False."""
    del rung  # the cache key; the jit specializes on idx.shape

    @jax.jit
    def gather(state, idx, valid):
        rows = jax.tree.map(lambda a: a[idx], state)
        return rows, valid

    return gather


class TenantPartitions:
    """Per-tenant pow2 capacity ladders over the shared state tensors.

    The global :class:`DeviceState` epoch is a single fixed-capacity
    tensor — it never resizes, so tenant isolation at this layer means
    each tenant's QUERY/EXPORT surface runs through its own padded
    partition view: a gather of the tenant's device rows padded to a
    pow2 rung.  Rungs ride a sticky ladder (grow to the next pow2 when
    the tenant's device count exceeds the rung, shrink only once count
    falls to a quarter of it — the registry-ladder hysteresis from the
    rules subsystem), so registration churn inside one tenant bumps
    only THAT tenant's rung.  ``compile_count`` counts a tenant's rung
    transitions — the churn-storm bench pins it flat for untouched
    tenants while a noisy neighbor registers devices in waves.  The
    gather kernel itself is cached per RUNG (module-level), so two
    tenants on the same rung share one compiled executable.
    """

    def __init__(self, tenant_column_provider,
                 min_capacity: int = 64, metrics=None):
        self._provider = tenant_column_provider
        self.min_capacity = _next_pow2(max(1, int(min_capacity)))
        self._lock = threading.Lock()
        # tenant_id → {"count", "rung", "compile_count"}
        self._parts: Dict[int, Dict[str, int]] = {}
        self._column: Optional[np.ndarray] = None
        self._m_tracked = None
        self._m_compiles = None
        self._m_resizes = None
        if metrics is not None:
            self.bind_metrics(metrics)

    def bind_metrics(self, metrics) -> None:
        self._m_tracked = metrics.gauge("tenant.partition.tracked")
        self._m_compiles = metrics.counter("tenant.partition.compiles")
        self._m_resizes = metrics.counter("tenant.partition.resizes")

    def refresh(self) -> None:
        """Re-derive per-tenant device counts from the registry mirror's
        tenant column and walk each tenant's rung ladder.  O(capacity)
        bincount — called from query surfaces and on a registration
        cadence, never from the step hot path."""
        col = np.asarray(self._provider())
        owned = col[col >= 0]
        counts = (np.bincount(owned) if owned.size
                  else np.zeros(0, np.int64))
        tenants = np.nonzero(counts)[0]
        with self._lock:
            self._column = col
            for t in tenants.tolist():
                count = int(counts[t])
                part = self._parts.get(t)
                if part is None:
                    self._parts[t] = {
                        "count": count,
                        "rung": max(self.min_capacity, _next_pow2(count)),
                        "compile_count": 1,
                    }
                    if self._m_compiles is not None:
                        self._m_compiles.inc()
                    continue
                part["count"] = count
                rung = part["rung"]
                if count > rung:
                    part["rung"] = _next_pow2(count)
                elif (count <= rung // 4
                      and rung > self.min_capacity):
                    # shrink-at-quarter hysteresis: a tenant oscillating
                    # around a rung boundary never flaps its kernel
                    part["rung"] = max(self.min_capacity,
                                       _next_pow2(count))
                if part["rung"] != rung:
                    part["compile_count"] += 1
                    if self._m_compiles is not None:
                        self._m_compiles.inc()
                    if self._m_resizes is not None:
                        self._m_resizes.inc()
            if self._m_tracked is not None:
                self._m_tracked.set(len(self._parts))

    def tenants(self) -> List[int]:
        with self._lock:
            return sorted(self._parts)

    def compile_count(self, tenant_id: int) -> int:
        with self._lock:
            part = self._parts.get(int(tenant_id))
            return 0 if part is None else part["compile_count"]

    def partition_of(self, tenant_id: int) -> Optional[Dict[str, int]]:
        with self._lock:
            part = self._parts.get(int(tenant_id))
            return None if part is None else dict(part)

    def indices_of(self, tenant_id: int):
        """``(idx, valid)`` for one tenant's partition view: the
        tenant's device ids padded to its rung (padding gathers row 0,
        masked out by ``valid``).  None if the tenant owns nothing."""
        with self._lock:
            part = self._parts.get(int(tenant_id))
            col = self._column
        if part is None or col is None:
            return None
        ids = np.nonzero(col == int(tenant_id))[0].astype(np.int32)
        rung = part["rung"]
        idx = np.zeros(rung, np.int32)
        valid = np.zeros(rung, bool)
        n = min(len(ids), rung)
        idx[:n] = ids[:n]
        valid[:n] = True
        return idx, valid

    def view(self, state, tenant_id: int):
        """Padded per-tenant gather of ``state`` — ``(rows, valid)`` on
        device, through the rung-cached jitted gather."""
        iv = self.indices_of(tenant_id)
        if iv is None:
            return None
        idx, valid = iv
        gather = _partition_gather(len(idx))
        return gather(state, jnp.asarray(idx), jnp.asarray(valid))

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            return {
                "tenants": len(self._parts),
                "min_capacity": self.min_capacity,
                "partitions": {str(t): dict(p)
                               for t, p in sorted(self._parts.items())},
            }


def _packed_codecs():
    """Module-level jitted pack/unpack (lazy import breaks the cycle;
    per-call ``jax.jit(...)`` would retrace every time)."""
    global _PACK, _UNPACK
    if "_PACK" not in globals():
        from sitewhere_tpu.pipeline.packed import pack_state, unpack_state

        _PACK = jax.jit(pack_state)
        _UNPACK = jax.jit(unpack_state)
    return _PACK, _UNPACK


@jax.jit
def _merge_presence(new_si, cur_si, present_now):
    """Packed-form presence reconciliation (see :meth:`commit` docstring):
    a concurrent sweep's missing flags survive unless THIS step merged an
    event for the device."""
    from sitewhere_tpu.pipeline.packed import PRESENCE_ROW

    merged = (new_si[PRESENCE_ROW] != 0) | (
        (cur_si[PRESENCE_ROW] != 0) & ~present_now)
    return new_si.at[PRESENCE_ROW].set(merged.astype(new_si.dtype))


class DeviceStateManager(LifecycleComponent):
    """Holds the authoritative :class:`DeviceState` epoch.

    The pipeline dispatcher calls :meth:`commit` with each step's
    ``new_state``; readers get consistent snapshots.  ``tenant_ids`` for
    presence StateChange emission come from the registry mirror columns
    (the enrichment source of truth).
    """

    def __init__(
        self,
        capacity: int,
        identity: IdentityMap,
        num_mtype_slots: int = 8,
        tenant_id_of_device=None,  # Callable[[np.ndarray], np.ndarray]
        num_ewma_scales: int = 3,
    ):
        super().__init__(name="device-state-manager")
        self.identity = identity
        self._lock = threading.RLock()
        self._state: Optional[DeviceState] = DeviceState.empty(
            capacity, num_mtype_slots, num_ewma_scales)
        # Packed twin of the epoch (pipeline/packed.py): the dispatcher's
        # steady-state carry.  Exactly one of the two may be stale (None);
        # each is materialized lazily from the other so sweeps/queries and
        # the packed step loop never force each other's representation.
        self._packed = None
        self._tenant_id_of_device = tenant_id_of_device
        # Monotonic count of lease_packed() calls — the device-fault
        # containment protocol's observable: a failed donated chain is
        # recovered by simply leasing AGAIN from the still-held epoch, so
        # "re-leased without restart" is `lease_generation` advancing on
        # one live manager (tools/devfault_bench.py asserts exactly this).
        self.lease_generation = 0
        # Tenant-partitioned query views (attach_partitions): per-tenant
        # pow2 rung ladders over the shared tensors, so one tenant's
        # registration churn recompiles only its own partition view
        self.partitions: Optional[TenantPartitions] = None

    def attach_partitions(self, tenant_column_provider,
                          min_capacity: int = 64,
                          metrics=None) -> TenantPartitions:
        """Wire the tenant-partition ladder (instance passes the registry
        mirror's tenant column provider)."""
        self.partitions = TenantPartitions(
            tenant_column_provider, min_capacity=min_capacity,
            metrics=metrics)
        return self.partitions

    def tenant_state_summary(self, tenant_id: int) -> Dict[str, object]:
        """Per-tenant state summary through the tenant's partition view:
        the partitioned analog of :meth:`summary`.  Snapshot under the
        lock, gather + transfer OUTSIDE it (the lease lock must never
        ride a D2H — see missing_device_ids)."""
        require(self.partitions is not None,
                EntityNotFound("tenant partitions are not attached"))
        self.partitions.refresh()
        part = self.partitions.partition_of(tenant_id)
        if part is None:
            return {"devices": 0, "capacity": 0, "compile_count": 0,
                    "devices_with_state": 0, "devices_missing": 0}
        with self._lock:
            s = self.current
        view = self.partitions.view(s, tenant_id)
        if view is None:   # raced a refresh that dropped the column
            return {"devices": part["count"], "capacity": part["rung"],
                    "compile_count": part["compile_count"],
                    "devices_with_state": 0, "devices_missing": 0}
        rows, valid = view
        valid = np.asarray(valid)
        has = np.asarray(rows.last_event_type != NULL_ID) & valid
        missing = np.asarray(rows.presence_missing) & valid
        return {
            "devices": part["count"],
            "capacity": part["rung"],
            "compile_count": part["compile_count"],
            "devices_with_state": int(has.sum()),
            "devices_missing": int(missing.sum()),
        }

    # -- epoch plumbing ----------------------------------------------------

    @property
    def current(self) -> DeviceState:
        with self._lock:
            if self._state is None:
                _, unpack = _packed_codecs()
                self._state = unpack(self._packed)
            return self._state

    @property
    def current_packed(self):
        """The packed epoch (pack lazily after an unpacked commit)."""
        with self._lock:
            if self._packed is None:
                pack, _ = _packed_codecs()
                self._packed = pack(self.current)
            return self._packed

    def lease_packed(self):
        """Exclusive hand-off of the packed epoch for a DONATED step
        chain (the device-resident dispatch loop's carry).

        Donation deletes the input buffers once the chain runs, so the
        manager must stop being a co-owner: the unpacked twin is
        materialized FIRST (one async unpack dispatch — readers arriving
        mid-chain see the pre-chain epoch from fresh buffers, never the
        donated ones) and ``_packed`` is dropped.  Returns
        ``(packed, lease_token)``; pass the token to :meth:`commit_packed`
        so it can tell whether anything (a presence sweep, a migration
        import) intervened during the chain.

        If the chain crashes before commit, the manager simply still
        holds the pre-chain epoch — the chain's plans stay outstanding
        and journal replay re-steps them (at-least-once), identical to a
        single-step dispatch failure.  The dispatcher's containment path
        leans on exactly that: recovery NEVER touches the donated
        ``packed`` again (its buffers may be deleted — swlint DN001
        guards this statically); it re-leases a fresh pack of the held
        epoch and re-dispatches the re-parked plans single-step.
        """
        with self._lock:
            packed = self.current_packed
            if self._state is None:
                _, unpack = _packed_codecs()
                self._state = unpack(packed)
            self._packed = None
            self.lease_generation += 1
            # token = the materialized twin's identity: every out-of-band
            # state write (commit/sweep/import) replaces _state, so
            # `self._state is token` at commit time means nothing
            # intervened and the presence merge can be skipped
            return packed, self._state

    def commit_packed(self, new_packed, present_now,
                      read_epoch=None, lease_token=None) -> None:
        """Adopt a packed step's output state (the packed-loop analog of
        :meth:`commit`): re-apply ``presence_missing`` flags a concurrent
        sweep set on the current epoch for devices this step did not merge
        (``present_now`` = the step's — or the whole chain's OR'd —
        winner map).

        Pass ``read_epoch`` (the PackedState the step consumed): when the
        current epoch is still that object, nothing intervened and the
        merge — an extra per-step dispatch — is skipped entirely.  A
        donated chain passes ``lease_token`` from :meth:`lease_packed`
        instead (the consumed epoch's buffers no longer exist to compare).
        """
        with self._lock:
            unchanged = (
                (read_epoch is not None and self._packed is read_epoch)
                or (lease_token is not None and self._state is lease_token))
            if not unchanged:
                cur = self.current_packed
                new_packed = new_packed.replace(
                    si=_merge_presence(new_packed.si, cur.si, present_now))
            self._packed = new_packed
            self._state = None

    def commit(self, new_state: DeviceState,
               batch: Optional[EventBatch] = None,
               accepted=None, present_now=None) -> None:
        """Adopt a pipeline step's output state (the merge already ran on
        device inside the step).

        Pass the step's ``present_now`` output (``bool[capacity]``, the
        devices the step actually merged) — or the ``batch`` it consumed
        plus the ``accepted`` mask to re-derive it — so a presence sweep
        that ran concurrently (between the dispatcher's read and this
        commit) is not lost: ``presence_missing`` flags on the current
        epoch are re-applied for devices the step did not actually merge.
        Rows the step REJECTED (unregistered/unassigned/tenant mismatch)
        never cleared presence in the step, so they must not count as
        touched here either.  Computed on device — no host transfer on the
        hot path; the ``present_now`` form also costs no extra scatter
        (the step derived it from its winner map).
        """
        with self._lock:
            current = self.current
            if current is not new_state and (
                    present_now is not None or batch is not None):
                cap = new_state.capacity
                if present_now is not None:
                    touched = present_now
                else:
                    # mirror the step's merge mask: update_state=False rows
                    # never cleared presence in the step
                    merged_rows = (batch.valid & (batch.device_id >= 0)
                                   & batch.update_state)
                    if accepted is not None:
                        merged_rows = merged_rows & accepted
                    ids = jnp.where(merged_rows, batch.device_id, cap)
                    touched = jnp.zeros((cap,), bool).at[ids].set(
                        True, mode="drop")
                merged = new_state.presence_missing | (
                    current.presence_missing & ~touched
                )
                new_state = new_state.replace(presence_missing=merged)
            self._state = new_state
            self._packed = None

    # -- presence ----------------------------------------------------------

    def apply_presence_sweep(
        self, now_s: int, missing_after_s: int
    ) -> Optional[EventBatch]:
        """Run the jitted sweep, adopt the flagged state, and build the
        STATE_CHANGE batch for newly-missing devices (None if none)."""
        with self._lock:
            new_state, newly_missing = presence_sweep(
                self.current, jnp.int32(now_s), jnp.int32(missing_after_s)
            )
            self._state = new_state
            self._packed = None
        (idx,) = np.nonzero(np.asarray(newly_missing))
        if idx.size == 0:
            return None
        idx = idx.astype(np.int32)
        if self._tenant_id_of_device is not None:
            tenant_ids = np.asarray(self._tenant_id_of_device(idx), np.int32)
        else:
            tenant_ids = np.zeros(idx.size, np.int32)
        return state_changes_for(idx, tenant_ids, now_s)

    # -- queries (reference: DeviceStateImpl RPCs) --------------------------

    def get_device_state(self, device_token: str) -> Dict[str, object]:
        """Last-known state for one device, as a host dict."""
        device_id = self.identity.device.lookup(device_token)
        require(
            device_id != NULL_ID, EntityNotFound(f"no device {device_token!r}")
        )
        return self.get_device_state_by_id(int(device_id))

    def get_device_state_by_id(self, device_id: int) -> Dict[str, object]:
        with self._lock:
            s = self.current
        require(
            0 <= device_id < s.capacity, EntityNotFound(f"bad device id {device_id}")
        )
        # one batched device→host transfer for the whole row
        r = jax.device_get(jax.tree.map(lambda a: a[device_id], s))
        row = {
            "device_id": device_id,
            "last_event_ts_s": int(r.last_event_ts_s),
            "last_event_type": int(r.last_event_type),
            "presence_missing": bool(r.presence_missing),
            "last_location": {
                "lat": float(r.last_lat),
                "lon": float(r.last_lon),
                "elevation": float(r.last_elevation),
                "ts_s": int(r.last_location_ts_s),
            },
            "last_alert": {
                "code": int(r.last_alert_code),
                "ts_s": int(r.last_alert_ts_s),
            },
            "last_values": np.asarray(r.last_values).tolist(),
            "last_value_ts_s": np.asarray(r.last_value_ts_s).tolist(),
        }
        if row["last_event_type"] == NULL_ID:
            row["last_event_type"] = None
        return row

    # -- migration (ownership handoff; rpc/migration.py) --------------------

    def export_row(self, device_id: int) -> Dict[str, object]:
        """One device's FULL state row as a jsonable dict (unlike
        :meth:`get_device_state_by_id`'s REST subset, this carries every
        field, plus shape metadata so the importer can check fit)."""
        with self._lock:
            s = self.current
        require(0 <= device_id < s.capacity,
                EntityNotFound(f"bad device id {device_id}"))
        row = jax.device_get(jax.tree.map(lambda a: a[device_id], s))
        out: Dict[str, object] = {
            "_mtype_slots": s.num_mtype_slots,
            "_ewma_scales": s.num_ewma_scales,
        }
        for fld in s.__dataclass_fields__:
            v = np.asarray(getattr(row, fld))
            out[fld] = v.tolist() if v.ndim else v.item()
        return out

    def import_row(self, device_id: int, row: Dict[str, object]) -> bool:
        """Adopt an exported row, NEWEST-WINS: applied only when the
        incoming ``last_event_ts_s`` is newer than what this host holds
        (a device that already re-registered and streamed here must not
        be rolled back).  Measurement-shape mismatches drop the per-slot
        stats but keep the scalar columns.  Returns True if applied."""
        with self._lock:
            s = self.current
            require(0 <= device_id < s.capacity,
                    EntityNotFound(f"bad device id {device_id}"))
            incoming = int(row.get("last_event_ts_s") or 0)
            current_ts = int(np.asarray(s.last_event_ts_s[device_id]))
            if incoming <= current_ts:
                return False
            shapes_ok = (int(row.get("_mtype_slots") or 0) ==
                         s.num_mtype_slots
                         and int(row.get("_ewma_scales") or 0) ==
                         s.num_ewma_scales)
            updates = {}
            for fld in s.__dataclass_fields__:
                if fld not in row:
                    continue
                cur = getattr(s, fld)
                if cur.ndim > 1 and not shapes_ok:
                    continue
                val = jnp.asarray(np.asarray(row[fld], cur.dtype))
                if val.shape != cur.shape[1:]:
                    continue
                updates[fld] = cur.at[device_id].set(val)
            self._state = s.replace(**updates)
            self._packed = None
        return True

    def missing_device_ids(self) -> List[int]:
        """Devices currently flagged missing (vectorized scan + index copy).

        The lock covers only the epoch snapshot; the blocking
        device→host transfer runs OUTSIDE it (epochs are immutable —
        commit replaces, never mutates).  A REST scan must never hold
        the lease lock through a D2H round-trip: ``commit_packed`` takes
        this lock on every batch, so a slow transfer here would stall
        dispatch (swlint lock-discipline LK004)."""
        with self._lock:
            s = self.current
        mask = np.asarray(s.presence_missing)
        return [int(i) for i in np.nonzero(mask)[0]]

    def missing_device_tokens(self) -> List[str]:
        """Missing devices as TOKENS — the cross-host-safe form (dense
        ids are meaningful only inside their minting host's identity
        map, so the remote facade surfaces this, never the id form)."""
        return [t for t in (self.identity.device.token_of(i)
                            for i in self.missing_device_ids())
                if t is not None]

    def seen_since_tokens(self, since_s: int) -> List[str]:
        """Token form of :meth:`seen_since` (see missing_device_tokens)."""
        return [t for t in (self.identity.device.token_of(i)
                            for i in self.seen_since(since_s))
                if t is not None]

    def seen_since(self, since_s: int) -> List[int]:
        """Devices with any event at/after ``since_s``.  Snapshot under
        the lock, compute + transfer outside it (see
        :meth:`missing_device_ids`)."""
        with self._lock:
            s = self.current
        mask = np.asarray(
            (s.last_event_type != NULL_ID) & (s.last_event_ts_s >= since_s)
        )
        return [int(i) for i in np.nonzero(mask)[0]]

    def summary(self) -> Dict[str, int]:
        # snapshot under the lock, transfer outside it (see
        # missing_device_ids — the lease lock must never ride a D2H)
        with self._lock:
            s = self.current
        has = np.asarray(s.last_event_type != NULL_ID)
        missing = np.asarray(s.presence_missing)
        return {
            "devices_with_state": int(has.sum()),
            "devices_missing": int(missing.sum()),
        }
