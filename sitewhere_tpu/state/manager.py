"""DeviceStateManager: owner + query surface of the DeviceState tensors.

Reference: ``service-device-state`` is the queryable materialized view of
last-known device state (``grpc/DeviceStateImpl.java`` + Mongo persistence
``MongoDeviceStateManagement``) fed by the enriched-events consumer.  Here
the view *is* the :class:`~sitewhere_tpu.schema.DeviceState` pytree the
pipeline step threads through every batch; this manager holds the current
epoch, applies step outputs, answers host queries (single-device reads,
missing/recent scans), and runs the presence sweep against it.

Device-resident by design: queries that scan all devices (missing list,
recently-seen) are vectorized reductions on device, with only the
resulting indices/rows copied back.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from sitewhere_tpu.ids import NULL_ID, IdentityMap
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent
from sitewhere_tpu.schema import DeviceState, EventBatch
from sitewhere_tpu.services.common import EntityNotFound, require
from sitewhere_tpu.state.presence import presence_sweep, state_changes_for


def _packed_codecs():
    """Module-level jitted pack/unpack (lazy import breaks the cycle;
    per-call ``jax.jit(...)`` would retrace every time)."""
    global _PACK, _UNPACK
    if "_PACK" not in globals():
        from sitewhere_tpu.pipeline.packed import pack_state, unpack_state

        _PACK = jax.jit(pack_state)
        _UNPACK = jax.jit(unpack_state)
    return _PACK, _UNPACK


@jax.jit
def _merge_presence(new_si, cur_si, present_now):
    """Packed-form presence reconciliation (see :meth:`commit` docstring):
    a concurrent sweep's missing flags survive unless THIS step merged an
    event for the device."""
    from sitewhere_tpu.pipeline.packed import PRESENCE_ROW

    merged = (new_si[PRESENCE_ROW] != 0) | (
        (cur_si[PRESENCE_ROW] != 0) & ~present_now)
    return new_si.at[PRESENCE_ROW].set(merged.astype(new_si.dtype))


class DeviceStateManager(LifecycleComponent):
    """Holds the authoritative :class:`DeviceState` epoch.

    The pipeline dispatcher calls :meth:`commit` with each step's
    ``new_state``; readers get consistent snapshots.  ``tenant_ids`` for
    presence StateChange emission come from the registry mirror columns
    (the enrichment source of truth).
    """

    def __init__(
        self,
        capacity: int,
        identity: IdentityMap,
        num_mtype_slots: int = 8,
        tenant_id_of_device=None,  # Callable[[np.ndarray], np.ndarray]
        num_ewma_scales: int = 3,
    ):
        super().__init__(name="device-state-manager")
        self.identity = identity
        self._lock = threading.RLock()
        self._state: Optional[DeviceState] = DeviceState.empty(
            capacity, num_mtype_slots, num_ewma_scales)
        # Packed twin of the epoch (pipeline/packed.py): the dispatcher's
        # steady-state carry.  Exactly one of the two may be stale (None);
        # each is materialized lazily from the other so sweeps/queries and
        # the packed step loop never force each other's representation.
        self._packed = None
        self._tenant_id_of_device = tenant_id_of_device
        # Monotonic count of lease_packed() calls — the device-fault
        # containment protocol's observable: a failed donated chain is
        # recovered by simply leasing AGAIN from the still-held epoch, so
        # "re-leased without restart" is `lease_generation` advancing on
        # one live manager (tools/devfault_bench.py asserts exactly this).
        self.lease_generation = 0

    # -- epoch plumbing ----------------------------------------------------

    @property
    def current(self) -> DeviceState:
        with self._lock:
            if self._state is None:
                _, unpack = _packed_codecs()
                self._state = unpack(self._packed)
            return self._state

    @property
    def current_packed(self):
        """The packed epoch (pack lazily after an unpacked commit)."""
        with self._lock:
            if self._packed is None:
                pack, _ = _packed_codecs()
                self._packed = pack(self.current)
            return self._packed

    def lease_packed(self):
        """Exclusive hand-off of the packed epoch for a DONATED step
        chain (the device-resident dispatch loop's carry).

        Donation deletes the input buffers once the chain runs, so the
        manager must stop being a co-owner: the unpacked twin is
        materialized FIRST (one async unpack dispatch — readers arriving
        mid-chain see the pre-chain epoch from fresh buffers, never the
        donated ones) and ``_packed`` is dropped.  Returns
        ``(packed, lease_token)``; pass the token to :meth:`commit_packed`
        so it can tell whether anything (a presence sweep, a migration
        import) intervened during the chain.

        If the chain crashes before commit, the manager simply still
        holds the pre-chain epoch — the chain's plans stay outstanding
        and journal replay re-steps them (at-least-once), identical to a
        single-step dispatch failure.  The dispatcher's containment path
        leans on exactly that: recovery NEVER touches the donated
        ``packed`` again (its buffers may be deleted — swlint DN001
        guards this statically); it re-leases a fresh pack of the held
        epoch and re-dispatches the re-parked plans single-step.
        """
        with self._lock:
            packed = self.current_packed
            if self._state is None:
                _, unpack = _packed_codecs()
                self._state = unpack(packed)
            self._packed = None
            self.lease_generation += 1
            # token = the materialized twin's identity: every out-of-band
            # state write (commit/sweep/import) replaces _state, so
            # `self._state is token` at commit time means nothing
            # intervened and the presence merge can be skipped
            return packed, self._state

    def commit_packed(self, new_packed, present_now,
                      read_epoch=None, lease_token=None) -> None:
        """Adopt a packed step's output state (the packed-loop analog of
        :meth:`commit`): re-apply ``presence_missing`` flags a concurrent
        sweep set on the current epoch for devices this step did not merge
        (``present_now`` = the step's — or the whole chain's OR'd —
        winner map).

        Pass ``read_epoch`` (the PackedState the step consumed): when the
        current epoch is still that object, nothing intervened and the
        merge — an extra per-step dispatch — is skipped entirely.  A
        donated chain passes ``lease_token`` from :meth:`lease_packed`
        instead (the consumed epoch's buffers no longer exist to compare).
        """
        with self._lock:
            unchanged = (
                (read_epoch is not None and self._packed is read_epoch)
                or (lease_token is not None and self._state is lease_token))
            if not unchanged:
                cur = self.current_packed
                new_packed = new_packed.replace(
                    si=_merge_presence(new_packed.si, cur.si, present_now))
            self._packed = new_packed
            self._state = None

    def commit(self, new_state: DeviceState,
               batch: Optional[EventBatch] = None,
               accepted=None, present_now=None) -> None:
        """Adopt a pipeline step's output state (the merge already ran on
        device inside the step).

        Pass the step's ``present_now`` output (``bool[capacity]``, the
        devices the step actually merged) — or the ``batch`` it consumed
        plus the ``accepted`` mask to re-derive it — so a presence sweep
        that ran concurrently (between the dispatcher's read and this
        commit) is not lost: ``presence_missing`` flags on the current
        epoch are re-applied for devices the step did not actually merge.
        Rows the step REJECTED (unregistered/unassigned/tenant mismatch)
        never cleared presence in the step, so they must not count as
        touched here either.  Computed on device — no host transfer on the
        hot path; the ``present_now`` form also costs no extra scatter
        (the step derived it from its winner map).
        """
        with self._lock:
            current = self.current
            if current is not new_state and (
                    present_now is not None or batch is not None):
                cap = new_state.capacity
                if present_now is not None:
                    touched = present_now
                else:
                    # mirror the step's merge mask: update_state=False rows
                    # never cleared presence in the step
                    merged_rows = (batch.valid & (batch.device_id >= 0)
                                   & batch.update_state)
                    if accepted is not None:
                        merged_rows = merged_rows & accepted
                    ids = jnp.where(merged_rows, batch.device_id, cap)
                    touched = jnp.zeros((cap,), bool).at[ids].set(
                        True, mode="drop")
                merged = new_state.presence_missing | (
                    current.presence_missing & ~touched
                )
                new_state = new_state.replace(presence_missing=merged)
            self._state = new_state
            self._packed = None

    # -- presence ----------------------------------------------------------

    def apply_presence_sweep(
        self, now_s: int, missing_after_s: int
    ) -> Optional[EventBatch]:
        """Run the jitted sweep, adopt the flagged state, and build the
        STATE_CHANGE batch for newly-missing devices (None if none)."""
        with self._lock:
            new_state, newly_missing = presence_sweep(
                self.current, jnp.int32(now_s), jnp.int32(missing_after_s)
            )
            self._state = new_state
            self._packed = None
        (idx,) = np.nonzero(np.asarray(newly_missing))
        if idx.size == 0:
            return None
        idx = idx.astype(np.int32)
        if self._tenant_id_of_device is not None:
            tenant_ids = np.asarray(self._tenant_id_of_device(idx), np.int32)
        else:
            tenant_ids = np.zeros(idx.size, np.int32)
        return state_changes_for(idx, tenant_ids, now_s)

    # -- queries (reference: DeviceStateImpl RPCs) --------------------------

    def get_device_state(self, device_token: str) -> Dict[str, object]:
        """Last-known state for one device, as a host dict."""
        device_id = self.identity.device.lookup(device_token)
        require(
            device_id != NULL_ID, EntityNotFound(f"no device {device_token!r}")
        )
        return self.get_device_state_by_id(int(device_id))

    def get_device_state_by_id(self, device_id: int) -> Dict[str, object]:
        with self._lock:
            s = self.current
        require(
            0 <= device_id < s.capacity, EntityNotFound(f"bad device id {device_id}")
        )
        # one batched device→host transfer for the whole row
        r = jax.device_get(jax.tree.map(lambda a: a[device_id], s))
        row = {
            "device_id": device_id,
            "last_event_ts_s": int(r.last_event_ts_s),
            "last_event_type": int(r.last_event_type),
            "presence_missing": bool(r.presence_missing),
            "last_location": {
                "lat": float(r.last_lat),
                "lon": float(r.last_lon),
                "elevation": float(r.last_elevation),
                "ts_s": int(r.last_location_ts_s),
            },
            "last_alert": {
                "code": int(r.last_alert_code),
                "ts_s": int(r.last_alert_ts_s),
            },
            "last_values": np.asarray(r.last_values).tolist(),
            "last_value_ts_s": np.asarray(r.last_value_ts_s).tolist(),
        }
        if row["last_event_type"] == NULL_ID:
            row["last_event_type"] = None
        return row

    # -- migration (ownership handoff; rpc/migration.py) --------------------

    def export_row(self, device_id: int) -> Dict[str, object]:
        """One device's FULL state row as a jsonable dict (unlike
        :meth:`get_device_state_by_id`'s REST subset, this carries every
        field, plus shape metadata so the importer can check fit)."""
        with self._lock:
            s = self.current
        require(0 <= device_id < s.capacity,
                EntityNotFound(f"bad device id {device_id}"))
        row = jax.device_get(jax.tree.map(lambda a: a[device_id], s))
        out: Dict[str, object] = {
            "_mtype_slots": s.num_mtype_slots,
            "_ewma_scales": s.num_ewma_scales,
        }
        for fld in s.__dataclass_fields__:
            v = np.asarray(getattr(row, fld))
            out[fld] = v.tolist() if v.ndim else v.item()
        return out

    def import_row(self, device_id: int, row: Dict[str, object]) -> bool:
        """Adopt an exported row, NEWEST-WINS: applied only when the
        incoming ``last_event_ts_s`` is newer than what this host holds
        (a device that already re-registered and streamed here must not
        be rolled back).  Measurement-shape mismatches drop the per-slot
        stats but keep the scalar columns.  Returns True if applied."""
        with self._lock:
            s = self.current
            require(0 <= device_id < s.capacity,
                    EntityNotFound(f"bad device id {device_id}"))
            incoming = int(row.get("last_event_ts_s") or 0)
            current_ts = int(np.asarray(s.last_event_ts_s[device_id]))
            if incoming <= current_ts:
                return False
            shapes_ok = (int(row.get("_mtype_slots") or 0) ==
                         s.num_mtype_slots
                         and int(row.get("_ewma_scales") or 0) ==
                         s.num_ewma_scales)
            updates = {}
            for fld in s.__dataclass_fields__:
                if fld not in row:
                    continue
                cur = getattr(s, fld)
                if cur.ndim > 1 and not shapes_ok:
                    continue
                val = jnp.asarray(np.asarray(row[fld], cur.dtype))
                if val.shape != cur.shape[1:]:
                    continue
                updates[fld] = cur.at[device_id].set(val)
            self._state = s.replace(**updates)
            self._packed = None
        return True

    def missing_device_ids(self) -> List[int]:
        """Devices currently flagged missing (vectorized scan + index copy).

        The lock covers only the epoch snapshot; the blocking
        device→host transfer runs OUTSIDE it (epochs are immutable —
        commit replaces, never mutates).  A REST scan must never hold
        the lease lock through a D2H round-trip: ``commit_packed`` takes
        this lock on every batch, so a slow transfer here would stall
        dispatch (swlint lock-discipline LK004)."""
        with self._lock:
            s = self.current
        mask = np.asarray(s.presence_missing)
        return [int(i) for i in np.nonzero(mask)[0]]

    def missing_device_tokens(self) -> List[str]:
        """Missing devices as TOKENS — the cross-host-safe form (dense
        ids are meaningful only inside their minting host's identity
        map, so the remote facade surfaces this, never the id form)."""
        return [t for t in (self.identity.device.token_of(i)
                            for i in self.missing_device_ids())
                if t is not None]

    def seen_since_tokens(self, since_s: int) -> List[str]:
        """Token form of :meth:`seen_since` (see missing_device_tokens)."""
        return [t for t in (self.identity.device.token_of(i)
                            for i in self.seen_since(since_s))
                if t is not None]

    def seen_since(self, since_s: int) -> List[int]:
        """Devices with any event at/after ``since_s``.  Snapshot under
        the lock, compute + transfer outside it (see
        :meth:`missing_device_ids`)."""
        with self._lock:
            s = self.current
        mask = np.asarray(
            (s.last_event_type != NULL_ID) & (s.last_event_ts_s >= since_s)
        )
        return [int(i) for i in np.nonzero(mask)[0]]

    def summary(self) -> Dict[str, int]:
        # snapshot under the lock, transfer outside it (see
        # missing_device_ids — the lease lock must never ride a D2H)
        with self._lock:
            s = self.current
        has = np.asarray(s.last_event_type != NULL_ID)
        missing = np.asarray(s.presence_missing)
        return {
            "devices_with_state": int(has.sum()),
            "devices_missing": int(missing.sum()),
        }
