"""Presence detection: vectorized missing-device sweep.

Reference: ``service-device-state/.../presence/DevicePresenceManager.java``
— a background thread (default check every 10m) queries assignments whose
last interaction predates the missing interval (default 8h) and fires
StateChange events via ``PresenceNotificationStrategies.
SendOnceNotificationStrategy`` (notify once per missing episode).

Here the scan is one jitted pass over the ``DeviceState`` columns: a
device is *newly missing* when it has seen at least one event, is not
already flagged, and its last event is older than the missing interval.
Send-once falls out of the ``presence_missing`` flag itself (the pipeline
step clears it on any accepted event, re-arming notification).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from sitewhere_tpu.ids import NULL_ID
from sitewhere_tpu.runtime.lifecycle import LifecycleComponent
from sitewhere_tpu.schema import DeviceState, EventBatch, EventType

logger = logging.getLogger("sitewhere_tpu.state.presence")

# StateChange codes carried in the alert_code column of STATE_CHANGE events
# (reference: IDeviceStateChangeCreateRequest category/type strings
# "presence"/"missing").
STATE_CHANGE_PRESENCE_MISSING = 1
# Device crossed the numeric-integrity quarantine threshold (cumulative
# NaN/Inf rows — runtime/dispatcher.py _scan_quarantine); rides the same
# STATE_CHANGE egress as presence transitions.
STATE_CHANGE_QUARANTINED = 2


@jax.jit
def presence_sweep(
    state: DeviceState, now_s: jax.Array, missing_after_s: jax.Array
) -> Tuple[DeviceState, jax.Array]:
    """One vectorized presence pass.

    Returns ``(new_state, newly_missing)`` where ``newly_missing`` is a
    ``bool[D]`` mask of devices flagged by THIS sweep (the send-once set).
    """
    has_events = state.last_event_type != NULL_ID
    overdue = (now_s - state.last_event_ts_s) > missing_after_s
    newly_missing = has_events & overdue & ~state.presence_missing
    return (
        state.replace(presence_missing=state.presence_missing | newly_missing),
        newly_missing,
    )


def state_changes_for(
    device_ids: np.ndarray, tenant_ids: np.ndarray, now_s: int
) -> EventBatch:
    """Build a presence STATE_CHANGE event batch for the given devices.

    Host-side (variable count → exact-width batch) — re-injected through
    the normal ingest path like the reference's presence StateChange events
    flow back through event management.  ``tenant_ids`` aligns with
    ``device_ids`` row for row.
    """
    width = int(device_ids.size)
    batch = EventBatch.empty(width)
    return batch.replace(
        valid=jnp.ones(width, bool),
        device_id=jnp.asarray(np.asarray(device_ids, np.int32)),
        tenant_id=jnp.asarray(np.asarray(tenant_ids, np.int32)),
        event_type=jnp.full(width, EventType.STATE_CHANGE, jnp.int32),
        ts_s=jnp.full(width, now_s, jnp.int32),
        alert_code=jnp.full(width, STATE_CHANGE_PRESENCE_MISSING, jnp.int32),
        # System-generated: must not mark the device present or bump its
        # last-event time (reference isUpdateState() semantics).
        update_state=jnp.zeros(width, bool),
    )


class PresenceManager(LifecycleComponent):
    """Background presence checker over a :class:`DeviceStateManager`.

    ``on_state_changes`` receives the STATE_CHANGE :class:`EventBatch` for
    each sweep that found newly-missing devices (the notification-strategy
    hook); wire it to the ingest path for re-injection.
    """

    def __init__(
        self,
        state_manager,  # DeviceStateManager
        check_interval_s: float = 600.0,  # reference default "10m"
        missing_after_s: int = 8 * 3600,  # reference default "8h"
        on_state_changes: Optional[Callable[[EventBatch], None]] = None,
        clock: Optional[Callable[[], float]] = None,
    ):
        super().__init__(name="presence-manager")
        self.state_manager = state_manager
        self.check_interval_s = check_interval_s
        self.missing_after_s = missing_after_s
        self.on_state_changes = on_state_changes
        self._clock = clock or time.time
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.sweeps = 0
        self.total_marked_missing = 0

    def sweep_once(self, now_s: Optional[int] = None) -> int:
        """Run one sweep; returns how many devices were newly marked.

        Reference: one iteration of the ``PresenceChecker`` loop.
        """
        now = int(self._clock()) if now_s is None else now_s
        marked = self.state_manager.apply_presence_sweep(now, self.missing_after_s)
        self.sweeps += 1
        if marked is not None:
            count = int(marked.valid.sum())
            self.total_marked_missing += count
            if self.on_state_changes is not None:
                self.on_state_changes(marked)
            return count
        return 0

    def _loop(self) -> None:
        while not self._stop.wait(self.check_interval_s):
            try:
                self.sweep_once()
            except Exception:
                logger.exception("presence sweep failed")

    def start(self) -> None:
        super().start()
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="presence-checker", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        super().stop()
