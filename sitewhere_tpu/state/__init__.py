"""Device state: last-known-state materialization + presence detection.

Reference: ``service-device-state`` — enriched events merge into per-device
``IDeviceState`` documents (``processing/DeviceStateProcessingLogic.java:
46-80``) and a background presence thread marks devices missing after an
interval, emitting StateChange events through a notification strategy
(``presence/DevicePresenceManager.java:49-88``,
``PresenceNotificationStrategies.java``).

TPU-first reshape: the merge already happens *inside* the fused pipeline
step (:func:`sitewhere_tpu.pipeline.update_device_state` — time-ordered
scatters); this package owns the resulting :class:`DeviceState` tensors on
the host side: the query surface over them, and the presence sweep — a
single jitted vectorized pass over all devices instead of a per-device
scan loop.
"""

from sitewhere_tpu.state.manager import DeviceStateManager
from sitewhere_tpu.state.presence import (
    PresenceManager,
    presence_sweep,
)

__all__ = ["DeviceStateManager", "PresenceManager", "presence_sweep"]
