"""Tiled Pallas point-in-polygon kernel for large zone sets.

The dense :func:`sitewhere_tpu.ops.geo.points_in_polygons` materializes a
``[B, Z, V]`` crossing tensor; fine for the pipeline's default zone table
(Z ≤ a few hundred) but at large B·Z·V that intermediate dominates HBM
traffic.  This kernel tiles the ``[B, Z]`` output grid, streams each
polygon tile's edges through VMEM once, and accumulates crossing parity
over vertices — the working set per grid cell is ``TB·TZ`` ints plus one
``TZ``-wide edge slice, independent of V.

Mosaic constraints found on real hardware (v5e, 2026-07-29): edges must be
vertex-major ``[V, Z]`` so the per-vertex slice is a dynamic *sublane*
index (a dynamic lane-axis column load fails to legalize), and crossing
parity must be carried as int32 (i1 vectors fail to legalize as loop
carries).  The vertex loop is UNROLLED (V is small and static) and each
edge's inverse slope is precomputed outside the kernel, removing the
per-iteration divide — together 2.2x over the fori_loop/divide form
(measured on v5e at B=131072, Z=512, V=16: 2.9 ms vs 6.4 ms).

Same padding contract as the dense path (repeat-last-vertex, wraparound
edge equals closing edge).  Reference behavior mirrored:
``service-rule-processing/.../geospatial/ZoneTestRuleProcessor.java:32-70``
(JTS ``contains`` per event × zone).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# [B, Z] output tile: sublane × lane aligned for float32/bool VPU ops.
TILE_B = 512
TILE_Z = 128


def _pip_kernel(px_ref, py_ref, y1_ref, y2_ref, x1_ref, slope_ref, out_ref):
    """One [TB, TZ] tile: parity of edge crossings over all V vertices.

    ``slope_ref[v] = (x2 - x1) / (y2 - y1)`` (guarded against horizontal
    edges, which never straddle) so the crossing abscissa is one fused
    multiply-add per vertex.
    """
    px = px_ref[:]  # [TB, 1]
    py = py_ref[:]
    n_verts = y1_ref.shape[0]

    parity = jnp.zeros(out_ref.shape, jnp.int32)
    for v in range(n_verts):  # static unroll: V is small (padded ring)
        y1 = y1_ref[pl.ds(v, 1), :]  # [1, TZ]
        y2 = y2_ref[pl.ds(v, 1), :]
        x1 = x1_ref[pl.ds(v, 1), :]
        slope = slope_ref[pl.ds(v, 1), :]
        straddles = (y1 > py) != (y2 > py)
        x_cross = slope * (py - y1) + x1
        crossing = straddles & (px < x_cross)
        # Carry parity as int32: Mosaic cannot legalize i1 vectors as
        # loop carries, and xor-int is as cheap as xor-bool on the VPU.
        parity = parity ^ crossing.astype(jnp.int32)
    out_ref[:] = parity.astype(jnp.bool_)


@functools.partial(jax.jit, static_argnames=("interpret",))
def points_in_polygons_pallas(
    points: jax.Array, verts: jax.Array, interpret: bool = False
) -> jax.Array:
    """Drop-in for :func:`points_in_polygons` via the tiled kernel.

    Args:
      points: ``float32[B, 2]`` (x, y).
      verts:  ``float32[Z, V, 2]`` padded rings.
      interpret: run in interpreter mode (CPU tests).

    Returns ``bool[B, Z]``.
    """
    b, _ = points.shape
    z, v, _ = verts.shape
    pad_b = (-b) % TILE_B
    pad_z = (-z) % TILE_Z

    # Lay out points as [B, 1] columns (sublane-major) and polygon edges
    # vertex-major as [V, Z] (zones ride the lane axis; the kernel's dynamic
    # per-vertex slice rides the sublane axis); pad Z with degenerate
    # polygons (zero area -> no crossings).
    px = jnp.pad(points[:, 0], (0, pad_b)).reshape(-1, 1)
    py = jnp.pad(points[:, 1], (0, pad_b)).reshape(-1, 1)
    x1 = jnp.pad(verts[:, :, 0], ((0, pad_z), (0, 0))).T  # [V, Zp]
    y1 = jnp.pad(verts[:, :, 1], ((0, pad_z), (0, 0))).T
    x2 = jnp.roll(x1, -1, axis=0)
    y2 = jnp.roll(y1, -1, axis=0)
    # Horizontal edges (y2 == y1) never straddle; the guard only keeps the
    # division finite.
    denom = jnp.where(y2 == y1, 1.0, y2 - y1)
    slope = (x2 - x1) / denom

    bp, zp = b + pad_b, z + pad_z
    grid = (bp // TILE_B, zp // TILE_Z)
    edge_spec = lambda: pl.BlockSpec(  # noqa: E731 — six identical specs
        (v, TILE_Z), lambda i, j: (0, j), memory_space=pltpu.VMEM)
    out = pl.pallas_call(
        _pip_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((TILE_B, 1), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((TILE_B, 1), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            edge_spec(), edge_spec(), edge_spec(), edge_spec(),
        ],
        out_specs=pl.BlockSpec((TILE_B, TILE_Z), lambda i, j: (i, j),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((bp, zp), jnp.bool_),
        interpret=interpret,
    )(px, py, y1, y2, x1, slope)
    return out[:b, :z]


# Dense-vs-Pallas crossover, measured on v5e with fetch-forced timing
# (2026-07-30): at B=131072, V=16 the dense path wins at Z=64 (0.47 ms vs
# 0.91 ms — XLA's fused [B,Z,V] pipeline beats the kernel while the
# intermediate still fits) and loses at Z=512 (3.34 ms vs 2.87 ms).  The
# earlier-claimed 38x kernel win was an async-dispatch artifact of
# block_until_ready returning early through the axon tunnel.
PALLAS_WORK_THRESHOLD = 1 << 29

PALLAS_ENABLED = bool(int(os.environ.get("SW_TPU_GEO_PALLAS", "1")))


def points_in_polygons_auto(points: jax.Array, verts: jax.Array) -> jax.Array:
    """Pick dense XLA vs tiled Pallas by static work size + backend."""
    from sitewhere_tpu.ops.geo import points_in_polygons

    b = points.shape[0]
    z, v, _ = verts.shape
    if (PALLAS_ENABLED and jax.default_backend() == "tpu"
            and b * z * v >= PALLAS_WORK_THRESHOLD):
        return points_in_polygons_pallas(points, verts)
    return points_in_polygons(points, verts)
