"""Tiled Pallas point-in-polygon kernel for large zone sets.

The dense :func:`sitewhere_tpu.ops.geo.points_in_polygons` materializes a
``[B, Z, V]`` crossing tensor; fine for the pipeline's default zone table
(Z ≤ a few hundred) but at large B·Z·V that intermediate dominates HBM
traffic.  This kernel tiles the ``[B, Z]`` output grid, streams each
polygon tile's edges through VMEM once, and accumulates crossing parity
with a ``fori_loop`` over vertices — the working set per grid cell is
``TB·TZ`` booleans plus one ``TZ``-wide edge slice, independent of V.

Same padding contract as the dense path (repeat-last-vertex, wraparound
edge equals closing edge).  Reference behavior mirrored:
``service-rule-processing/.../geospatial/ZoneTestRuleProcessor.java:32-70``
(JTS ``contains`` per event × zone).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# [B, Z] output tile: sublane × lane aligned for float32/bool VPU ops.
TILE_B = 256
TILE_Z = 128


def _pip_kernel(px_ref, py_ref, x1_ref, y1_ref, x2_ref, y2_ref, out_ref):
    """One [TB, TZ] tile: parity of edge crossings over all V vertices.

    Edge arrays are vertex-major ``[V, TZ]`` so the per-iteration slice is
    a dynamic *sublane* index (supported by Mosaic); a dynamic lane-axis
    column load is not.
    """
    px = px_ref[:]  # [TB, 1]
    py = py_ref[:]
    n_verts = x1_ref.shape[0]

    def body(v, parity):
        x1 = x1_ref[pl.ds(v, 1), :]  # [1, TZ]
        y1 = y1_ref[pl.ds(v, 1), :]
        x2 = x2_ref[pl.ds(v, 1), :]
        y2 = y2_ref[pl.ds(v, 1), :]
        straddles = (y1 > py) != (y2 > py)
        denom = jnp.where(y2 == y1, 1.0, y2 - y1)
        x_cross = (x2 - x1) * (py - y1) / denom + x1
        crossing = straddles & (px < x_cross)
        # Carry parity as int32: Mosaic cannot legalize i1 vectors as
        # scf.for loop carries.
        return parity ^ crossing.astype(jnp.int32)

    parity = jax.lax.fori_loop(
        0, n_verts, body,
        jnp.zeros(out_ref.shape, jnp.int32),
    )
    out_ref[:] = parity.astype(jnp.bool_)


@functools.partial(jax.jit, static_argnames=("interpret",))
def points_in_polygons_pallas(
    points: jax.Array, verts: jax.Array, interpret: bool = False
) -> jax.Array:
    """Drop-in for :func:`points_in_polygons` via the tiled kernel.

    Args:
      points: ``float32[B, 2]`` (x, y).
      verts:  ``float32[Z, V, 2]`` padded rings.
      interpret: run in interpreter mode (CPU tests).

    Returns ``bool[B, Z]``.
    """
    b, _ = points.shape
    z, v, _ = verts.shape
    pad_b = (-b) % TILE_B
    pad_z = (-z) % TILE_Z

    # Lay out points as [B, 1] columns (sublane-major) and polygon edges
    # vertex-major as [V, Z] (zones ride the lane axis; the kernel's dynamic
    # per-vertex slice rides the sublane axis); pad Z with degenerate
    # polygons (zero area -> no crossings).
    px = jnp.pad(points[:, 0], (0, pad_b)).reshape(-1, 1)
    py = jnp.pad(points[:, 1], (0, pad_b)).reshape(-1, 1)
    x1 = jnp.pad(verts[:, :, 0], ((0, pad_z), (0, 0))).T  # [V, Zp]
    y1 = jnp.pad(verts[:, :, 1], ((0, pad_z), (0, 0))).T
    x2 = jnp.roll(x1, -1, axis=0)
    y2 = jnp.roll(y1, -1, axis=0)

    bp, zp = b + pad_b, z + pad_z
    grid = (bp // TILE_B, zp // TILE_Z)
    out = pl.pallas_call(
        _pip_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((TILE_B, 1), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((TILE_B, 1), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((v, TILE_Z), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((v, TILE_Z), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((v, TILE_Z), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((v, TILE_Z), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((TILE_B, TILE_Z), lambda i, j: (i, j),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((bp, zp), jnp.bool_),
        interpret=interpret,
    )(px, py, x1, y1, x2, y2)
    return out[:b, :z]


# Dense-path work above which the tiled kernel pays off on TPU (the [B,Z,V]
# intermediate stops fitting comfortably in VMEM/fusion).
PALLAS_WORK_THRESHOLD = 1 << 22

# Validated on real hardware (v5e, 2026-07-29): Mosaic compiles the
# vertex-major/int32-carry form and it beats the dense path 38x at
# B=4096, Z=256, V=16 (1.7ms vs 65ms) with exact output match.  On by
# default; SW_TPU_GEO_PALLAS=0 force-disables.
PALLAS_ENABLED = bool(int(os.environ.get("SW_TPU_GEO_PALLAS", "1")))


def points_in_polygons_auto(points: jax.Array, verts: jax.Array) -> jax.Array:
    """Pick dense XLA vs tiled Pallas by static work size + backend."""
    from sitewhere_tpu.ops.geo import points_in_polygons

    b = points.shape[0]
    z, v, _ = verts.shape
    if (PALLAS_ENABLED and jax.default_backend() == "tpu"
            and b * z * v >= PALLAS_WORK_THRESHOLD):
        return points_in_polygons_pallas(points, verts)
    return points_in_polygons(points, verts)
