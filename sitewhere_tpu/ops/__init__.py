"""Reusable device-side ops: geospatial kernels and masked time-ordered scatters."""

from sitewhere_tpu.ops.geo import pad_polygon, points_in_polygons  # noqa: F401
from sitewhere_tpu.ops.geo_pallas import (  # noqa: F401
    points_in_polygons_auto,
    points_in_polygons_pallas,
)
from sitewhere_tpu.ops.scatter import (  # noqa: F401
    bincount_fixed,
    scatter_last_by_time,
    scatter_max_by_key,
)
