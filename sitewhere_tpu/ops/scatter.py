"""Masked, time-ordered scatters: the TPU replacement for per-event writes.

The reference's state materialization processes one Kafka record at a time
(``service-device-state/.../processing/DeviceStateProcessingLogic.java:46-80``),
so "last write wins" falls out of per-partition ordering.  In a batched SPMD
step many events for one device land in the same batch, so we scatter with
an explicit time key: first a scatter-max of the ``(ts_s, ts_ns)`` key, then
payload writes masked to the rows that won.  Ties (identical key) are broken
by batch row index (highest row wins) so exactly ONE event row writes all
payload columns — independent per-column scatters with duplicate indices
would otherwise be free to mix columns from different tied events.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp


def scatter_last_by_time(
    cur_ts_s: jax.Array,
    cur_ts_ns: jax.Array,
    cur_payload: Sequence[jax.Array],
    ids: jax.Array,
    ts_s: jax.Array,
    ts_ns: jax.Array,
    payload: Sequence[jax.Array],
    mask: jax.Array,
) -> Tuple[jax.Array, jax.Array, Tuple[jax.Array, ...]]:
    """Scatter ``payload`` rows into per-id slots, newest ``(ts_s, ts_ns)`` wins.

    Args:
      cur_ts_s/cur_ts_ns: ``int32[D]`` current per-slot time key.
      cur_payload: arrays of shape ``[D, ...]`` to update alongside the key.
      ids: ``int32[B]`` target slot per event (rows with ``mask=False`` or
        out-of-range ids are dropped).
      ts_s/ts_ns: ``int32[B]`` event time key.
      payload: arrays of shape ``[B, ...]`` matching ``cur_payload``.
      mask: ``bool[B]``.

    Returns:
      ``(new_ts_s, new_ts_ns, new_payload)``.
    """
    if len(cur_payload) != len(payload):
        raise ValueError(
            f"payload arity mismatch: {len(cur_payload)} state arrays vs "
            f"{len(payload)} event arrays (pass tuples, not bare arrays)"
        )
    capacity = cur_ts_s.shape[0]
    # mode="drop" drops ids >= capacity but NEGATIVE ids would wrap
    # (python-style indexing) — sanitize both to the drop sentinel.
    mask = mask & (ids >= 0)
    safe_ids = jnp.where(mask, ids, capacity)

    # Pass 1: winning second per slot.
    new_s = cur_ts_s.at[safe_ids].max(ts_s, mode="drop")
    # Pass 2: winning ns among events that have the winning second.  If the
    # second advanced past the current slot value, the old ns must not be
    # compared — reset it to -1 (below any real ns).
    base_ns = jnp.where(cur_ts_s == new_s, cur_ts_ns, -1)
    sec_won = mask & (ts_s == new_s[jnp.clip(ids, 0, capacity - 1)])
    ns_ids = jnp.where(sec_won, ids, capacity)
    new_ns = base_ns.at[ns_ids].max(ts_ns, mode="drop")

    # Winner rows: their (s, ns) equals the final slot key.
    clip_ids = jnp.clip(ids, 0, capacity - 1)
    won = sec_won & (ts_ns == new_ns[clip_ids])
    win_ids, won = _unique_winner(won, ids, capacity)
    new_payload = tuple(
        cur.at[win_ids].set(val, mode="drop") for cur, val in zip(cur_payload, payload)
    )
    return new_s, new_ns, new_payload


def _unique_winner(won: jax.Array, ids: jax.Array, capacity: int):
    """Reduce a (possibly tied) winner mask to exactly one row per slot.

    Highest batch row index wins among tied rows, so all payload columns are
    written by the same event.
    """
    row = jnp.arange(won.shape[0], dtype=jnp.int32)
    cand_ids = jnp.where(won, ids, capacity)
    best_row = jnp.full((capacity,), -1, jnp.int32).at[cand_ids].max(row, mode="drop")
    final = won & (row == best_row[jnp.clip(ids, 0, capacity - 1)])
    return jnp.where(final, ids, capacity), final


def scatter_max_by_key(
    cur_key: jax.Array,
    cur_payload: Sequence[jax.Array],
    ids: jax.Array,
    key: jax.Array,
    payload: Sequence[jax.Array],
    mask: jax.Array,
) -> Tuple[jax.Array, Tuple[jax.Array, ...]]:
    """Single-key (seconds-only) variant of :func:`scatter_last_by_time`."""
    if len(cur_payload) != len(payload):
        raise ValueError(
            f"payload arity mismatch: {len(cur_payload)} state arrays vs "
            f"{len(payload)} event arrays (pass tuples, not bare arrays)"
        )
    capacity = cur_key.shape[0]
    mask = mask & (ids >= 0)  # negative ids would wrap; see scatter_last_by_time
    safe_ids = jnp.where(mask, ids, capacity)
    new_key = cur_key.at[safe_ids].max(key, mode="drop")
    won = mask & (key == new_key[jnp.clip(ids, 0, capacity - 1)])
    win_ids, _ = _unique_winner(won, ids, capacity)
    new_payload = tuple(
        cur.at[win_ids].set(val, mode="drop") for cur, val in zip(cur_payload, payload)
    )
    return new_key, new_payload


def bincount_fixed(ids: jax.Array, mask: jax.Array, length: int) -> jax.Array:
    """Masked bincount with static length (metrics rollups)."""
    safe = jnp.where(mask & (ids >= 0), ids, length)
    return jnp.zeros((length,), jnp.int32).at[safe].add(1, mode="drop")
