"""Masked, time-ordered scatters: the TPU replacement for per-event writes.

The reference's state materialization processes one Kafka record at a time
(``service-device-state/.../processing/DeviceStateProcessingLogic.java:46-80``),
so "last write wins" falls out of per-partition ordering.  In a batched SPMD
step many events for one device land in the same batch, so each slot needs
the row with the newest ``(ts_s, ts_ns)`` key, tie-broken by batch row index
(highest row wins) so exactly ONE event row writes all payload columns.

Implementation is SORT-based, not scatter-based: XLA lowers scatters with
duplicate indices to a serialized update loop on TPU, which measured 13x
slower than this design at pipeline widths (131072 rows -> 16384 slots,
13.7 ms vs 1.1 ms on v5e).  The stable multi-key sort groups rows by slot
with newest-last, segment boundaries mark each slot's winning row, the
winner map is written with UNIQUE indices (the fast scatter path), and
payload columns are applied with gathers — every op on the parallel path.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def _winner_rows_sort(
    ids: jax.Array,
    keys: Sequence[jax.Array],
    mask: jax.Array,
    capacity: int,
) -> jax.Array:
    """Sort-based winner map (the TPU fast path).

    The stable ascending sort on ``(id, *keys)`` leaves each slot's winning
    row LAST in its run (stability preserves batch order among equal keys,
    giving the highest-row tie-break); run boundaries then identify
    winners, which scatter into the slot map with unique indices.
    """
    b = ids.shape[0]
    mask = mask & (ids >= 0) & (ids < capacity)
    eff = jnp.where(mask, ids, capacity).astype(jnp.int32)
    rows = jnp.arange(b, dtype=jnp.int32)
    sorted_ops = lax.sort(
        (eff, *keys, rows), num_keys=1 + len(keys), is_stable=True
    )
    eff_s, rows_s = sorted_ops[0], sorted_ops[-1]
    nxt = jnp.concatenate([eff_s[1:], jnp.full((1,), capacity + 1, jnp.int32)])
    boundary = (eff_s != nxt) & (eff_s < capacity)
    win_ids = jnp.where(boundary, eff_s, capacity)
    return jnp.full((capacity,), -1, jnp.int32).at[win_ids].set(
        rows_s, mode="drop", unique_indices=True
    )


def _winner_rows_scatter(
    ids: jax.Array,
    keys: Sequence[jax.Array],
    mask: jax.Array,
    capacity: int,
) -> jax.Array:
    """Scatter-based winner map (the CPU fast path).

    Lexicographic multi-pass scatter-max: pass k keeps the rows whose key
    equals the per-slot max among rows that survived passes 0..k-1; a
    final scatter-max of the row index breaks remaining ties (highest row
    wins).  XLA CPU runs duplicate-index scatters well but variadic sorts
    poorly — the mirror image of TPU (7.1 ms vs 0.5 ms at width 16k for
    the sort form on CPU; 13.7 ms vs 1.1 ms for the scatter form on v5e).
    """
    won = mask & (ids >= 0) & (ids < capacity)
    clip_ids = jnp.clip(ids, 0, capacity - 1)
    key_min = jnp.iinfo(jnp.int32).min
    for k in keys:
        eff = jnp.where(won, ids, capacity)
        mx = jnp.full((capacity,), key_min, jnp.int32).at[eff].max(
            k, mode="drop")
        won = won & (k == mx[clip_ids])
    rows = jnp.arange(ids.shape[0], dtype=jnp.int32)
    eff = jnp.where(won, ids, capacity)
    return jnp.full((capacity,), -1, jnp.int32).at[eff].max(rows, mode="drop")


def winner_rows_by_keys(
    ids: jax.Array,
    keys: Sequence[jax.Array],
    mask: jax.Array,
    capacity: int,
) -> jax.Array:
    """Per-slot winning batch row (max lexicographic key, highest row on ties).

    Returns ``int32[capacity]`` — the batch row index whose ``keys`` tuple
    is largest among masked rows targeting each slot, or ``-1`` for slots
    no masked row targets.  Rows with out-of-range ids are dropped.

    Backend-adaptive (chosen at trace time): sort-based on TPU, where
    sorts are native and duplicate-index scatters serialize; scatter-based
    everywhere else, where the opposite holds.
    """
    if jax.default_backend() == "tpu":
        return _winner_rows_sort(ids, keys, mask, capacity)
    return _winner_rows_scatter(ids, keys, mask, capacity)


def winner_rows(
    ids: jax.Array,
    ts_s: jax.Array,
    ts_ns: jax.Array,
    mask: jax.Array,
    capacity: int,
) -> jax.Array:
    """Per-slot winning batch row (newest ``(ts_s, ts_ns)``, highest row on
    ties) — the two-part-time-key form of :func:`winner_rows_by_keys`."""
    return winner_rows_by_keys(ids, (ts_s, ts_ns), mask, capacity)


def apply_winners(
    slot_row: jax.Array,
    cur_ts_s: jax.Array,
    cur_ts_ns: jax.Array,
    cur_payload: Sequence[jax.Array],
    ts_s: jax.Array,
    ts_ns: jax.Array,
    payload: Sequence[jax.Array],
) -> Tuple[jax.Array, jax.Array, Tuple[jax.Array, ...]]:
    """Apply a :func:`winner_rows` map: update slots whose winning event is
    at least as new as the slot's current key (events win exact ties, the
    same contract per-partition ordering gives the reference).

    The time keys and payload columns are gathered in dtype-grouped packs
    (one multi-column gather per dtype) — separate [B]-sized gathers cost
    ~1 ms each at pipeline widths on v5e, packed ones barely more than one.
    """
    capacity = cur_ts_s.shape[0]
    has = slot_row >= 0
    wr = jnp.clip(slot_row, 0)

    b = ts_s.shape[0]
    items = [("__ts", ts_s.reshape(b, 1)), ("__ns", ts_ns.reshape(b, 1))]
    items += [(i, val.reshape(b, -1)) for i, val in enumerate(payload)]
    groups: dict = {}
    for key, arr in items:
        groups.setdefault(jnp.dtype(arr.dtype), []).append((key, arr))
    gathered = {}
    for _, lst in groups.items():
        packed = jnp.concatenate([a for _, a in lst], axis=1)[wr]  # [D, k]
        off = 0
        for key, a in lst:
            gathered[key] = packed[:, off:off + a.shape[1]]
            off += a.shape[1]

    w_s = gathered["__ts"][:, 0]
    w_ns = gathered["__ns"][:, 0]
    newer = has & ((w_s > cur_ts_s) | ((w_s == cur_ts_s) & (w_ns >= cur_ts_ns)))
    new_s = jnp.where(newer, w_s, cur_ts_s)
    new_ns = jnp.where(newer, w_ns, cur_ts_ns)
    out = []
    for i, (cur, val) in enumerate(zip(cur_payload, payload)):
        nd = jnp.reshape(newer, (capacity,) + (1,) * (val.ndim - 1))
        win = gathered[i].reshape((capacity,) + val.shape[1:]).astype(val.dtype)
        out.append(jnp.where(nd, win, cur))
    return new_s, new_ns, tuple(out)


def scatter_last_by_time(
    cur_ts_s: jax.Array,
    cur_ts_ns: jax.Array,
    cur_payload: Sequence[jax.Array],
    ids: jax.Array,
    ts_s: jax.Array,
    ts_ns: jax.Array,
    payload: Sequence[jax.Array],
    mask: jax.Array,
) -> Tuple[jax.Array, jax.Array, Tuple[jax.Array, ...]]:
    """Scatter ``payload`` rows into per-id slots, newest ``(ts_s, ts_ns)`` wins.

    Args:
      cur_ts_s/cur_ts_ns: ``int32[D]`` current per-slot time key.
      cur_payload: arrays of shape ``[D, ...]`` to update alongside the key.
      ids: ``int32[B]`` target slot per event (rows with ``mask=False`` or
        out-of-range ids are dropped).
      ts_s/ts_ns: ``int32[B]`` event time key.
      payload: arrays of shape ``[B, ...]`` matching ``cur_payload``.
      mask: ``bool[B]``.

    Returns:
      ``(new_ts_s, new_ts_ns, new_payload)``.
    """
    if len(cur_payload) != len(payload):
        raise ValueError(
            f"payload arity mismatch: {len(cur_payload)} state arrays vs "
            f"{len(payload)} event arrays (pass tuples, not bare arrays)"
        )
    slot_row = winner_rows(ids, ts_s, ts_ns, mask, cur_ts_s.shape[0])
    return apply_winners(
        slot_row, cur_ts_s, cur_ts_ns, cur_payload, ts_s, ts_ns, payload
    )


def scatter_max_by_key(
    cur_key: jax.Array,
    cur_payload: Sequence[jax.Array],
    ids: jax.Array,
    key: jax.Array,
    payload: Sequence[jax.Array],
    mask: jax.Array,
) -> Tuple[jax.Array, Tuple[jax.Array, ...]]:
    """Single-key (seconds-only) variant of :func:`scatter_last_by_time`."""
    if len(cur_payload) != len(payload):
        raise ValueError(
            f"payload arity mismatch: {len(cur_payload)} state arrays vs "
            f"{len(payload)} event arrays (pass tuples, not bare arrays)"
        )
    capacity = cur_key.shape[0]
    slot_row = winner_rows_by_keys(ids, (key,), mask, capacity)
    has = slot_row >= 0
    wr = jnp.clip(slot_row, 0)
    w_key = key[wr]
    newer = has & (w_key >= cur_key)
    new_key = jnp.where(newer, w_key, cur_key)
    out = []
    for cur, val in zip(cur_payload, payload):
        nd = jnp.reshape(newer, (capacity,) + (1,) * (val.ndim - 1))
        out.append(jnp.where(nd, val[wr], cur))
    return new_key, tuple(out)


def bincount_fixed(ids: jax.Array, mask: jax.Array, length: int) -> jax.Array:
    """Masked bincount with static length (metrics rollups).

    One-hot compare + column sum: for small ``length`` this is a [B, length]
    reduction XLA fuses, avoiding the duplicate-index scatter-add path.
    """
    hit = (ids[:, None] == jnp.arange(length, dtype=ids.dtype)[None, :]) & (
        mask[:, None]
    )
    return hit.sum(axis=0, dtype=jnp.int32)
