"""Geospatial kernels: batched point-in-polygon for geofence rules.

The reference tests each location event against JTS polygons one at a time
on the JVM (``service-rule-processing/.../geospatial/ZoneTestRuleProcessor.java:32-70``,
polygons built by ``sitewhere-core/.../geospatial/GeoUtils.java``).  Here the
test is a dense ``[B, Z, V]`` ray-crossing computation over padded vertex
tensors — one fused XLA op on the VPU.  (A tiled Pallas variant for very
large ``Z*V`` is planned; this module is its drop-in home.)

Padding contract (matches :class:`sitewhere_tpu.schema.ZoneTable`): polygons
are padded to ``V`` vertices by repeating the last real vertex, so padded
edges are zero-length (contribute no crossings) and the wraparound edge
``v[V-1] → v[0]`` coincides with the true closing edge.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def pad_polygon(verts, max_verts: int) -> np.ndarray:
    """Host-side: pad a polygon ring to ``max_verts`` per the module contract
    (repeat the last real vertex).  The single source of the padding rule —
    use this when building :class:`~sitewhere_tpu.schema.ZoneTable` rows.
    """
    verts = np.asarray(verts, np.float32)
    if verts.ndim != 2 or verts.shape[1] != 2 or len(verts) < 3:
        raise ValueError(f"polygon needs shape [>=3, 2], got {verts.shape}")
    if len(verts) > max_verts:
        raise ValueError(f"polygon has {len(verts)} verts > max {max_verts}")
    pad = np.repeat(verts[-1:], max_verts - len(verts), axis=0)
    return np.concatenate([verts, pad])


def points_in_polygons(points: jax.Array, verts: jax.Array) -> jax.Array:
    """Ray-crossing containment test for every (point, polygon) pair.

    Args:
      points: ``float32[B, 2]`` — (x, y) == (lon, lat).
      verts:  ``float32[Z, V, 2]`` — padded polygon rings (see module doc).

    Returns:
      ``bool[B, Z]`` — point strictly inside polygon (boundary points may
      land either way, same as the reference's JTS ``contains`` edge cases).
    """
    px = points[:, 0][:, None, None]  # [B, 1, 1]
    py = points[:, 1][:, None, None]
    x1 = verts[None, :, :, 0]  # [1, Z, V]
    y1 = verts[None, :, :, 1]
    x2 = jnp.roll(verts[:, :, 0], -1, axis=-1)[None]  # wraparound edge
    y2 = jnp.roll(verts[:, :, 1], -1, axis=-1)[None]

    straddles = (y1 > py) != (y2 > py)
    # Safe division: where the edge is horizontal/degenerate, straddles is
    # False and the quotient is irrelevant — guard the denominator only.
    # The slope-first ordering matches the Pallas kernel's precomputed-
    # slope form EXACTLY (same rounding), keeping the two paths bitwise
    # equal so the work-size auto-switch never flips a containment result.
    denom = jnp.where(y2 == y1, 1.0, y2 - y1)
    slope = (x2 - x1) / denom
    x_cross = slope * (py - y1) + x1
    crossing = straddles & (px < x_cross)
    # Odd number of crossings => inside.
    return (jnp.sum(crossing.astype(jnp.int32), axis=-1) % 2) == 1
