"""Hot tier: recent segments retained in packed-column form.

Sealed segments are durable as npz files; the hot tier additionally
keeps the most recently sealed/scanned segments resident as the SAME
packed ``([Ci, n] int32, [Cf, n] float32)`` block pair the TPU
pipeline stages to the device — a hot segment is one ``device_put``
pair away from H2D, and the retrospective scan lane serves its column
views with zero file IO and zero pivot.

Tier transitions:

- **adopt** — a seal worker hands the freshly written segment's packed
  block straight from the shard buffer (one copy, off the hot path);
- **demote** — byte-budget LRU eviction drops the packed copy; the
  segment silently degrades to file-backed (the column LRU in
  :class:`~sitewhere_tpu.store.segment.ColumnCache` is the next tier
  down, the npz file the last);
- **promote** — a scan that touches a demoted segment re-packs it into
  the tier (budget permitting), so a repeatedly queried window heats
  back up.

Demotion→promotion round-trips are bit-identical by construction: the
packed block IS the column data, row-sliced.
"""

from __future__ import annotations

import threading
from collections import OrderedDict, deque
from typing import Dict, Optional, Tuple

import numpy as np

from sitewhere_tpu.store.segment import COLUMN_NAMES, Segment, pack_cols

_BlockPair = Tuple[np.ndarray, np.ndarray]

# bytes per packed row, derived from the schema (every column is a
# 4-byte int32/float32) — never a hand-maintained constant
_ROW_BYTES = 4 * len(COLUMN_NAMES)


class HotTier:
    """Byte-bounded LRU of packed segment blocks."""

    def __init__(self, max_bytes: int, metrics=None):
        self.max_bytes = int(max_bytes)
        self._od: "OrderedDict[int, _BlockPair]" = OrderedDict()
        # dropped seqs (retention/compaction removed the segment):
        # refuses a promote() racing drop() — a scan that materialized
        # the segment just before it was delisted would otherwise park
        # a dead block at the MRU end, evicting live segments.  Seqs
        # never recycle, so only RECENT tombstones matter (FIFO bound,
        # mirroring ColumnCache._dead).
        self._dead: set = set()
        self._dead_order: "deque" = deque()
        self._lock = threading.Lock()
        self.bytes = 0
        self.adoptions = 0
        self.promotions = 0
        self.demotions = 0
        self.hits = 0
        self._m_promote = self._m_demote = None
        if metrics is not None:
            self._m_promote = metrics.counter("store.tier_promotions")
            self._m_demote = metrics.counter("store.tier_demotions")

    def __len__(self) -> int:
        with self._lock:
            return len(self._od)

    def adopt(self, seq: int, ints: np.ndarray, flts: np.ndarray,
              n: int) -> None:
        """Copy one packed block into the tier (seal-worker hand-off —
        the source views belong to a shard buffer about to recycle)."""
        if self.max_bytes <= 0:
            return
        self._put(seq, ints[:, :n].copy(), flts[:, :n].copy())
        self.adoptions += 1

    def promote(self, seg: Segment, cols: Dict[str, np.ndarray]) -> bool:
        """Re-pack a demoted segment from materialized columns (scan
        re-heat).  Refused when the block alone would blow the budget."""
        if self.max_bytes <= 0:
            return False
        nbytes = seg.n * _ROW_BYTES
        if nbytes > self.max_bytes:
            return False
        ints, flts = pack_cols(cols)
        self._put(seg.seq, ints, flts)
        self.promotions += 1
        if self._m_promote is not None:
            self._m_promote.inc()
        return True

    def _put(self, seq: int, ints: np.ndarray, flts: np.ndarray) -> None:
        with self._lock:
            if seq in self._dead:
                return
            old = self._od.pop(seq, None)
            if old is not None:
                self.bytes -= old[0].nbytes + old[1].nbytes
            self._od[seq] = (ints, flts)
            self.bytes += ints.nbytes + flts.nbytes
            while self.bytes > self.max_bytes and len(self._od) > 1:
                _, (oi, of) = self._od.popitem(last=False)
                self.bytes -= oi.nbytes + of.nbytes
                self.demotions += 1
                if self._m_demote is not None:
                    self._m_demote.inc()

    def get(self, seq: int) -> Optional[_BlockPair]:
        """The packed block for a hot segment (LRU touch), else None —
        the caller falls through to the column cache / file."""
        with self._lock:
            pair = self._od.get(seq)
            if pair is not None:
                self._od.move_to_end(seq)
                self.hits += 1
            return pair

    def drop(self, seq: int) -> None:
        """Retention/compaction removed the segment — a demotion with
        no file left behind (and a tombstone so a racing promote
        can't resurrect the block)."""
        with self._lock:
            if seq not in self._dead:
                self._dead.add(seq)
                self._dead_order.append(seq)
                while len(self._dead_order) > 1024:
                    self._dead.discard(self._dead_order.popleft())
            pair = self._od.pop(seq, None)
            if pair is not None:
                self.bytes -= pair[0].nbytes + pair[1].nbytes

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "segments": len(self._od),
                "bytes": self.bytes,
                "max_bytes": self.max_bytes,
                "adoptions": self.adoptions,
                "promotions": self.promotions,
                "demotions": self.demotions,
                "hits": self.hits,
            }


__all__ = ["HotTier"]
